"""CLI bootstrap — the reference's per-role ``main`` classes + run scripts
(SURVEY.md §2 L4, §3 "Bootstrap mains + scripts") as one argparse entrypoint:

    python -m akka_allreduce_tpu local-demo   --nodes 4 --size 1000000
    python -m akka_allreduce_tpu cluster-master --port 7070 --nodes 2 --rounds 20
    python -m akka_allreduce_tpu cluster-node --seed 127.0.0.1:7070
    python -m akka_allreduce_tpu bench        --floats 67108864 --schedule psum
    python -m akka_allreduce_tpu train-mlp    --steps 100 --batch 64
    python -m akka_allreduce_tpu train-resnet --steps 5 --bucket 262144
    python -m akka_allreduce_tpu train-lm     --steps 30 --seq-len 256 --impl ring
    python -m akka_allreduce_tpu elastic-demo --steps 30 --drop-at 10 --rejoin-at 20

``local-demo`` is the reference's single-process N-worker fixture (BASELINE
config 1) on the host engine; the rest run the XLA data plane on whatever
devices are visible (TPU chips, or a virtual CPU mesh via
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time


def _add_obs_flags(p: argparse.ArgumentParser) -> None:
    """Observability flags shared by the cluster roles (obs/ — OBSERVABILITY.md)."""
    p.add_argument(
        "--trace-out",
        default=None,
        metavar="FILE",
        help="write this process's spans as Chrome/Perfetto trace_event "
        "JSON on exit (merge multiple processes' files with "
        "`obs merge-trace`)",
    )
    p.add_argument(
        "--flight-dir",
        default=None,
        metavar="DIR",
        help="arm the flight recorder: dump a post-mortem JSONL here on "
        "unhandled crash or SIGUSR1 (SIGUSR1 dumps, then kills the "
        "process — kill-with-post-mortem); AKKA_OBS_DIR is the env "
        "equivalent",
    )


def _install_obs(args) -> None:
    if getattr(args, "flight_dir", None):
        from akka_allreduce_tpu.obs import flight

        flight.install(args.flight_dir, signal_exit=True)


def _write_trace(args) -> None:
    if getattr(args, "trace_out", None):
        from akka_allreduce_tpu.obs import trace as obs_trace

        path = obs_trace.write_chrome_trace(args.trace_out)
        print(f"trace written to {path}", flush=True)


def _add_chaos_flags(p: argparse.ArgumentParser) -> None:
    """Chaos + retry-policy flags for the cluster master roles. The chaos
    spec is distributed to every node via Welcome (like every other knob),
    so ONE master flag arms the whole cluster with the same seed; the
    retry policy travels the same way (RESILIENCE.md)."""
    p.add_argument(
        "--chaos-seed", type=int, default=0,
        help="seed of the deterministic chaos schedule (same seed -> same "
        "per-process event log)",
    )
    p.add_argument(
        "--chaos-spec", default="",
        metavar="SPEC",
        help="fault spec, e.g. 'drop:p=0.05;delay:ms=20;corrupt:p=0.01;"
        "partition:groups=m+0|1,at=round10,heal=5s' (empty = chaos off)",
    )
    p.add_argument(
        "--chaos-log", default=None, metavar="FILE",
        help="write this process's chaos event log (JSONL, deterministic "
        "per seed) here on exit",
    )
    p.add_argument(
        "--send-retries", type=int, default=1,
        help="transport reconnect-resend budget per failure burst "
        "(exponential backoff + full jitter; 0 = fail fast)",
    )
    p.add_argument(
        "--send-backoff-base", type=float, default=0.05,
        help="base backoff seconds (doubles per retry, capped)",
    )
    p.add_argument(
        "--send-backoff-max", type=float, default=2.0,
        help="backoff cap in seconds",
    )


def _add_gossip_flags(p: argparse.ArgumentParser) -> None:
    """SWIM gossip membership (control/gossip.py, RESILIENCE.md 'Tier 6').
    Master-role flags: the section rides Welcome, so one flag switches the
    whole cluster from hub heartbeats to decentralized probing."""
    p.add_argument(
        "--gossip", action="store_true",
        help="decentralized membership: nodes probe each other (SWIM "
        "ping / ping-req / suspicion) instead of all heartbeating into "
        "the master's phi detector; the master consumes the gossip view",
    )
    p.add_argument(
        "--gossip-interval", type=float, default=0.5, metavar="S",
        help="gossip probe period in seconds (ack timeout is 0.3x this; "
        "suspicion confirms after 4 unrefuted periods)",
    )


def _gossip_config_from(args):
    import math

    from akka_allreduce_tpu.config import GossipConfig

    if not getattr(args, "gossip", False):
        return GossipConfig()
    interval = getattr(args, "gossip_interval", 0.5)
    return GossipConfig(
        enabled=True,
        probe_interval_s=interval,
        probe_timeout_s=interval * 0.3,
        # keep the suspicion window >= ~2s regardless of the probe
        # cadence: a short interval should mean fast PROBING, not a
        # hair-trigger conviction — a loaded host can stall a healthy
        # process past 1s (GIL, checkpoint fsync), and refutation needs
        # time to travel
        suspicion_periods=max(4, math.ceil(2.0 / interval)),
        seed=getattr(args, "chaos_seed", 0),
    )


def _add_adapt_flags(p: argparse.ArgumentParser) -> None:
    """Closed-loop adaptive degradation (control/adapt.py, RESILIENCE.md
    'Tier 5'): the leader's per-round controller. Master-role flags only —
    workers need no config, the policy rides every Prepare/Start."""
    p.add_argument(
        "--adapt", action="store_true",
        help="enable the per-round adaptive controller: degrade th_reduce "
        "and wire precision (f16 -> int8) when straggler evidence grows, "
        "restore when the tail recovers",
    )
    p.add_argument(
        "--adapt-floor", type=float, default=0.5,
        help="th_reduce never degrades below this fraction",
    )
    p.add_argument(
        "--adapt-window", type=int, default=8,
        help="round completions per controller decision",
    )
    p.add_argument(
        "--adapt-dwell", type=int, default=16,
        help="minimum rounds at a level before the next transition "
        "(the anti-flap hysteresis dwell)",
    )
    p.add_argument(
        "--adapt-lag", type=int, default=12,
        help="worker contribution lag (rounds) that triggers a degrade; "
        "restore requires lag back under a third of this (min 1)",
    )
    p.add_argument(
        "--adapt-log", default=None, metavar="FILE",
        help="write the controller's decision log (JSONL, logical fields "
        "only — same evidence replays the same bytes) here on exit",
    )


def _adapt_config_from(args):
    from akka_allreduce_tpu.config import AdaptConfig

    if not getattr(args, "adapt", False):
        return AdaptConfig()
    lag = max(2, args.adapt_lag)
    return AdaptConfig(
        enabled=True,
        floor_th_reduce=args.adapt_floor,
        window=args.adapt_window,
        min_dwell=args.adapt_dwell,
        lag_degrade=lag,
        lag_restore=max(1, lag // 3),
    )


def _add_wire_dtype_flag(p: argparse.ArgumentParser) -> None:
    """TCP wire compression for the host data plane (cluster masters only —
    the knob is distributed to every node via Welcome)."""
    p.add_argument(
        "--wire-dtype",
        choices=("f32", "f16"),
        default="f32",
        help="float width of Scatter/ReduceBlock payloads on the TCP wire; "
        "f16 halves the network bytes (accumulation stays f32)",
    )


def _add_data_plane_flags(p: argparse.ArgumentParser) -> None:
    """Host data-plane sharding knobs (cluster masters only — distributed
    to every node via Welcome, like --wire-dtype)."""
    p.add_argument(
        "--streams", type=int, default=1,
        help="parallel TCP sockets per peer endpoint: stream 0 carries "
        "control (ordering preserved, byte-identical legacy wire), "
        "payload frames stripe across streams 1..N-1 by chunk id, each "
        "drained by a dedicated sender thread running deferred "
        "encode/checksum/sendmmsg off the event loop "
        "(BENCHMARKS.md round 8); 1 = the legacy single-socket plane",
    )
    p.add_argument(
        "--pump-pool", type=int, default=0,
        help="worker threads for INBOUND decode offload of >=4MB bodies "
        "(0 = auto: streams x endpoints, capped at 8)",
    )
    # the data plane v3 levers (BENCHMARKS.md round 9) — each independently
    # gated, defaulting off, riding Welcome like every knob above
    p.add_argument(
        "--uring", action="store_true",
        help="drain sender-thread bursts through io_uring (one ring "
        "submission per burst; runtime-probed — kernels without it fall "
        "back to the sendmmsg/sendmsg path, byte-identical)",
    )
    p.add_argument(
        "--intra-chunk", type=int, default=0, metavar="BYTES",
        dest="intra_chunk",
        help="split payload frames at/above this many encoded bytes into "
        "sub-frames striped across the payload streams (needs --streams "
        ">= 3 to actually split; 0 = off) — a one-chunk round stops "
        "serializing onto one socket",
    )
    p.add_argument(
        "--congestion", action="store_true",
        help="congestion-aware stripe scheduling: per-stream drain "
        "evidence shifts assignment weight away from a persistently slow "
        "stream (deficit-weighted, hysteresis both edges)",
    )


def _add_sharded_compress_flag(p: argparse.ArgumentParser) -> None:
    """--compress/--overlap for the sharded-param trainers (train-lm/-moe/-pp)."""
    p.add_argument(
        "--compress",
        choices=("bf16", "int8"),
        default=None,
        help="gradient wire compression: bf16 runs each sharding class's "
        "grouped psum at half width; int8 rides the explicit ring "
        "(per-segment scales) over each class's reduce axes at a quarter",
    )
    p.add_argument(
        "--overlap",
        action="store_true",
        help="issue one grad collective per param leaf INSIDE the backward "
        "pass (each over the leaf's replication axes) so the latency-hiding "
        "scheduler can run comm behind compute; composes with --compress",
    )


def _add_mesh_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--devices", type=int, default=None, help="mesh size (default: all)")
    p.add_argument(
        "--mesh",
        choices=("line", "grid"),
        default="line",
        help="1D line or 2D butterfly grid (SURVEY.md §4.3)",
    )


def _make_mesh(args):
    import jax

    from akka_allreduce_tpu.parallel import grid_mesh, line_mesh

    if args.mesh == "grid":
        devs = None if args.devices is None else jax.devices()[: args.devices]
        return grid_mesh(devices=devs)
    return line_mesh(args.devices)


def _cmd_local_demo(argv: list[str]) -> int:
    from akka_allreduce_tpu.control.local import _main

    sys.argv = ["local-demo", *argv]
    _main()
    return 0


def _cmd_bench(argv: list[str]) -> int:
    p = argparse.ArgumentParser("bench", description="threshold-allreduce bandwidth")
    p.add_argument("--floats", type=int, default=64 * 1024 * 1024)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--schedule", choices=("psum", "butterfly", "ring"), default="psum")
    p.add_argument("--bucket", type=int, default=None)
    p.add_argument(
        "--compress",
        choices=("bf16", "int8"),
        default=None,
        help="wire compression: bf16 halves collective bytes "
        "(psum/butterfly/ring), int8 quarters them (ring only)",
    )
    _add_mesh_flags(p)
    args = p.parse_args(argv)

    import json

    from akka_allreduce_tpu.comm.bandwidth import measure_allreduce

    mesh = _make_mesh(args)
    r = measure_allreduce(
        mesh,
        args.floats,
        iters=args.iters,
        schedule=args.schedule,
        bucket_size=args.bucket,
        compress=args.compress,
    )
    print(json.dumps(r.to_dict()))
    return 0


def _basic_train_flags(p: argparse.ArgumentParser) -> None:
    """The shared core every DP training CLI carries — train-zero1 uses
    exactly this subset, so its defaults can never drift from train-mlp's
    (the advertised numerical equivalence depends on them)."""
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=64, help="global batch size")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--metrics-out", default=None, help="JSONL metrics path")
    _compile_cache_flag(p)
    _checkpoint_flags(p)


def _compile_cache_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--compile-cache",
        nargs="?",
        const="",
        default=None,
        metavar="DIR",
        help="enable JAX's persistent compilation cache (optional DIR; "
        "default a shared temp dir) — recurring program shapes load from "
        "disk instead of recompiling across runs and re-meshes",
    )


def _maybe_enable_compile_cache(args) -> None:
    """Honor a --compile-cache flag if the CLI carries one."""
    if getattr(args, "compile_cache", None) is not None:
        from akka_allreduce_tpu.utils import enable_persistent_compile_cache

        # CLI processes keep the cache for their whole lifetime — the
        # restore handle matters for scoped users (bench-suite config 5)
        d = enable_persistent_compile_cache(args.compile_cache or None)
        print(f"persistent compile cache: {d.directory}")


def _checkpoint_flags(p: argparse.ArgumentParser) -> None:
    """--checkpoint-* flags — ONE definition so every training CLI gets
    the same set (including --async-checkpoint)."""
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument(
        "--async-checkpoint",
        action="store_true",
        help="save checkpoints WITHOUT stalling the step loop: capture is "
        "an on-device copy + async device-to-host launch (shard-local for "
        "ZeRO-1/FSDP/PP — no gather), serialization runs off-thread (a "
        "save still in flight at the next interval is skipped, not queued)",
    )
    p.add_argument(
        "--delta-checkpoint",
        action="store_true",
        help="per-leaf content-addressed store instead of Orbax: a save "
        "writes only leaves whose bytes changed since any kept checkpoint "
        "(unchanged leaves cost one hash, zero bytes — size saves to a "
        "slow link); composes with --async-checkpoint for non-stalling "
        "link-sized saves",
    )


def _make_checkpointer(args):
    """The checkpointer the --checkpoint-* flags ask for."""
    from akka_allreduce_tpu.train import (
        AsyncDeltaCheckpointer,
        AsyncTrainerCheckpointer,
        DeltaCheckpointer,
        TrainerCheckpointer,
    )

    is_async = getattr(args, "async_checkpoint", False)
    if getattr(args, "delta_checkpoint", False):
        cls = AsyncDeltaCheckpointer if is_async else DeltaCheckpointer
    else:
        cls = AsyncTrainerCheckpointer if is_async else TrainerCheckpointer
    return cls(args.checkpoint_dir)


def _train_flags(p: argparse.ArgumentParser) -> None:
    _add_mesh_flags(p)
    _basic_train_flags(p)
    p.add_argument("--bucket", type=int, default=None, help="grad bucket (elements)")
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace of the step loop (SURVEY.md §6); "
        "view with tensorboard or xprof",
    )
    p.add_argument(
        "--device-data",
        action="store_true",
        help="sample batches ON DEVICE inside one jitted chain (no host I/O "
        "per step — the right mode over a slow host<->device link)",
    )
    p.add_argument(
        "--bf16",
        action="store_true",
        help="bfloat16 activations/matmuls, fp32 params (MXU-native dtype)",
    )
    p.add_argument(
        "--accum",
        type=int,
        default=1,
        help="gradient-accumulation microbatches per step: one collective "
        "per effective batch, bigger batches in fixed memory",
    )
    p.add_argument(
        "--compress",
        choices=("bf16", "int8"),
        default=None,
        help="gradient wire compression: bf16 halves the collective bytes "
        "(psum); int8 quarters them (explicit ring, 1D mesh only; "
        "optimizer state stays fp32 either way)",
    )
    p.add_argument(
        "--error-feedback",
        action="store_true",
        help="carry each device's compression residual into its next "
        "contribution (EF-SGD): lossy sync becomes unbiased over time and a "
        "threshold-dropped device's gradient is delayed, not lost "
        "(requires --compress)",
    )
    p.add_argument(
        "--overlap",
        action="store_true",
        help="issue one grad collective per param leaf INSIDE the backward "
        "pass so the latency-hiding scheduler can run comm behind compute "
        "(SURVEY.md §8.4; composes with --compress bf16; excludes --bucket, "
        "int8, --error-feedback)",
    )


def _mfu_fields(flops_per_step, sec_per_step, n_devices: int = 1) -> dict:
    """tflops/mfu JSONL+print fields (empty off-TPU or without a FLOP model).

    MFU convention: GLOBAL model FLOPs (no remat recompute) over the mesh's
    aggregate dense bf16 peak — utils/benchmarking.py docstring
    (VERDICT r2 #1).
    """
    from akka_allreduce_tpu.utils.benchmarking import device_peak_flops, mfu

    if not flops_per_step or not sec_per_step or sec_per_step <= 0:
        return {}
    out = {"tflops_per_s": round(flops_per_step / sec_per_step / 1e12, 2)}
    u = mfu(
        flops_per_step, sec_per_step, device_peak_flops(),
        n_devices=n_devices,
    )
    if u is not None:
        out["mfu"] = round(u, 4)
    return out


def _mfu_note(fields: dict) -> str:
    if "mfu" in fields:
        return f"; {fields['tflops_per_s']} TFLOP/s, MFU {fields['mfu']:.1%}"
    if fields.get("tflops_per_s", 0) >= 0.01:
        return f"; {fields['tflops_per_s']} TFLOP/s"
    return ""


def _run_training_chain(trainer, ds, args, *, label: str, flops_per_step=None) -> int:
    """On-device block training: steps run in jitted blocks with no per-step
    host I/O. Honors the same checkpoint/profile/metrics flags as the host
    loop (checkpoints land between blocks of ``--checkpoint-every`` steps)."""
    import contextlib

    import numpy as np

    from akka_allreduce_tpu.utils.metrics import MetricsLogger

    shards = trainer.data_shards
    if args.batch % shards:
        raise SystemExit(
            f"global batch {args.batch} not divisible by {shards} data shards"
        )
    if getattr(args, "accum", 1) != 1:
        raise SystemExit(
            "--accum is not supported with --device-data (the on-device "
            "chain samples fixed per-device batches); drop one of the flags"
        )
    profile = contextlib.nullcontext()
    if getattr(args, "profile_dir", None):
        import jax

        profile = jax.profiler.trace(args.profile_dir)
    ckpt = None
    if args.checkpoint_dir:
        ckpt = _make_checkpointer(args)
        if ckpt.latest_step() is not None:
            step = ckpt.restore(trainer)
            print(f"resumed from step {step}")

    logger = MetricsLogger(args.metrics_out)
    sampler = ds.device_sampler()
    if ckpt and getattr(sampler, "diverges_from_host_stream", False):
        print(
            "warning: this dataset's device sampler regenerates templates on "
            "device; a checkpoint from the host loop continues on a "
            "DIFFERENT synthetic task"
        )
    per_dev = args.batch // shards
    block = (
        args.checkpoint_every
        if ckpt and args.checkpoint_every
        else args.steps
    )
    history = []
    t0 = time.perf_counter()
    with profile:
        remaining = args.steps
        while remaining > 0:
            n = min(block, remaining)
            history.extend(trainer.train_chain(sampler, n, per_dev))
            remaining -= n
            if ckpt and remaining > 0:
                ckpt.save(trainer)
    total = time.perf_counter() - t0
    if ckpt:
        ckpt.save(trainer, force=True, block=True)
        ckpt.close()
    for m in history:
        logger.log_event(
            kind="train_step", workload=label, step=m.step, loss=m.loss,
            contributors=m.contributors,
        )
    losses = [m.loss for m in history]
    # amortized time still includes compile, so this MFU is a LOWER bound;
    # bench-mfu is the slope-timed (compile-excluded) measurement
    perf = _mfu_fields(
        flops_per_step, total / max(len(losses), 1), trainer.n_devices
    )
    if perf:
        logger.log_event(
            kind="train_summary", workload=label, steps=len(losses),
            amortized_incl_compile=True, **perf,
        )
    logger.close()
    trend = (
        f"loss {losses[0]:.4f} -> {np.mean(losses[-5:]):.4f}"
        if losses
        else "no steps taken"
    )
    print(
        f"{label}: {len(losses)} on-device steps on {trainer.n_devices} "
        f"devices in {total:.2f}s incl. compile "
        f"({total / max(len(losses), 1) * 1e3:.1f} ms/step amortized)"
        f"{_mfu_note(perf)}; {trend}"
    )
    return 0


def _run_training(trainer, ds, args, *, label: str, flops_per_step=None) -> int:
    import contextlib

    import numpy as np

    from akka_allreduce_tpu.utils.metrics import MetricsLogger

    if getattr(args, "device_data", False):
        return _run_training_chain(
            trainer, ds, args, label=label, flops_per_step=flops_per_step
        )

    profile = contextlib.nullcontext()
    if getattr(args, "profile_dir", None):
        import jax

        profile = jax.profiler.trace(args.profile_dir)

    logger = MetricsLogger(args.metrics_out)
    ckpt = None
    if args.checkpoint_dir:
        ckpt = _make_checkpointer(args)
        if ckpt.latest_step() is not None:
            step = ckpt.restore(trainer)
            print(f"resumed from step {step}")
    accum = getattr(args, "accum", 1)
    if accum < 1:
        raise SystemExit(f"--accum must be >= 1, got {accum}")
    # trainer numbers feed the process registry too (OBSERVABILITY.md):
    # step count / last loss / step time, MFU at the end
    from akka_allreduce_tpu.obs.metrics import REGISTRY

    c_steps = REGISTRY.counter("trainer.steps")
    g_loss = REGISTRY.gauge("trainer.loss")
    h_step = REGISTRY.histogram("trainer.step_time_s")
    t0 = time.perf_counter()
    losses = []
    with profile:
        for x, y in ds.batches(args.batch, args.steps):
            st = time.perf_counter()
            if accum > 1:
                m = trainer.train_step_accum(x, y, accum)
            else:
                m = trainer.train_step(x, y)
            dt = time.perf_counter() - st
            losses.append(m.loss)
            c_steps.inc()
            g_loss.set(m.loss)
            h_step.observe(dt)
            logger.log_event(
                kind="train_step", workload=label, step=m.step, loss=m.loss,
                contributors=m.contributors, step_time_s=round(dt, 6),
                **_mfu_fields(flops_per_step, dt, trainer.n_devices),
            )
            if ckpt and args.checkpoint_every and m.step % args.checkpoint_every == 0:
                ckpt.save(trainer)
    total = time.perf_counter() - t0
    if ckpt:
        ckpt.save(trainer, force=True, block=True)
        ckpt.close()
    # host-loop step time includes per-step host<->device I/O (and the
    # tunnel, here), so this MFU is a floor; bench-mfu / --device-data
    # measure the on-device figure
    perf = _mfu_fields(
        flops_per_step, total / max(len(losses), 1), trainer.n_devices
    )
    if perf:
        if "mfu" in perf:
            REGISTRY.gauge("trainer.mfu").set(perf["mfu"])
        REGISTRY.gauge("trainer.tflops_per_s").set(perf["tflops_per_s"])
        logger.log_event(
            kind="train_summary", workload=label, steps=len(losses),
            host_loop=True, **perf,
        )
    logger.log_snapshot(REGISTRY, workload=label)
    logger.close()
    trend = (
        f"loss {losses[0]:.4f} -> {np.mean(losses[-5:]):.4f}"
        if losses
        else "no steps taken"
    )
    print(
        f"{label}: {len(losses)} steps on {trainer.n_devices} devices in "
        f"{total:.2f}s ({total / max(len(losses), 1) * 1e3:.1f} ms/step)"
        f"{_mfu_note(perf)}; {trend}"
    )
    return 0


def _cmd_bench_suite(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "bench-suite",
        description="run the full BASELINE config matrix (configs 1-5), one "
        "JSON record each (BASELINE.md)",
    )
    p.add_argument("--out", default=None, help="append records to this JSONL")
    p.add_argument("--quick", action="store_true", help="1/8-size payloads")
    args = p.parse_args(argv)

    from akka_allreduce_tpu.bench_suite import run_suite

    run_suite(quick=args.quick, out=args.out)
    return 0


def _cmd_train_zero1(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-zero1",
        description="MLP/MNIST DP-SGD with ZeRO-1 sharded optimizer state "
        "(optimizer memory / n_devices; numerically identical to train-mlp "
        "with the same optimizer — tests/test_zero1.py)",
    )
    p.add_argument("--devices", type=int, default=None, help="1D mesh size")
    _basic_train_flags(p)
    p.add_argument("--hidden", type=int, nargs="+", default=[128])
    p.add_argument(
        "--compress",
        choices=("bf16",),
        default=None,
        help="bf16 wire on the gradient reduce-scatter (weights' all_gather "
        "stays f32)",
    )
    p.add_argument(
        "--error-feedback",
        action="store_true",
        help="carry the bf16 cast residual into the next contribution "
        "(requires --compress bf16; costs no extra collective here)",
    )
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import numpy as np
    import optax

    from akka_allreduce_tpu.models import MLP, data
    from akka_allreduce_tpu.parallel import line_mesh
    from akka_allreduce_tpu.train import Zero1DPTrainer

    trainer = Zero1DPTrainer(
        MLP(hidden=tuple(args.hidden), classes=10),
        line_mesh(args.devices),
        example_input=np.zeros((1, 28, 28, 1), np.float32),
        # SGD to match train-mlp's default (the trainer's own default is
        # adam, which the CLI's lr=0.1 default would destabilize) — this is
        # what makes the advertised train-mlp equivalence hold
        optimizer=optax.sgd(args.lr),
        compress=args.compress,
        error_feedback=args.error_feedback,
    )
    print(
        f"ZeRO-1: {trainer.param_count / 1e3:.1f}K params, optimizer shard "
        f"{trainer.optimizer_shard_elems} elems/device on "
        f"{trainer.n_devices} devices"
    )
    from akka_allreduce_tpu.utils.benchmarking import dense_train_flops

    return _run_training(
        trainer, data.mnist_like(), args, label="zero1_mnist",
        flops_per_step=dense_train_flops(trainer.param_count, args.batch),
    )


def _cmd_train_fsdp(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-fsdp",
        description="FSDP / ZeRO-3 Transformer LM: trunk params AND "
        "optimizer state sharded 1/n over the data mesh, one layer gathered "
        "at a time inside the scan (train/fsdp.py; numerics match the dense "
        "model — tests/test_fsdp.py)",
    )
    p.add_argument("--devices", type=int, default=None, help="1D mesh size")
    _basic_train_flags(p)
    p.set_defaults(lr=1e-2)  # adam on an LM: the MLP-SGD default 0.1 diverges
    p.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel shards (FSDP x SP over a (data, seq) mesh; "
        "params still shard over the WHOLE mesh)",
    )
    p.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel shards (FSDP x TP over a (data, model[, seq]) "
        "mesh: attention heads / MLP hidden split Megatron-style over "
        "`model` while each shard's slice still FSDP-shards 1/(dp*sp))",
    )
    p.add_argument(
        "--impl", choices=("ring", "ulysses"), default="ring",
        help="attention schedule over the seq axis (with --sp > 1)",
    )
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument(
        "--kv-heads", type=int, default=None,
        help="grouped-query attention: K/V heads (divides --heads; 1 = MQA)",
    )
    p.add_argument("--layers", type=int, default=2)
    p.add_argument(
        "--remat",
        nargs="?",
        const="full",
        default=False,
        choices=("full", "params"),
        help="'full' (also bare --remat): recompute each layer on backward "
        "— one layer's activations AND one layer's gathered params live at "
        "a time, the full FSDP memory profile. 'params': drop the gathered "
        "layers and re-gather on backward — matmul activations stay saved, "
        "no matmul recompute (the ZeRO-3 sweet spot when activations fit)",
    )
    p.add_argument(
        "--compress",
        choices=("bf16", "int8"),
        default=None,
        help="per-layer collective compression: bf16 halves FSDP's "
        "collective bytes (gather + reduce-scatter transpose); int8 "
        "quarters them — one quantization per shard on the forward "
        "gather, sequential per-axis per-hop-scaled ring reduce-scatters "
        "on backward (composes with --sp; master params/moments stay "
        "f32 either way)",
    )
    p.add_argument(
        "--prefetch",
        action="store_true",
        help="software-pipeline the gathers: layer k+1's all_gather issues "
        "before layer k's compute so the latency-hiding scheduler can "
        "overlap them (same math, one extra gathered layer live). With "
        "--remat params the trunk unrolls so BACKWARD re-gathers overlap "
        "too; excludes --remat full",
    )
    p.add_argument(
        "--device-data",
        action="store_true",
        help="sample token batches ON DEVICE inside one jitted chain "
        "(no host I/O per step)",
    )
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.parallel import data_seq_mesh, line_mesh
    from akka_allreduce_tpu.train import FSDPLMTrainer

    n = args.devices or len(jax.devices())
    if n % (args.sp * args.tp):
        p.error(
            f"--sp {args.sp} x --tp {args.tp} does not divide the device "
            f"count {n}; devices would be silently idled"
        )
    if args.tp > 1 and args.sp > 1:
        # the canonical 3-axis layout (model innermost: TP's per-layer
        # psums are the most latency-sensitive collectives)
        from akka_allreduce_tpu.parallel import data_seq_model_mesh

        mesh = data_seq_model_mesh(
            n // (args.sp * args.tp), args.sp, args.tp
        )
    elif args.tp > 1:
        mesh = jax.make_mesh(
            (n // args.tp, args.tp), ("data", "model"),
            devices=jax.devices()[:n],
        )
    elif args.sp > 1:
        mesh = data_seq_mesh(n // args.sp, args.sp)
    else:
        mesh = line_mesh(args.devices)
    trainer = FSDPLMTrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=args.heads,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        seq_impl=args.impl,
        learning_rate=args.lr,
        remat=args.remat,
        compress=args.compress,
        prefetch=args.prefetch,
    )
    print(
        f"FSDP: {trainer.param_count / 1e3:.1f}K params, trunk shard "
        f"{trainer.trunk_shard_elems} elems/device, mesh "
        f"dp={trainer.dp} x tp={trainer.tp} x sp={trainer.sp}"
    )
    ds = data.lm_copy_task(args.seq_len, vocab=args.vocab)
    from akka_allreduce_tpu.utils.benchmarking import transformer_train_flops

    flops = transformer_train_flops(
        n_params=trainer.param_count, batch=args.batch, seq=args.seq_len,
        d_model=args.d_model, n_layers=args.layers,
    )
    return _run_training(
        trainer, ds, args, label="fsdp_lm", flops_per_step=flops
    )


def _cmd_bench_mfu(argv: list[str]) -> int:
    from akka_allreduce_tpu.bench_mfu import main as mfu_main

    return mfu_main(argv)


def _cmd_train_mlp(argv: list[str]) -> int:
    p = argparse.ArgumentParser("train-mlp", description="MLP/MNIST DP-SGD (config 3)")
    _train_flags(p)
    p.add_argument("--hidden", type=int, nargs="+", default=[128])
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax.numpy as jnp
    import numpy as np

    from akka_allreduce_tpu.models import MLP, data
    from akka_allreduce_tpu.train import DPTrainer

    trainer = DPTrainer(
        MLP(
            hidden=tuple(args.hidden),
            classes=10,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        ),
        _make_mesh(args),
        example_input=np.zeros((1, 28, 28, 1), np.float32),
        learning_rate=args.lr,
        bucket_size=args.bucket,
        compress=args.compress,
        error_feedback=args.error_feedback,
        overlap=args.overlap,
    )
    from akka_allreduce_tpu.utils.benchmarking import dense_train_flops

    return _run_training(
        trainer, data.mnist_like(), args, label="mlp_mnist",
        flops_per_step=dense_train_flops(trainer.param_count, args.batch),
    )


def _cmd_train_resnet(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-resnet", description="ResNet-50 DP grad sync (config 4)"
    )
    _train_flags(p)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax.numpy as jnp
    import numpy as np

    from akka_allreduce_tpu.models import ResNet50, data
    from akka_allreduce_tpu.train import DPTrainer

    trainer = DPTrainer(
        ResNet50(
            classes=args.classes,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        ),
        _make_mesh(args),
        example_input=np.zeros(
            (1, args.image_size, args.image_size, 3), np.float32
        ),
        learning_rate=args.lr,
        # the reference's chunk geometry by default; --overlap drops only
        # the DEFAULT (an explicit --bucket still reaches the trainer's
        # conflicting-flags guard, same contract as train-mlp)
        bucket_size=(
            args.bucket
            if args.bucket is not None
            else (None if args.overlap else 262_144)
        ),
        compress=args.compress,
        error_feedback=args.error_feedback,
        overlap=args.overlap,
    )
    print(f"ResNet params: {trainer.param_count / 1e6:.1f}M")
    ds = data.SyntheticClassification(
        (args.image_size, args.image_size, 3), args.classes, seed=0
    )
    # conv FLOPs from the analytic architecture mirror (the 6N rule
    # undercounts convs), x3 for fwd + bwd — the SAME convention bench-mfu
    # uses, so the two tools always agree on ResNet MFU
    from akka_allreduce_tpu.models.resnet import resnet_fwd_flops

    fwd = resnet_fwd_flops(trainer.model, args.image_size, args.batch)
    return _run_training(
        trainer, ds, args, label="resnet50", flops_per_step=3 * fwd
    )


def _cmd_train_lm(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-lm",
        description="long-context Transformer LM, DP x SP with ring attention "
        "or Ulysses (no analog in the reference — SURVEY.md §6)",
    )
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seq-len", type=int, default=256, help="GLOBAL sequence length")
    p.add_argument("--dp", type=int, default=None, help="data-parallel rows")
    p.add_argument("--sp", type=int, default=None, help="sequence shards")
    p.add_argument(
        "--tp",
        type=int,
        default=1,
        help="tensor-parallel shards (Megatron-style heads/hidden split "
        "over a third mesh axis; needs --dp and --sp too)",
    )
    p.add_argument("--impl", choices=("ring", "ulysses"), default="ring")
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument(
        "--kv-heads", type=int, default=None,
        help="grouped-query attention: K/V heads (divides --heads; 1 = "
        "MQA). Under ring/Ulysses SP the compact K/V form crosses the "
        "wire, shrinking per-step ICI bytes by heads/kv_heads",
    )
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--metrics-out", default=None, help="JSONL metrics path")
    p.add_argument(
        "--device-data",
        action="store_true",
        help="sample token batches ON DEVICE inside one jitted chain",
    )
    p.add_argument(
        "--bf16",
        action="store_true",
        help="bfloat16 activations/matmuls (params and logits stay fp32) — "
        "the MXU-native dtype",
    )
    p.add_argument(
        "--remat",
        action="store_true",
        help="rematerialize each block on backward (jax.checkpoint): "
        "O(layers) activation memory for one extra forward of FLOPs — "
        "the long-sequence memory knob",
    )
    _checkpoint_flags(p)
    _add_sharded_compress_flag(p)
    _compile_cache_flag(p)
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax.numpy as jnp

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.parallel import data_seq_mesh, data_seq_model_mesh
    from akka_allreduce_tpu.train import LongContextTrainer

    if args.tp > 1:
        if not (args.dp and args.sp):
            p.error("--tp requires explicit --dp and --sp")
        mesh = data_seq_model_mesh(args.dp, args.sp, args.tp)
    else:
        mesh = data_seq_mesh(args.dp, args.sp)
    trainer = LongContextTrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=args.heads,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        seq_impl=args.impl,
        learning_rate=args.lr,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        remat=args.remat,
        compress=args.compress,
        overlap=args.overlap,
    )
    print(
        f"LM params: {trainer.param_count / 1e6:.2f}M, mesh "
        f"dp={trainer.dp} x sp={trainer.sp} x tp={trainer.tp}, "
        f"seq_len={args.seq_len} ({args.impl})"
    )
    ds = data.lm_copy_task(args.seq_len, vocab=args.vocab)
    from akka_allreduce_tpu.utils.benchmarking import transformer_train_flops

    flops = transformer_train_flops(
        n_params=trainer.param_count, batch=args.batch, seq=args.seq_len,
        d_model=args.d_model, n_layers=args.layers,
    )
    # --device-data is handled inside _run_training via _run_training_chain
    # (trainer.data_shards tells it rows are per DP replica, not per device)
    return _run_training(
        trainer, ds, args, label=f"lm_{args.impl}", flops_per_step=flops
    )


def _cmd_cluster_master(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "cluster-master",
        description="seed/master role: membership + round scheduling over TCP "
        "(the reference's master main, SURVEY.md §4.1)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--nodes", type=int, default=2, help="nodes before organizing")
    p.add_argument("--dims", type=int, default=1, choices=(1, 2))
    p.add_argument("--size", type=int, default=1_000_000)
    p.add_argument("--chunk", type=int, default=262_144)
    p.add_argument("--rounds", type=int, default=20, help="-1 = run forever")
    p.add_argument(
        "--round-window", type=int, default=2,
        help="line rounds in flight (max 4 = the workers' out-of-order "
        "buffer window): deeper windows overlap the per-round "
        "master<->node RTT chain (the latency-bound share of the pair "
        "wall — BENCHMARKS.md round 4)",
    )
    p.add_argument("--th", type=float, default=1.0, help="all three thresholds")
    p.add_argument("--heartbeat", type=float, default=1.0, help="interval (s)")
    p.add_argument(
        "--line-shards", type=int, default=1,
        help="dims-1 round-scheduling shards: split the membership into "
        "up to N LineMasters, each owning (and reducing within) a "
        "contiguous worker subset (RESILIENCE.md 'Tier 6')",
    )
    p.add_argument(
        "--grid", default="", metavar="RxC",
        help="pod-grid coordinate bootstrap (RESILIENCE.md 'Scale'): "
        "anchor node ids to an RxC layout (nodes derive theirs from "
        "--process-index / the pod env), so shard membership and dims-2 "
        "row/column lines follow the pod layout instead of join order",
    )
    p.add_argument("--metrics-out", default=None, help="per-round JSONL path")
    p.add_argument(
        "--round-deadline", type=float, default=0.0,
        help="stall watchdog: a round in flight longer than this many "
        "seconds dumps the flight recorder (0 = off)",
    )
    _add_wire_dtype_flag(p)
    _add_data_plane_flags(p)
    _add_chaos_flags(p)
    _add_adapt_flags(p)
    _add_gossip_flags(p)
    _add_obs_flags(p)
    args = p.parse_args(argv)
    from akka_allreduce_tpu.config import WorkerConfig

    worker_window = WorkerConfig().round_window
    if not 1 <= args.round_window <= worker_window:
        # past the workers' bounded out-of-order buffer, fast-forwarding
        # silently corrupts round accounting (measured collapse at 8)
        p.error(
            f"--round-window must be in [1, {worker_window}] (the "
            f"workers' out-of-order buffer window), got {args.round_window}"
        )
    return _run_cluster_master(args)


def _run_cluster_master(args) -> int:
    """Shared master bootstrap for cluster-master / train-cluster-master."""
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    import asyncio

    from akka_allreduce_tpu.config import (
        AllreduceConfig,
        ChaosConfig,
        DataPlaneConfig,
        LineMasterConfig,
        MasterConfig,
        MetaDataConfig,
        RetryPolicy,
        ThresholdConfig,
        WorkerConfig,
    )
    from akka_allreduce_tpu.control.bootstrap import MasterProcess
    from akka_allreduce_tpu.utils.metrics import MetricsLogger

    chaos_spec = getattr(args, "chaos_spec", "")
    if chaos_spec:
        # fail fast on a malformed spec — before any process is spawned
        from akka_allreduce_tpu.control.chaos import parse_spec

        parse_spec(chaos_spec)
    grid_rows = grid_cols = 0
    if getattr(args, "grid", ""):
        from akka_allreduce_tpu.control.pod import parse_grid

        grid_rows, grid_cols = parse_grid(args.grid)
    cfg = AllreduceConfig(
        threshold=ThresholdConfig(args.th, args.th, args.th),
        metadata=MetaDataConfig(
            data_size=args.size,
            max_chunk_size=args.chunk,
            wire_dtype=getattr(args, "wire_dtype", "f32"),
        ),
        line_master=LineMasterConfig(
            round_window=args.round_window, max_rounds=args.rounds
        ),
        master=MasterConfig(
            node_num=args.nodes,
            dimensions=args.dims,
            line_shards=getattr(args, "line_shards", 1),
            grid_rows=grid_rows,
            grid_cols=grid_cols,
            heartbeat_interval_s=args.heartbeat,
            round_deadline_s=getattr(args, "round_deadline", 0.0),
            retry=RetryPolicy(
                max_retries=getattr(args, "send_retries", 1),
                backoff_base_s=getattr(args, "send_backoff_base", 0.05),
                backoff_max_s=getattr(args, "send_backoff_max", 2.0),
            ),
        ),
        # both CLI node roles publish snapshots (fixed demo arrays / weights
        # replaced by reference), so the zero-copy scatter path is sound
        worker=WorkerConfig(zero_copy_scatter=True),
        chaos=ChaosConfig(
            seed=getattr(args, "chaos_seed", 0), spec=chaos_spec
        ),
        adapt=_adapt_config_from(args),
        data_plane=DataPlaneConfig(
            streams=getattr(args, "streams", 1),
            pump_pool=getattr(args, "pump_pool", 0),
            uring=getattr(args, "uring", False),
            intra_chunk_min_bytes=getattr(args, "intra_chunk", 0),
            congestion=getattr(args, "congestion", False),
        ),
        gossip=_gossip_config_from(args),
    )
    _install_obs(args)

    async def run() -> None:
        metrics = MetricsLogger(args.metrics_out) if args.metrics_out else None
        master = MasterProcess(
            cfg, args.host, args.port, metrics=metrics,
            # a real OS process: the chaos `crash:node=m` fault may
            # os._exit here (the chaos-failover drill's leader kill) —
            # and the injector flushes the chaos log on its way down
            allow_crash=True,
            chaos_log=getattr(args, "chaos_log", None),
        )
        ep = await master.start()
        print(f"master listening on {ep}", flush=True)
        # SIGTERM ends an open-ended (--rounds -1) run GRACEFULLY: nodes get
        # a Shutdown broadcast and every process flushes its metrics/chaos
        # logs — the chaos runner's --duration mode depends on this
        import signal as _signal

        from akka_allreduce_tpu.control.remote import observed_task

        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(
                _signal.SIGTERM,
                lambda: observed_task(
                    master.shutdown("sigterm"), name="sigterm-shutdown"
                ),
            )
        except (NotImplementedError, RuntimeError):  # pragma: no cover
            pass  # non-Unix event loops: SIGTERM stays abrupt
        try:
            t0, c0 = time.perf_counter(), time.process_time()
            await master.run_until_done()
            print(
                f"master done: {master.rounds_completed} line-rounds "
                f"completed (wall {time.perf_counter() - t0:.2f}s, own cpu "
                f"{time.process_time() - c0:.2f}s over the round window)",
                flush=True,
            )
            await asyncio.sleep(2 * args.heartbeat)  # let Shutdown flush
        finally:
            await master.stop()
            if getattr(args, "chaos_log", None) and master.transport.chaos:
                path = master.transport.chaos.write_log(args.chaos_log)
                print(f"chaos event log: {path}", flush=True)
            if getattr(args, "adapt_log", None) and master.adapt is not None:
                path = master.adapt.write_log(args.adapt_log)
                print(f"adapt decision log: {path}", flush=True)
            if metrics is not None:
                from akka_allreduce_tpu.obs.metrics import REGISTRY

                metrics.log_snapshot(REGISTRY, role="master")
                metrics.close()

    asyncio.run(run())
    _write_trace(args)
    return 0


def _cmd_cluster_node(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "cluster-node",
        description="worker-node role: joins the seed, serves one worker per "
        "grid dimension (the reference's worker main, SURVEY.md §4.1)",
    )
    p.add_argument("--seed", required=True, help="master host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--node-id", type=int, default=-1, help="-1 = master assigns")
    p.add_argument(
        "--grid", default="", metavar="RxC",
        help="pod-grid coordinate bootstrap (RESILIENCE.md 'Scale'): "
        "derive this node's id from its process index, row-major over "
        "the RxC layout (SNIPPETS.md [2]'s multi-controller pattern — "
        "process_index/local_devices as grid coordinates), so shard "
        "membership follows the pod layout instead of join order",
    )
    p.add_argument(
        "--process-index", type=int, default=-1,
        help="this process's pod index for --grid (-1 = resolve from "
        "AKKA_PROCESS_INDEX & friends, then a live jax.distributed)",
    )
    p.add_argument("--data-seed", type=int, default=None, help="payload RNG seed")
    p.add_argument(
        "--metrics-out", default=None,
        help="JSONL path for the node's per-stage protocol timing "
        "(fields encode/socket_write/decode/handler as wall spans, plus "
        "cpu_s/wall_s — the on-cpu/off-cpu partition of the round "
        "window)",
    )
    p.add_argument(
        "--chaos-log", default=None, metavar="FILE",
        help="write this node's chaos event log (JSONL) on exit; the "
        "chaos spec itself arrives from the master via Welcome",
    )
    p.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="arm peer state transfer (RESILIENCE.md 'Recovery'): "
        "delta-checkpoint this node's running state here, replicate the "
        "chunks to peers after every save, and on (re)join restore from "
        "disk — or, when this directory is gone, pull the chunks back "
        "from live peers",
    )
    p.add_argument(
        "--state-every", type=int, default=5,
        help="save + replicate state every N flushed rounds",
    )
    p.add_argument(
        "--replicas", type=int, default=2,
        help="how many peers each checkpoint is pushed to (K)",
    )
    p.add_argument(
        "--uniform-check", action="store_true",
        help="assert-quality accounting for drills: with every node running "
        "the SAME --data-seed, each round's reduced average must equal the "
        "payload regardless of how many contributors made it — track the "
        "max deviation (the wire-compression + EF error) and report it as "
        "max_err= in the shutdown line (chaos-adapt's error-budget check)",
    )
    _add_obs_flags(p)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    if args.grid:
        # grid-coordinate bootstrap: the node id IS the pod coordinate
        # (row-major), never the join order — which is what anchors
        # shard membership to the layout (control/pod.py)
        from akka_allreduce_tpu.control import pod as _pod

        rows, cols = _pod.parse_grid(args.grid)
        idx = _pod.resolve_process_index(
            args.process_index if args.process_index >= 0 else None
        )
        row, col = _pod.grid_coords(idx, rows, cols)
        if args.node_id >= 0 and args.node_id != idx:
            p.error(
                f"--node-id {args.node_id} contradicts the grid "
                f"coordinate {idx} ({row},{col}); drop one of them"
            )
        args.node_id = idx
        print(
            f"pod grid {rows}x{cols}: process {idx} -> coords "
            f"({row},{col}), node id {idx}",
            flush=True,
        )
    _install_obs(args)

    import asyncio
    import json

    import numpy as np

    from akka_allreduce_tpu.control.bootstrap import NodeProcess
    from akka_allreduce_tpu.control.cluster import Endpoint
    from akka_allreduce_tpu.control.remote import observed_task
    from akka_allreduce_tpu.protocol import AllReduceInput

    state = {"payload": None, "flushes": 0, "t0": None, "node": None,
             "save_task": None, "step_base": 0, "save_enabled": False,
             "last_flush_round": -1, "dup_flushes": 0, "max_err": 0.0}

    def source(req):
        if state["payload"] is None:
            raise RuntimeError("source called before Welcome sized the payload")
        return AllReduceInput(state["payload"])

    def sink(out):
        state["flushes"] += 1
        # flushed round ids are strictly increasing BY CONSTRUCTION (the
        # worker abandons older rounds on completion, and the cross-epoch
        # floor survives rejoins) — a non-increasing flush means a round
        # was applied twice. The chaos-failover drill asserts this stays 0
        # across a master failover.
        if out.iteration <= state["last_flush_round"]:
            state["dup_flushes"] += 1
        else:
            state["last_flush_round"] = out.iteration
        if args.uniform_check and state["payload"] is not None:
            # identical payloads on every node => the true average IS the
            # payload wherever at least one contribution landed; any
            # deviation is wire-compression error (f16 rounding / int8
            # quantization net of the EF carry) — the budget chaos-adapt
            # asserts. O(size) numpy per flush, drill-scale only.
            got = out.average()
            mask = out.count > 0
            if mask.any():
                err = float(
                    np.max(np.abs(got[mask] - state["payload"][mask]))
                )
                state["max_err"] = max(state["max_err"], err)
        node = state["node"]
        n = state["flushes"]
        if (
            node is None
            or node.state is None
            or not state["save_enabled"]
            or not args.state_every
            or n % args.state_every
        ):
            # saves stay gated until the startup restore DECIDED: a reborn
            # node writing fresh saves into its emptied store mid-restore
            # would shadow the peer state it is trying to recover
            return
        prev = state["save_task"]
        if prev is not None and not prev.done():
            return  # bounded: at most one save+replicate cycle in flight
        snap = {
            "payload": state["payload"],
            # the reduced view aliases a recycled recv buffer — snapshot it
            "reduced": np.array(out.data, dtype=np.float32, copy=True),
        }
        step = state["step_base"] + n
        state["save_task"] = observed_task(
            node.save_state(step, snap), name=f"state-save-{step}"
        )

    async def run() -> int:
        node = NodeProcess(
            Endpoint.parse(args.seed),
            source,
            sink,
            args.host,
            args.port,
            preferred_node_id=args.node_id,
            # real OS process: the chaos `crash` fault may os._exit here
            allow_crash=True,
            chaos_log=args.chaos_log,
            state_dir=args.state_dir,
            replicas=args.replicas,
        )
        state["node"] = node
        await node.start()
        nid = await node.wait_welcomed()
        size = node.config.metadata.data_size
        seed = args.data_seed if args.data_seed is not None else nid
        state["payload"] = (
            np.random.default_rng(seed).standard_normal(size).astype(np.float32)
        )
        if args.state_dir:
            # the rejoin restore path: disk when it is current, else a
            # parallel chunk pull from live peer holders (statetransfer).
            # give_up: rounds flush through THIS loop while the restore
            # coroutine waits its turn — once a couple of save periods
            # have gone by with the master still answering "nothing
            # known", more blind patience only pushes the first
            # checkpoint past an early seeded crash (the chaos-recover
            # flake under load); an active chunk pull is never capped
            flushes0 = state["flushes"]
            # one save period of our own rounds: the whole pipeline behind
            # the gate (save -> replicate -> peers verify -> advert) needs
            # its own rounds of margin before a seeded early crash, so the
            # blind window must not eat a second period
            budget = max(1, args.state_every or 1)
            rest = await node.restore_state(
                give_up=lambda: state["flushes"] - flushes0 >= budget
            )
            if rest is not None and rest.get("complete"):
                try:
                    step, saved = node.state.store.load_state()
                except (FileNotFoundError, ValueError) as e:
                    print(f"state restore unreadable: {e}", flush=True)
                else:
                    payload = saved.get("payload")
                    if payload is not None and payload.size == size:
                        state["payload"] = np.ascontiguousarray(
                            payload, dtype=np.float32
                        )
                    # continue the save-step numbering where it left off so
                    # post-restore adverts stay monotonic (flushes itself
                    # keeps counting only THIS process's rounds)
                    state["step_base"] = int(step)
            state["save_enabled"] = True
            print(
                "RESTORE "
                + json.dumps(rest if rest is not None else {"source": "none"}),
                flush=True,
            )
        state["t0"] = time.perf_counter()
        cpu0 = time.process_time()
        print(f"node {nid} joined {args.seed}", flush=True)
        try:
            reason = await node.run_until_shutdown()
        finally:
            await node.stop()
            if args.chaos_log and node.transport.chaos is not None:
                node.transport.chaos.write_log(args.chaos_log)
        dt = time.perf_counter() - state["t0"]
        cpu = time.process_time() - cpu0
        mbs = state["flushes"] * size * 4 / max(dt, 1e-9) / 1e6
        stages = dict(node.transport.stage_seconds)
        accounted = sum(stages.values())
        stage_note = ", ".join(
            f"{k}={v:.3f}s" for k, v in stages.items()
        )
        # provenance for the recorded number: which wire codec ran (the C++
        # hot loop vs the struct/numpy fallback) — same flag the engine
        # kernels use, so one bool covers both hot paths. loaded(), not
        # available(): the latter may block on a compile and then describe
        # a library the finished run never used
        from akka_allreduce_tpu import native as _native

        wire_path = "native" if _native.loaded() else "python"
        err_note = (
            f", max_err={state['max_err']:.6f}" if args.uniform_check else ""
        )
        print(
            f"node {nid} shut down ({reason}): {state['flushes']} rounds, "
            f"{mbs:.1f} MB/s reduced, dup_flushes={state['dup_flushes']}"
            f"{err_note}",
            flush=True,
        )
        # wall decomposition (VERDICT r3 #9). Two views, different units:
        # the PARTITION of wall is own-cpu vs off-cpu (process_time —
        # off-cpu = the OS ran someone else, e.g. the peer/master on a
        # shared core, or the socket was idle); the stage timers are
        # WALL SPANS (they include awaits and any preemption inside a
        # stage), an overlay for locating where time passes, not a
        # disjoint part of the partition.
        print(
            f"node {nid} stage times over {dt:.2f}s wall: {stage_note} "
            f"(wall spans, {accounted:.2f}s total; partition: own cpu "
            f"{cpu:.2f}s, off-cpu {max(dt - cpu, 0.0):.2f}s = "
            f"peer/master scheduled or socket idle; wire={wire_path})",
            flush=True,
        )
        if args.metrics_out:
            from akka_allreduce_tpu.obs.metrics import REGISTRY
            from akka_allreduce_tpu.utils.metrics import MetricsLogger

            m = MetricsLogger(args.metrics_out)
            m.log_event(
                kind="node_stage_times", node=nid, wall_s=round(dt, 3),
                cpu_s=round(cpu, 3),
                rounds=state["flushes"], mb_per_s=round(mbs, 1),
                dup_flushes=state["dup_flushes"],
                wire=wire_path,
                **{k: round(v, 4) for k, v in stages.items()},
            )
            m.log_snapshot(REGISTRY, role="node", node=nid)
            m.close()
        return 0

    rc = asyncio.run(run())
    _write_trace(args)
    return rc


def _cmd_cluster_standby(argv: list[str]) -> int:
    """Warm-standby master role (RESILIENCE.md 'Tier 4'): registers with
    the leader, absorbs the replicated state-digest stream, and takes over
    — bumping the leadership epoch — when its lease on the leader expires.
    Nodes walk the standby list distributed via Welcome/AddressBook and
    re-join here; the round budget then completes under the new epoch."""
    p = argparse.ArgumentParser(
        "cluster-standby",
        description="warm-standby master: replicate the leader's control-"
        "plane state and take over on leader loss (epoch-fenced failover)",
    )
    p.add_argument("--seed", required=True, help="leader master host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--heartbeat", type=float, default=1.0,
        help="lease tick + expected digest cadence (s); match the "
        "leader's --heartbeat",
    )
    p.add_argument(
        "--phi", type=float, default=8.0,
        help="phi-accrual threshold of the leader lease (lower = faster, "
        "riskier takeover)",
    )
    p.add_argument("--metrics-out", default=None, help="per-round JSONL path")
    _add_obs_flags(p)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    _install_obs(args)

    import asyncio
    import json

    from akka_allreduce_tpu.config import AllreduceConfig
    from akka_allreduce_tpu.control.bootstrap import MasterProcess
    from akka_allreduce_tpu.control.cluster import Endpoint
    from akka_allreduce_tpu.config import MasterConfig
    from akka_allreduce_tpu.utils.metrics import MetricsLogger

    async def run() -> int:
        metrics = MetricsLogger(args.metrics_out) if args.metrics_out else None
        # placeholder config: everything that matters (thresholds, chaos,
        # retry, round budget) is ADOPTED from the leader's digest at
        # takeover — only the lease cadence is ours to configure
        cfg = AllreduceConfig(
            master=MasterConfig(heartbeat_interval_s=args.heartbeat)
        )
        master = MasterProcess(
            cfg, args.host, args.port,
            standby_of=Endpoint.parse(args.seed),
            phi_threshold=args.phi,
            metrics=metrics,
            allow_crash=True,
        )

        def on_takeover(m: MasterProcess) -> None:
            # machine-readable line the chaos-failover drill gates on
            print(
                "TAKEOVER "
                + json.dumps(
                    {
                        "epoch": m.epoch,
                        "members": sorted(m.grid.nodes),
                        "resume_round": m.grid.resume_round,
                        "completed_carried": m.grid._completed_before_reorg,
                        "ckpt_origins": sorted(m._ckpt),
                    }
                ),
                flush=True,
            )

        master.on_takeover = on_takeover
        ep = await master.start()
        print(f"standby listening on {ep} (leader {args.seed})", flush=True)
        import signal as _signal

        from akka_allreduce_tpu.control.remote import observed_task

        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(
                _signal.SIGTERM,
                lambda: observed_task(
                    master.shutdown("sigterm"), name="sigterm-shutdown"
                ),
            )
        except (NotImplementedError, RuntimeError):  # pragma: no cover
            pass
        try:
            t0 = time.perf_counter()
            await master.run_until_done()
            if master.active:
                print(
                    f"master done: {master.rounds_completed} line-rounds "
                    f"completed (epoch {master.epoch}, wall "
                    f"{time.perf_counter() - t0:.2f}s since standby start)",
                    flush=True,
                )
                await asyncio.sleep(2 * args.heartbeat)  # let Shutdown flush
            else:
                print(
                    f"standby released ({master.shutdown_reason})",
                    flush=True,
                )
        finally:
            await master.stop()
            if metrics is not None:
                from akka_allreduce_tpu.obs.metrics import REGISTRY

                metrics.log_snapshot(REGISTRY, role="standby")
                metrics.close()
        return 0

    rc = asyncio.run(run())
    _write_trace(args)
    return rc


def _mlp_trainer(hidden, lr, seed=0):
    import numpy as np

    from akka_allreduce_tpu.models import MLP
    from akka_allreduce_tpu.parallel import line_mesh
    from akka_allreduce_tpu.train import DPTrainer

    return DPTrainer(
        MLP(hidden=tuple(hidden), classes=10),
        line_mesh(1),  # local learner: one device per node process
        example_input=np.zeros((1, 28, 28, 1), np.float32),
        learning_rate=lr,
        seed=seed,
    )


def _cluster_model_flags(p) -> None:
    """Model-selection flags shared by the train-cluster master and nodes —
    every process must be started with the SAME model flags (the master
    derives the cluster's data_size from them)."""
    p.add_argument(
        "--model", choices=("mlp", "lm"), default="mlp",
        help="mlp = MLP/MNIST (reference workload); lm = Transformer LM",
    )
    p.add_argument("--hidden", type=int, nargs="+", default=[32])
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)


def _cluster_trainer(args, lr: float, seed: int = 17):
    """The node-local learner for the distributed cluster, per --model."""
    if args.model == "lm":
        import jax

        from akka_allreduce_tpu.parallel import data_seq_mesh
        from akka_allreduce_tpu.train import LongContextTrainer

        return LongContextTrainer(
            data_seq_mesh(1, 1, devices=jax.devices()[:1]),
            vocab=args.vocab,
            d_model=args.d_model,
            n_heads=args.heads,
            n_layers=args.layers,
            seq_len=args.seq_len,
            learning_rate=lr,
            seed=seed,
        )
    return _mlp_trainer(args.hidden, lr, seed=seed)


def _cluster_batches(args, data_seed: int):
    from akka_allreduce_tpu.models import data

    if args.model == "lm":
        ds = data.lm_copy_task(args.seq_len, vocab=args.vocab, seed=data_seed)
        return iter(ds.batches(args.batch, args.steps))
    return iter(
        data.mnist_like(seed=data_seed).batches(args.batch, args.steps)
    )


def _cmd_train_cluster_master(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-cluster-master",
        description="master for distributed elastic-averaging training "
        "(the reference's multi-JVM training deployment, SURVEY.md §4.4); "
        "data_size is derived from the model so start nodes with the SAME "
        "model flags",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--nodes", type=int, default=2)
    _cluster_model_flags(p)
    p.add_argument("--rounds", type=int, default=30, help="-1 = run forever")
    p.add_argument("--chunk", type=int, default=65536)
    p.add_argument("--th", type=float, default=1.0, help="all three thresholds")
    p.add_argument("--heartbeat", type=float, default=0.5, help="interval (s)")
    p.add_argument("--metrics-out", default=None, help="per-round JSONL path")
    _add_wire_dtype_flag(p)
    args = p.parse_args(argv)
    args.size = _cluster_trainer(args, 0.1).param_count
    print(f"model: {args.size} params -> data_size {args.size}", flush=True)
    args.dims = 1
    return _run_cluster_master(args)


def _cmd_train_cluster_node(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-cluster-node",
        description="training node: local SGD on its own data shard + "
        "asynchronous elastic-averaging weight sync over the cluster",
    )
    p.add_argument("--seed", required=True, help="master host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--node-id", type=int, default=-1, help="-1 = master assigns")
    _cluster_model_flags(p)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--elastic-rate", type=float, default=0.5)
    p.add_argument("--data-seed", type=int, default=None, help="shard RNG seed")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    import asyncio

    from akka_allreduce_tpu.control.cluster import Endpoint
    from akka_allreduce_tpu.train import ElasticClusterNode

    async def run() -> int:
        trainer = _cluster_trainer(args, args.lr, seed=17)
        node = ElasticClusterNode(
            Endpoint.parse(args.seed),
            trainer,
            _cluster_batches(
                args, args.data_seed if args.data_seed is not None else 0
            ),
            elastic_rate=args.elastic_rate,
            host=args.host,
            port=args.port,
            preferred_node_id=args.node_id,
        )
        t0 = time.perf_counter()
        steps = await node.run(args.steps)
        dt = time.perf_counter() - t0
        losses = node.losses
        trend = (
            f"loss {losses[0]:.4f} -> {losses[-1]:.4f}"
            if losses
            else "no steps taken"
        )
        print(
            f"trained {steps} steps in {dt:.1f}s "
            f"({node.rounds_applied} sync rounds applied); {trend}",
            flush=True,
        )
        return 0

    return asyncio.run(run())


def _cmd_elastic_demo(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "elastic-demo",
        description="config-5 dropout + late-joiner recovery, end to end",
    )
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-per-device", type=int, default=8)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--drop-at", type=int, default=10, help="step the last node dies")
    p.add_argument("--rejoin-at", type=int, default=20, help="step it comes back")
    p.add_argument(
        "--family",
        choices=("dp", "moe", "pp", "lc"),
        default="dp",
        help="which elastic trainer rides the cycle: dp = MLP DPTrainer; "
        "moe / pp / lc = the round-4 families whose expert / pipe / seq "
        "mesh axes RE-SHAPE with membership (the same experts "
        "redistribute, the same logical layers re-chunk, sequences "
        "re-split)",
    )
    _compile_cache_flag(p)
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax
    import numpy as np

    from akka_allreduce_tpu.models import MLP, data
    from akka_allreduce_tpu.train import (
        ElasticDPTrainer,
        ElasticLongContextTrainer,
        ElasticMoETrainer,
        ElasticPipelineTrainer,
    )

    devices = jax.devices()
    per = max(1, len(devices) // args.nodes)
    assignment = {
        n: devices[n * per : (n + 1) * per] for n in range(args.nodes)
    }
    now = {"t": 0.0}
    seq_len = 32
    fam_kw = dict(
        vocab=16, d_model=32, n_heads=2, learning_rate=1e-2, seed=0,
        clock=lambda: now["t"],
    )
    if args.family == "dp":
        trainer = ElasticDPTrainer(
            MLP(hidden=(32,), classes=10),
            assignment,
            example_input=np.zeros((1, 28, 28, 1), np.float32),
            clock=lambda: now["t"],
        )
        ds = data.mnist_like()
        batch_rows = lambda t: args.batch_per_device * t.n_devices  # noqa: E731
        shape_of = lambda t: f"{t.n_devices} devices"  # noqa: E731
    elif args.family == "moe":
        trainer = ElasticMoETrainer(
            assignment, n_experts=4, n_layers=1, seq_len=seq_len,
            capacity_factor=4.0, **fam_kw,
        )
        ds = data.lm_copy_task(seq_len, vocab=16)
        batch_rows = lambda t: t.dp * t.ep * args.batch_per_device  # noqa: E731
        shape_of = lambda t: f"dp{t.dp} x ep{t.ep}"  # noqa: E731
    elif args.family == "pp":
        trainer = ElasticPipelineTrainer(
            assignment, n_layers=4, microbatches=2, seq_len=seq_len,
            **fam_kw,
        )
        ds = data.lm_copy_task(seq_len, vocab=16)
        batch_rows = (  # noqa: E731
            lambda t: t.dp * t.microbatches * args.batch_per_device
        )
        shape_of = lambda t: f"dp{t.dp} x pp{t.stages}"  # noqa: E731
    else:  # lc
        trainer = ElasticLongContextTrainer(
            assignment, seq_len=seq_len, max_sp=4, n_layers=1, **fam_kw,
        )
        ds = data.lm_copy_task(seq_len, vocab=16)
        batch_rows = lambda t: t.dp * args.batch_per_device  # noqa: E731
        shape_of = lambda t: f"dp{t.dp} x sp{t.sp}"  # noqa: E731
    dead = args.nodes - 1
    for step in range(args.steps):
        live = set(trainer.member_nodes)
        if step == args.rejoin_at:
            trainer.heartbeat(dead)  # late joiner
        for n in range(args.nodes):
            if n == dead and args.drop_at <= step < args.rejoin_at:
                continue
            if n in trainer.devices_by_node:
                trainer.heartbeat(n)
        now["t"] += 1.0
        if trainer.poll():
            print(
                f"step {step}: re-meshed to {trainer.n_nodes} nodes / "
                f"{shape_of(trainer.trainer)} "
                f"(generation {trainer.generation})"
            )
        x, y = next(iter(ds.batches(batch_rows(trainer.trainer), 1,
                                    seed_offset=step)))
        m = trainer.train_step(x, y)
        if step % 5 == 0 or set(trainer.member_nodes) != live:
            print(
                f"step {m.step}: loss={m.loss:.4f} "
                f"contributors={m.contributors:.0f}"
            )
    print(f"done: {args.steps} steps, final generation {trainer.generation}")
    return 0


def _cmd_train_moe(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-moe",
        description="MoE LM with expert parallelism: DP x EP over a "
        "(data, expert) mesh, or DP x SP x EP with --sp (no analog in the "
        "reference — SURVEY.md §3)",
    )
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--dp", type=int, default=None, help="data-parallel rows")
    p.add_argument("--ep", type=int, default=1, help="expert-parallel shards")
    p.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel shards (3-axis data x seq x expert mesh)",
    )
    p.add_argument(
        "--impl", choices=("ring", "ulysses"), default="ring",
        help="attention schedule over the seq axis (with --sp > 1)",
    )
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument(
        "--mu-bf16",
        action="store_true",
        help="adam first moment in bf16: halves the biggest traffic "
        "stream of the all-expert optimizer update (BENCHMARKS.md round "
        "4); the variance stays f32",
    )
    p.add_argument(
        "--topk", type=int, choices=(1, 2), default=1,
        help="router: 1 = Switch top-1, 2 = GShard top-2",
    )
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument(
        "--kv-heads", type=int, default=None,
        help="grouped-query attention: K/V heads (divides --heads; 1 = MQA)",
    )
    p.add_argument("--layers", type=int, default=2)
    p.add_argument(
        "--dispatch", choices=("auto", "einsum", "scatter"), default="auto",
        help="token->expert data movement: one-hot einsums or "
        "scatter/gather (auto: scatter past ~4M one-hot elements)",
    )
    p.add_argument(
        "--device-data",
        action="store_true",
        help="sample batches ON DEVICE inside one jitted chain (no host "
        "I/O per step)",
    )
    _add_sharded_compress_flag(p)
    _compile_cache_flag(p)
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)

    import jax
    import jax.numpy as jnp

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.parallel import data_seq_model_mesh
    from akka_allreduce_tpu.train import MoETrainer

    devs = jax.devices()
    dp = args.dp or max(1, len(devs) // (args.ep * args.sp))
    if args.sp > 1:
        mesh = data_seq_model_mesh(
            dp, args.sp, args.ep, axes=("data", "seq", "expert")
        )
    elif args.ep > 1:
        mesh = jax.make_mesh(
            (dp, args.ep), ("data", "expert"), devices=devs[: dp * args.ep]
        )
    else:
        mesh = jax.make_mesh((dp,), ("data",), devices=devs[:dp])
    trainer = MoETrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=args.heads,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers,
        n_experts=args.experts,
        seq_len=args.seq_len,
        capacity_factor=args.capacity_factor,
        router_topk=args.topk,
        seq_impl=args.impl,
        learning_rate=args.lr,
        compress=args.compress,
        overlap=args.overlap,
        dispatch_impl=args.dispatch,
        mu_dtype=jnp.bfloat16 if args.mu_bf16 else None,
    )
    print(
        f"MoE params: {trainer.param_count / 1e6:.2f}M "
        f"({args.experts} experts), mesh dp={trainer.dp} x sp={trainer.sp} "
        f"x ep={trainer.ep}"
    )
    if args.steps <= 0:
        return 0
    ds = data.lm_copy_task(args.seq_len, vocab=args.vocab)
    import time

    t0 = time.perf_counter()
    if args.device_data:
        # the chain draws one stream per (data, expert) COORDINATE — seq
        # shards of a coordinate share its rows — so the global batch
        # divides by dp*ep, not n_devices
        coords = trainer.dp * trainer.ep
        rows = max(1, args.batch // coords)
        eff_batch = rows * coords
        if eff_batch != args.batch:
            print(
                f"--device-data: global batch rounded {args.batch} -> "
                f"{eff_batch} ({rows} rows per data x expert coordinate)"
            )
        hist = trainer.train_chain(
            ds.device_sampler(), args.steps, rows_per_device=rows
        )
    else:
        hist = [
            trainer.train_step(x, y)
            for x, y in ds.batches(args.batch, args.steps)
        ]
    dt = time.perf_counter() - t0
    mode = "on-device " if args.device_data else ""
    from akka_allreduce_tpu.utils.benchmarking import (
        moe_active_params,
        transformer_train_flops,
    )

    eff = rows * trainer.dp * trainer.ep if args.device_data else args.batch
    perf = _mfu_fields(
        transformer_train_flops(
            n_params=moe_active_params(
                trainer.params, args.topk, args.experts
            ),
            batch=eff, seq=args.seq_len,
            d_model=args.d_model, n_layers=args.layers,
        ),
        dt / args.steps,
        trainer.n_devices,
    )
    print(
        f"moe: {args.steps} {mode}steps on {trainer.n_devices} devices in "
        f"{dt:.2f}s ({dt / args.steps * 1e3:.1f} ms/step)"
        f"{_mfu_note(perf)}; "
        f"loss {hist[0].loss:.4f} -> {hist[-1].loss:.4f} "
        f"(aux {hist[-1].aux_loss:.3f}, dropped {hist[-1].dropped:.1%})"
    )
    return 0


def _cmd_train_pp(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "train-pp",
        description="pipeline-parallel Transformer LM: DP x PP over a "
        "(data, pipe) mesh, GPipe microbatching in one jitted SPMD program "
        "(no analog in the reference — SURVEY.md §3)",
    )
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--dp", type=int, default=None, help="data-parallel rows")
    p.add_argument("--pp", type=int, default=2, help="pipeline stages")
    p.add_argument("--layers-per-stage", type=int, default=1)
    p.add_argument("--microbatches", type=int, default=2)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument(
        "--device-data",
        action="store_true",
        help="sample batches ON DEVICE inside one jitted chain (no host "
        "I/O per step)",
    )
    p.add_argument(
        "--remat",
        action="store_true",
        help="rematerialize each layer on backward (jax.checkpoint): "
        "stage activation memory drops from layers_per_stage to 1 layer",
    )
    p.add_argument(
        "--schedule",
        choices=("gpipe", "1f1b", "interleaved"),
        default="gpipe",
        help="pipeline schedule: gpipe holds O(microbatches) activations "
        "in flight (AD through the tick scan); 1f1b interleaves each "
        "micro's backward right behind its forward, holding O(stages) — "
        "same numerics (tests/test_pipeline.py), the standard memory fix; "
        "interleaved adds --virtual chunks per stage (Megatron virtual "
        "pipeline) so the fill/drain bubble is paid in 1/virtual-sized "
        "chunk ticks",
    )
    p.add_argument(
        "--virtual", type=int, default=1,
        help="virtual chunks per stage for --schedule interleaved "
        "(layers-per-stage must divide by it)",
    )
    _add_sharded_compress_flag(p)
    _compile_cache_flag(p)
    args = p.parse_args(argv)
    _maybe_enable_compile_cache(args)
    import jax

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.train import PipelineLMTrainer

    devs = jax.devices()
    dp = args.dp or max(1, len(devs) // args.pp)
    mesh = jax.make_mesh(
        (dp, args.pp), ("data", "pipe"), devices=devs[: dp * args.pp]
    )
    try:
        # pure flag validation only — internal construction errors keep
        # their tracebacks; flag mistakes become argparse usage errors
        PipelineLMTrainer.validate_flags(
            schedule=args.schedule,
            virtual_chunks=args.virtual,
            layers_per_stage=args.layers_per_stage,
            overlap=args.overlap,
        )
    except ValueError as e:
        p.error(str(e))
    trainer = PipelineLMTrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=args.heads,
        layers_per_stage=args.layers_per_stage,
        microbatches=args.microbatches,
        seq_len=args.seq_len,
        learning_rate=args.lr,
        remat=args.remat,
        compress=args.compress,
        overlap=args.overlap,
        schedule=args.schedule,
        virtual_chunks=args.virtual,
    )
    sched = args.schedule + (
        f" v={args.virtual}" if args.schedule == "interleaved" else ""
    )
    print(
        f"PP params: {trainer.param_count / 1e6:.2f}M "
        f"({trainer.n_layers} layers), mesh dp={trainer.dp} x "
        f"pp={trainer.stages}, {args.microbatches} microbatches "
        f"({sched})"
    )
    if args.steps <= 0:
        return 0
    ds = data.lm_copy_task(args.seq_len, vocab=args.vocab)
    import time

    t0 = time.perf_counter()
    if args.device_data:
        # round rows per replica UP to a whole number of microbatches
        rows = max(1, args.batch // trainer.dp)
        rows = -(-rows // args.microbatches) * args.microbatches
        eff_batch = rows * trainer.dp
        if eff_batch != args.batch:
            print(
                f"--device-data: global batch rounded {args.batch} -> "
                f"{eff_batch} ({rows} rows/replica, whole microbatches)"
            )
        hist = trainer.train_chain(
            ds.device_sampler(), args.steps, rows_per_replica=rows
        )
    else:
        hist = [
            trainer.train_step(x, y)
            for x, y in ds.batches(args.batch, args.steps)
        ]
    dt = time.perf_counter() - t0
    mode = "on-device " if args.device_data else ""
    from akka_allreduce_tpu.utils.benchmarking import transformer_train_flops

    eff = rows * trainer.dp if args.device_data else args.batch
    perf = _mfu_fields(
        transformer_train_flops(
            n_params=trainer.param_count, batch=eff, seq=args.seq_len,
            d_model=args.d_model, n_layers=trainer.n_layers,
        ),
        dt / args.steps,
        trainer.n_devices,
    )
    print(
        f"pp: {args.steps} {mode}steps on {trainer.n_devices} devices in "
        f"{dt:.2f}s ({dt / args.steps * 1e3:.1f} ms/step)"
        f"{_mfu_note(perf)}; "
        f"loss {hist[0].loss:.4f} -> {hist[-1].loss:.4f}"
    )
    return 0


def _cmd_lm_generate(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "lm-generate",
        description="KV-cache autoregressive decoding (models/generate.py): "
        "optionally train on the copy task, then generate and report "
        "decode tokens/s (slope between two generation lengths)",
    )
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument(
        "--kv-heads", type=int, default=None,
        help="GQA: shrink the KV cache (B, L, H_kv, D) by heads/kv_heads",
    )
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument(
        "--gen", type=int, default=64,
        help="tokens to generate (>= 2: the slope timing needs two lengths)",
    )
    p.add_argument(
        "--train-steps", type=int, default=0,
        help="on-device copy-task training steps before decoding "
        "(0 = random params; >0 shows real text completion)",
    )
    p.add_argument("--seq-len", type=int, default=64, help="training seq len")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--bf16", action="store_true")
    p.add_argument(
        "--cache-quant", choices=("int8",), default=None,
        help="quantize the KV cache to int8 + per-row scales (4x fewer "
        "cache bytes than f32; ~0.4%% per-element error)",
    )
    p.add_argument(
        "--sp", type=int, default=1,
        help="sequence-sharded decode: shard the KV cache's SLOT dim over "
        "an sp-device 'seq' mesh axis (split-K partial-softmax merge — "
        "caches larger than one device)",
    )
    p.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel decode over a 'model' mesh axis (composes "
        "with --sp)",
    )
    args = p.parse_args(argv)
    if args.gen < 2:
        p.error("--gen must be >= 2 (the slope timing needs two lengths)")
    if args.sp < 1 or args.tp < 1:
        p.error("--sp and --tp must be >= 1")

    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from akka_allreduce_tpu.models import LMGenerator, TransformerLM
    from akka_allreduce_tpu.models.data import SyntheticCopyLM

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = TransformerLM(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_kv_heads=args.kv_heads, n_layers=args.layers, compute_dtype=dtype,
    )
    ds = SyntheticCopyLM(args.seq_len, vocab=args.vocab)
    if args.train_steps > 0:
        import optax

        from akka_allreduce_tpu.parallel import data_seq_mesh
        from akka_allreduce_tpu.train import LongContextTrainer

        trainer = LongContextTrainer(
            data_seq_mesh(1, 1), vocab=args.vocab, d_model=args.d_model,
            n_heads=args.heads, n_kv_heads=args.kv_heads,
            n_layers=args.layers, seq_len=args.seq_len,
            compute_dtype=dtype, optimizer=optax.adam(3e-3),
        )
        hist = trainer.train_chain(
            ds.device_sampler(), args.train_steps, args.batch
        )
        print(
            f"trained {args.train_steps} steps: loss "
            f"{hist[0].loss:.3f} -> {hist[-1].loss:.3f}"
        )
        params = jax.device_get(trainer.params)
    else:
        params = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, args.prompt_len), jnp.int32),
        )

    mesh = None
    if args.sp > 1 or args.tp > 1:
        shape, names = (), ()
        if args.sp > 1:
            shape, names = shape + (args.sp,), names + ("seq",)
        if args.tp > 1:
            shape, names = shape + (args.tp,), names + ("model",)
        mesh = jax.make_mesh(
            shape, names, devices=jax.devices()[: args.sp * args.tp]
        )
    max_len = args.prompt_len + args.gen
    max_len = -(-max_len // args.sp) * args.sp  # whole slots per seq shard
    gen = LMGenerator(
        model, max_len=max_len, cache_quant=args.cache_quant, mesh=mesh,
    )
    if mesh is not None:
        params = gen.place_params(params)
    x, _ = next(ds.batches(args.batch, 1, seed_offset=123))
    prompt = jnp.asarray(x[:, : args.prompt_len])

    # decode throughput: slope between a short and the full generation so
    # prefill + dispatch overhead cancels (bench.py's discipline)
    import statistics

    lo = max(1, args.gen // 4)
    gen.generate(params, prompt, lo, temperature=args.temperature)
    out = gen.generate(
        params, prompt, args.gen, temperature=args.temperature
    )  # compile both

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        o = gen.generate(
            params, prompt, steps, temperature=args.temperature,
            seed=int(t0 * 1e6) % (1 << 30),  # vary input (axon trap)
        )
        jax.device_get(o[:1, -1])  # real fence (block_until_ready is not)
        return time.perf_counter() - t0

    slopes = [
        (timed(args.gen) - timed(lo)) / (args.gen - lo) for _ in range(5)
    ]
    ms_per_tok = statistics.median(slopes) * 1e3
    out_np = np.asarray(out)
    print(f"prompt : {np.asarray(prompt)[0].tolist()}")
    print(f"decoded: {out_np[0].tolist()}")
    half = args.seq_len // 2
    if args.train_steps > 0 and args.prompt_len == half + 1:
        # prompt ends at position half, so greedy decode should emit the
        # copy x[1:half] (the copy task repeats [0, half) at [half, 2half))
        want = x[0, 1:half][: out_np.shape[1]]
        got = out_np[0][: len(want)]
        acc = float((got == want).mean()) if len(want) else 0.0
        print(f"copy accuracy vs source: {acc:.1%}")
    if ms_per_tok > 1e-3:
        rate = f"{args.batch * 1e3 / ms_per_tok:.0f} tokens/s"
    else:
        rate = "n/a (noise-dominated at this size)"
    qnote = f" {args.cache_quant}-quantized" if args.cache_quant else ""
    print(
        f"decode: {ms_per_tok:.2f} ms/token, {rate} "
        f"(batch {args.batch}, cache (B,{gen.max_len},"
        f"{args.kv_heads or args.heads},{args.d_model // args.heads})"
        f"{qnote})"
    )
    return 0


def _cmd_bench_checkpoint(argv: list[str]) -> int:
    """Measure checkpoint stall: sync save wall time (the step loop is
    frozen for all of it) vs async save (steps keep ticking while the
    on-device copy drains to host and Orbax writes off-thread)."""
    p = argparse.ArgumentParser(
        "bench-checkpoint",
        description="step-loop stall of sync vs async checkpointing on a "
        "transformer LM (VERDICT r3 #2: checkpoint cost is part of the "
        "recovery story)",
    )
    p.add_argument("--d-model", type=int, default=2048)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=None, help="default d/128")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--bf16", action="store_true")
    p.add_argument(
        "--trainer", choices=("lm", "fsdp", "zero1", "pipeline"),
        default="lm",
        help="trainer family under test: the sharded-state families "
        "(fsdp/zero1/pipeline) exercise the shard-local async capture "
        "path (VERDICT r4 #1)",
    )
    p.add_argument(
        "--store", choices=("orbax", "delta"), default="orbax",
        help="delta: content-addressed per-leaf store (async hashing)",
    )
    p.add_argument(
        "--remat", choices=("full", "params"), default=None,
        help="fsdp only: rematerialization mode (the flagship size OOMs "
        "one chip without it — same flag as bench-mfu)",
    )
    p.add_argument("--baseline-steps", type=int, default=5)
    p.add_argument("--max-steps-during", type=int, default=200)
    p.add_argument("--dir", default=None, help="default: a temp dir")
    p.add_argument("--skip-sync", action="store_true",
                   help="skip the (slow) synchronous-save comparison")
    args = p.parse_args(argv)

    import json
    import statistics
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.parallel import data_seq_mesh, line_mesh
    from akka_allreduce_tpu.train import (
        AsyncDeltaCheckpointer,
        AsyncTrainerCheckpointer,
        DeltaCheckpointer,
        FSDPLMTrainer,
        LongContextTrainer,
        PipelineLMTrainer,
        TrainerCheckpointer,
        Zero1DPTrainer,
    )

    heads = args.heads or max(1, args.d_model // 128)
    lm_kw = dict(
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
    )
    n_dev = len(jax.devices())
    if args.trainer == "lm":
        trainer = LongContextTrainer(
            data_seq_mesh(1, 1), learning_rate=1e-3, **lm_kw
        )
    elif args.trainer == "fsdp":
        trainer = FSDPLMTrainer(
            line_mesh(n_dev), remat=args.remat or False, **lm_kw
        )
    elif args.trainer == "pipeline":
        pp = n_dev  # all devices as stages (1 on the real chip)
        pp_kw = dict(lm_kw)
        pp_kw.pop("n_layers")
        trainer = PipelineLMTrainer(
            jax.make_mesh((1, pp), ("data", "pipe")),
            layers_per_stage=-(-args.layers // pp),
            microbatches=2,
            learning_rate=1e-3,
            **pp_kw,
        )
    else:  # zero1: MLP classification family, width scaled by --d-model
        import optax

        from akka_allreduce_tpu.models import MLP

        trainer = Zero1DPTrainer(
            MLP(hidden=(args.d_model,) * args.layers, classes=10),
            line_mesh(n_dev),
            example_input=np.zeros((1, 28, 28, 1), np.float32),
            optimizer=optax.adam(1e-3),
        )
    state_gb = trainer.param_count * 4 * 3 / 1e9  # f32 params + adam mu/nu
    # round the batch up to what the family's data placement divides by
    # (fsdp/zero1 spread rows over all devices; pipeline needs microbatches)
    div = {"fsdp": n_dev, "zero1": n_dev, "pipeline": 2}.get(args.trainer, 1)
    batch = -(-args.batch // div) * div
    if args.trainer == "zero1":
        ds = data.mnist_like()
        batches = ds.batches(batch, 10_000)
    else:
        ds = data.lm_copy_task(args.seq_len, vocab=args.vocab)
        batches = ds.batches(batch, 10_000)

    def step():
        t0 = time.perf_counter()
        trainer.train_step(*next(batches))  # loss float = device sync
        return time.perf_counter() - t0

    step()  # compile
    base = [step() for _ in range(args.baseline_steps)]
    base_ms = statistics.median(base) * 1e3

    # always a FRESH subdir: re-running against an existing directory would
    # hit the step-dedup early return and measure no save at all
    d = tempfile.mkdtemp(prefix="ckpt_bench_", dir=args.dir)
    sync_cls, async_cls = (
        (DeltaCheckpointer, AsyncDeltaCheckpointer)
        if args.store == "delta"
        else (TrainerCheckpointer, AsyncTrainerCheckpointer)
    )
    sync_s = None
    if not args.skip_sync:
        with sync_cls(f"{d}/sync") as ck:
            t0 = time.perf_counter()
            ck.save(trainer)
            sync_s = time.perf_counter() - t0

    delta_stats = None
    with async_cls(f"{d}/async") as ck:
        t0 = time.perf_counter()
        ck.save(trainer)
        capture_s = time.perf_counter() - t0  # the only stall the loop sees
        during = []
        while ck.busy() and len(during) < args.max_steps_during:
            during.append(step())
        stepped_s = time.perf_counter() - t0
        ck.wait_until_finished()
        # true background-save duration — past the step cap the loop just
        # waits, so this can exceed stepped_s
        save_wall_s = time.perf_counter() - t0
        saved_step = ck.latest_step()
        delta_stats = getattr(ck, "last_stats", None)
    during_ms = statistics.median(during) * 1e3 if during else None
    rec = {
        "metric": "checkpoint_stall",
        "trainer": args.trainer,
        "store": args.store,
        "delta_stats": delta_stats,
        "params_m": round(trainer.param_count / 1e6, 1),
        "state_gb": round(state_gb, 2),
        "baseline_ms_per_step": round(base_ms, 1),
        "async_capture_stall_s": round(capture_s, 3),
        "async_save_wall_s": round(save_wall_s, 1),
        "steps_during_async_save": len(during),
        "ms_per_step_during_save": (
            round(during_ms, 1) if during_ms is not None else None
        ),
        "sync_save_stall_s": round(sync_s, 1) if sync_s is not None else None,
        "saved_step": saved_step,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(rec))
    return 0


def _cmd_soak(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        "soak",
        description="the everything-on endurance run (VERDICT r4 #3): "
        "flagship FSDP LM + elastic membership churn + async "
        "checkpointing + a mid-run restore, unattended; prints per-event "
        "lines and one summary JSON",
    )
    p.add_argument("--steps", type=int, default=1000)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--d-model", type=int, default=2048)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=None, help="default d/128")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--batch-per-replica", type=int, default=2)
    p.add_argument("--f32", action="store_true", help="disable bf16 compute")
    p.add_argument(
        "--remat", choices=("full", "params", "none"), default="params"
    )
    p.add_argument("--no-prefetch", action="store_true")
    p.add_argument(
        "--compress", choices=("bf16", "int8", "none"), default="int8"
    )
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--drop-at", type=int, default=None)
    p.add_argument("--rejoin-at", type=int, default=None)
    p.add_argument("--restore-at", type=int, default=None)
    p.add_argument(
        "--chaos", type=int, default=None, metavar="SEED",
        help="seeded membership chaos: replace the single scripted "
        "drop/rejoin with deterministic random silence windows per node "
        "(node 0 never flaps); the same seed replays the same churn",
    )
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument(
        "--delta-checkpoint", action="store_true",
        help="async delta store instead of async Orbax",
    )
    p.add_argument(
        "--peer-restore", action="store_true",
        help="requires --delta-checkpoint: replicate every completed delta "
        "save into a replica chunk store, WIPE the local store at the "
        "mid-run restore (disk loss), and rebuild it chunk-verified from "
        "the replica — the report's restore.source reads 'peer' and the "
        "disk-vs-peer A/B is one JSON field (RESILIENCE.md 'Recovery')",
    )
    p.add_argument("--metrics-out", default=None)
    args = p.parse_args(argv)
    if args.peer_restore and not args.delta_checkpoint:
        p.error("--peer-restore replicates delta chunks; add --delta-checkpoint")
    if args.remat == "full" and not args.no_prefetch:
        p.error(
            "--remat full excludes prefetch (the prefetched layer rides "
            "the scan carry remat exists to drop): add --no-prefetch"
        )

    import json

    from akka_allreduce_tpu.soak import run_soak

    report = run_soak(
        steps=args.steps,
        nodes=args.nodes,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=args.heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        batch_per_replica=args.batch_per_replica,
        bf16=not args.f32,
        remat=False if args.remat == "none" else args.remat,
        prefetch=not args.no_prefetch,
        compress=None if args.compress == "none" else args.compress,
        learning_rate=args.lr,
        drop_at=args.drop_at,
        rejoin_at=args.rejoin_at,
        restore_at=args.restore_at,
        chaos_seed=args.chaos,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        delta=args.delta_checkpoint,
        peer_restore=args.peer_restore,
        metrics_out=args.metrics_out,
    )
    print(json.dumps(report.as_dict()))
    return 0


def _drill_spawn(env):
    """Subprocess factory shared by the chaos drills — ONE parent python
    owns every role (separate shell jobs may land in isolated sandbox
    network namespaces and never reach each other's loopback ports)."""
    import subprocess

    def spawn(*cli):
        return subprocess.Popen(
            [sys.executable, "-m", "akka_allreduce_tpu", *cli],
            env=env, stdout=subprocess.PIPE, text=True,
        )

    return spawn


def _drill_pump(proc, into: list):
    """Drain a drill subprocess's stdout into ``into`` from a daemon
    thread (shared by the drills that watch for marker lines — TAKEOVER,
    RESTORE — while the process keeps running)."""
    import threading

    t = threading.Thread(target=lambda: into.extend(proc.stdout), daemon=True)
    t.start()
    return t


def _add_drill_gossip_flags(p: argparse.ArgumentParser) -> None:
    """Every chaos drill can run its cluster under SWIM gossip membership
    instead of hub heartbeats (the Makefile pins --gossip on all of them,
    like --streams 2): the drills then prove their scenario survives the
    decentralized detector too."""
    p.add_argument(
        "--gossip", action="store_true",
        help="arm SWIM gossip membership on the drill's cluster "
        "(distributed via Welcome, RESILIENCE.md 'Tier 6')",
    )
    p.add_argument(
        "--gossip-interval", type=float, default=0.25, metavar="S",
        help="gossip probe period for the drill cluster",
    )


def _drill_gossip_args(args) -> list[str]:
    """Extra cluster-master CLI args for a drill's master spawn."""
    if not getattr(args, "gossip", False):
        return []
    return [
        "--gossip", "--gossip-interval",
        str(getattr(args, "gossip_interval", 0.25)),
    ]


def _add_drill_lever_flags(p: argparse.ArgumentParser) -> None:
    """Every chaos drill can arm the data plane v3 levers on its cluster
    (the Makefile pins all three, like --streams 2 and --gossip): the
    drills then prove their scenario survives the levered plane too. With
    --streams 2 the intra-chunk split is inert by construction (one
    payload stream — nothing to split across), but the knob distribution,
    scheduler, and uring probe/fallback paths all run."""
    p.add_argument(
        "--uring", action="store_true",
        help="arm io_uring burst submission on the drill's cluster",
    )
    p.add_argument(
        "--intra-chunk", type=int, default=0, metavar="BYTES",
        dest="intra_chunk",
        help="arm intra-chunk striping at this byte bar (0 = off)",
    )
    p.add_argument(
        "--congestion", action="store_true",
        help="arm congestion-aware stripe scheduling",
    )


def _drill_lever_args(args) -> list[str]:
    """Extra cluster-master CLI args arming the v3 levers for a drill."""
    out: list[str] = []
    if getattr(args, "uring", False):
        out.append("--uring")
    bar = getattr(args, "intra_chunk", 0)
    if bar:
        out += ["--intra-chunk", str(bar)]
    if getattr(args, "congestion", False):
        out.append("--congestion")
    return out


def _drill_jsonl_records(path):
    """Records of a (possibly live) metrics JSONL — the ONE torn-tolerant
    reader every drill scan goes through: blank lines and the in-progress
    writer's torn last line are skipped, never a traceback."""
    import json
    import os

    if not os.path.exists(path):
        return
    with open(path) as f:
        for ln in f:
            if not ln.strip():
                continue
            try:
                yield json.loads(ln)
            except ValueError:
                continue  # the writer is mid-append


def _drill_full_rounds(path, workers: int) -> int:
    """Completed line-rounds with FULL membership recorded in a master's
    metrics JSONL — recovery progress only counts when every node is back
    in the line."""
    return sum(
        1
        for rec in _drill_jsonl_records(path)
        if rec.get("kind") == "round" and rec.get("workers") == workers
    )


def _drill_phase_waiter(timeout_s: float, failures: list):
    """``await_phase(pred, what)`` with one shared timeout/report shape."""

    def await_phase(pred, what: str) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.2)
        failures.append(f"timed out waiting for {what}")
        return False

    return await_phase


def _cmd_bench_wire(argv: list[str]) -> int:
    """Deterministic host data-plane microbench (``make bench-wire``):
    per-core codec throughput (encode+checksum / decode+verify) and the
    syscall-batching comparison (one ``sendmsg`` per frame vs one
    ``sendmmsg`` per burst, plus the recv side) over loopback TCP. The
    pair-cluster A/B (BENCHMARKS.md round 8) measures the system; this
    measures the LEVERS, on a box whose run-to-run drift would otherwise
    drown them — legs are interleaved and the medians reported."""
    p = argparse.ArgumentParser(
        "bench-wire",
        description="wire codec + batch-syscall microbench (JSON output)",
    )
    p.add_argument(
        "--size", type=int, default=4096,
        help="floats per payload frame (default 16KB frames — small "
        "enough that per-syscall overhead is visible)",
    )
    p.add_argument("--frames", type=int, default=64, help="frames per burst")
    p.add_argument("--reps", type=int, default=9, help="interleaved reps/leg")
    p.add_argument("--json", action="store_true", help="print the JSON record")
    p.add_argument("--out", default=None, help="append the JSON record here")
    # data plane v3 per-lever A/Bs (BENCHMARKS.md round 9): each flag runs
    # its lever's leg and emits ONE extra JSON record, so `make bench-wire`
    # reproduces every A/B in one command
    p.add_argument(
        "--uring", action="store_true",
        help="A/B io_uring burst submission vs sendmmsg (or record the "
        "runtime probe's fallback reason on a kernel without io_uring)",
    )
    p.add_argument(
        "--intra-chunk", action="store_true", dest="intra_chunk",
        help="A/B a ONE-chunk round (one giant frame) on one stream vs "
        "split across payload streams, over per-stream-paced loopback "
        "drains (the per-connection bandwidth-ceiling model)",
    )
    p.add_argument(
        "--congestion", action="store_true",
        help="run the stripe scheduler's shed/restore simulation under a "
        "fake clock (deterministic: the record includes the replay check)",
    )
    args = p.parse_args(argv)

    import json
    import socket
    import statistics
    import threading

    import numpy as np

    from akka_allreduce_tpu import native
    from akka_allreduce_tpu.control import wire
    from akka_allreduce_tpu.protocol import ScatterBlock

    rng = np.random.default_rng(7)
    payloads = [
        rng.standard_normal(args.size).astype(np.float32)
        for _ in range(args.frames)
    ]
    msgs = [
        ScatterBlock(v, 0, 1, i, 1) for i, v in enumerate(payloads)
    ]
    dest = "worker:1"
    payload_bytes = args.size * 4 * args.frames

    def timed(fn) -> float:
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    # -- codec legs (pure compute, no sockets) --------------------------------
    def leg_encode() -> None:
        for m in msgs:
            wire.encode_frame_parts(dest, m)

    frames_bytes = [b"".join(wire.encode_frame_parts(dest, m)) for m in msgs]

    def leg_decode() -> None:
        for f in frames_bytes:
            wire.decode_frame_body_ex(memoryview(f)[4:])

    def leg_checksum() -> None:
        for v in payloads:
            native.wire_checksum(memoryview(v).cast("B"))

    codec: dict[str, list[float]] = {"encode": [], "decode": [], "checksum": []}
    for _ in range(args.reps):
        codec["encode"].append(timed(leg_encode))
        codec["decode"].append(timed(leg_decode))
        codec["checksum"].append(timed(leg_checksum))

    # -- syscall legs: loopback TCP, a drain thread on the far end ------------
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    tx = socket.create_connection(srv.getsockname())
    rx, _ = srv.accept()
    srv.close()
    tx.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    for sk in (tx, rx):
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                sk.setsockopt(socket.SOL_SOCKET, opt, 8 << 20)
            except OSError:
                pass
    stop = threading.Event()

    def drain() -> None:
        sink = bytearray(1 << 20)
        while not stop.is_set():
            try:
                if not rx.recv_into(sink):
                    return
            except OSError:
                return

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()

    frame_views = [
        [memoryview(f)] for f in frames_bytes
    ]  # one message per frame, rebuilt per send below

    def send_all(batched: bool, force_fallback: bool = False) -> None:
        frames = [list(f) for f in frame_views]
        if batched:
            while frames:
                n = native.batch_send(
                    tx.fileno(), frames, force_fallback=force_fallback
                )
                # advance past sent bytes
                while n and frames:
                    head = frames[0]
                    while n and head:
                        seg = head[0]
                        if n >= len(seg):
                            n -= len(seg)
                            head.pop(0)
                        else:
                            head[0] = seg[n:]
                            n = 0
                    if not head:
                        frames.pop(0)
            return
        for f in frames:  # one syscall per frame: the un-batched baseline
            views = list(f)
            while views:
                n = tx.sendmsg(views)
                while n and views:
                    seg = views[0]
                    if n >= len(seg):
                        n -= len(seg)
                        views.pop(0)
                    else:
                        views[0] = seg[n:]
                        n = 0

    have_native = native.batch_send_available()
    have_mmsg = native.sendmmsg_available()
    sysc: dict[str, list[float]] = {
        "sendmsg_loop": [], "sendmmsg": [], "sendmmsg_fallback": [],
    }
    for _ in range(args.reps):  # interleaved: noise hits every leg alike
        sysc["sendmsg_loop"].append(timed(lambda: send_all(False)))
        if have_native:
            sysc["sendmmsg"].append(timed(lambda: send_all(True)))
            sysc["sendmmsg_fallback"].append(
                timed(lambda: send_all(True, force_fallback=True))
            )
    stop.set()
    tx.close()
    rx.close()
    drainer.join(timeout=2.0)

    # -- recv legs: recvmmsg batch vs recv loop over a pre-pumped stream ------
    recv: dict[str, list[float]] = {"recv_loop": [], "recvmmsg": []}
    if have_native:
        chunk = 64 << 10
        nbufs = 16
        bufs = [bytearray(chunk) for _ in range(nbufs)]
        total = payload_bytes

        def recv_bench(batched: bool) -> float:
            a = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            a.bind(("127.0.0.1", 0))
            a.listen(1)
            c = socket.create_connection(a.getsockname())
            b, _ = a.accept()
            a.close()
            blob = b"\x00" * total

            def pump() -> None:
                try:
                    c.sendall(blob)
                finally:
                    c.close()

            th = threading.Thread(target=pump, daemon=True)
            th.start()
            got = 0
            t0 = time.perf_counter()
            while got < total:
                if batched:
                    n = native.batch_recv(b.fileno(), bufs)
                else:
                    n = b.recv_into(bufs[0])
                if n <= 0:
                    break
                got += n
            dt = time.perf_counter() - t0
            b.close()
            th.join(timeout=2.0)
            return dt

        for _ in range(args.reps):
            recv["recv_loop"].append(recv_bench(False))
            recv["recvmmsg"].append(recv_bench(True))

    def mbps(times: list[float]) -> float | None:
        if not times:
            return None
        return round(payload_bytes / statistics.median(times) / 1e6, 1)

    record = {
        "bench": "wire",
        "size_floats": args.size,
        "frames": args.frames,
        "reps": args.reps,
        "native_loaded": native.loaded(),
        "sendmmsg_available": have_mmsg,
        "encode_mbps": mbps(codec["encode"]),
        "decode_mbps": mbps(codec["decode"]),
        "checksum_mbps": mbps(codec["checksum"]),
        "sendmsg_loop_mbps": mbps(sysc["sendmsg_loop"]),
        "sendmmsg_mbps": mbps(sysc["sendmmsg"]),
        "sendmmsg_fallback_mbps": mbps(sysc["sendmmsg_fallback"]),
        "recv_loop_mbps": mbps(recv["recv_loop"]),
        "recvmmsg_mbps": mbps(recv["recvmmsg"]),
    }
    records = [record]
    if args.uring:
        records.append(_bench_wire_uring(args, frames_bytes, payload_bytes))
    if args.intra_chunk:
        records.append(_bench_wire_intra_chunk(args))
    if args.congestion:
        records.append(_bench_wire_congestion())
    out_lines = [json.dumps(r, sort_keys=True) for r in records]
    if args.out:
        with open(args.out, "a") as f:
            for line in out_lines:
                f.write(line + "\n")
    if args.json or not args.out:
        for line in out_lines:
            print(line)
    return 0


def _bench_wire_uring(args, frames_bytes, payload_bytes) -> dict:
    """Lever (a): io_uring burst submission vs the sendmmsg batch — same
    frame mix, same loopback drain, interleaved legs. On a kernel without
    io_uring the record carries the probe's fallback reason instead of a
    number: the lever's honest state on this box."""
    import socket
    import statistics
    import threading

    from akka_allreduce_tpu import native

    rec: dict = {
        "bench": "wire",
        "lever": "uring",
        "uring_available": native.uring_available(),
        "uring_probe_reason": native.uring_probe_reason(),
        "uring_mbps": None,
        "sendmmsg_mbps": None,
    }
    if not native.uring_available() or not native.batch_send_available():
        return rec
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    tx = socket.create_connection(srv.getsockname())
    rx, _ = srv.accept()
    srv.close()
    tx.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    stop = threading.Event()

    def drain() -> None:
        sink = bytearray(1 << 20)
        while not stop.is_set():
            try:
                if not rx.recv_into(sink):
                    return
            except OSError:
                return

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()
    ring = native.UringRing()

    def advance(frames: list, n: int) -> None:
        while n and frames:
            head = frames[0]
            while n and head:
                seg = head[0]
                if n >= len(seg):
                    n -= len(seg)
                    head.pop(0)
                else:
                    head[0] = seg[n:]
                    n = 0
            if not head:
                frames.pop(0)

    def send_all(use_uring: bool) -> None:
        frames = [[memoryview(f)] for f in frames_bytes]
        while frames:
            if use_uring:
                flat = [v for fr in frames for v in fr]
                try:
                    n = ring.send(tx.fileno(), flat)
                except BlockingIOError:
                    continue
            else:
                n = native.batch_send(tx.fileno(), frames)
            advance(frames, n)

    times: dict[str, list[float]] = {"sendmmsg": [], "uring": []}
    try:
        for _ in range(args.reps):
            for key, flag in (("sendmmsg", False), ("uring", True)):
                t0 = time.perf_counter()
                send_all(flag)
                times[key].append(time.perf_counter() - t0)
    finally:
        ring.close()
        stop.set()
        tx.close()
        rx.close()
        drainer.join(timeout=2.0)
    for key in times:
        rec[f"{key}_mbps"] = round(
            payload_bytes / statistics.median(times[key]) / 1e6, 1
        )
    rec["uring_ge_sendmmsg"] = rec["uring_mbps"] >= rec["sendmmsg_mbps"]
    return rec


def _bench_wire_intra_chunk(args) -> dict:
    """Lever (b): a ONE-chunk round's bytes over one stream (what chunk-id
    striping does to a single-tensor allreduce or a state-transfer frame)
    vs split across 3 payload streams — over loopback connections whose
    drains are PACED to a fixed per-stream rate, the model of the real
    phenomenon (each TCP stream has a bandwidth ceiling; on loopback the
    kernel would otherwise hide it). The bytes are a real encoded frame,
    split at the same offsets the transport's splitter uses."""
    import socket
    import statistics
    import threading

    import numpy as np

    from akka_allreduce_tpu.control import wire
    from akka_allreduce_tpu.protocol import ScatterBlock

    n_payload = 3  # streams=4
    pace_mbps = 200.0  # per-stream drain ceiling
    read_chunk = 256 << 10
    value = np.random.default_rng(7).standard_normal(6_000_000).astype(
        np.float32
    )  # ~24 MB one-chunk frame
    body = b"".join(
        bytes(p) for p in wire.encode_frame_parts("worker:1", ScatterBlock(value, 0, 1, 0, 1))
    )

    def leg(n_streams: int) -> float:
        frag = -(-len(body) // n_streams)
        slices = [
            body[i * frag : (i + 1) * frag] for i in range(n_streams)
        ]
        pairs = []
        for _ in range(n_streams):
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            c = socket.create_connection(srv.getsockname())
            a, _ = srv.accept()
            srv.close()
            pairs.append((c, a))
        done = threading.Barrier(2 * n_streams + 1)

        def write(sock, blob) -> None:
            try:
                sock.sendall(blob)
            finally:
                done.wait()

        def drain(sock, want: int) -> None:
            sink = bytearray(read_chunk)
            got = 0
            budget = time.perf_counter()
            try:
                while got < want:
                    n = sock.recv_into(sink)
                    if not n:
                        break
                    got += n
                    # pace: this stream may not drain faster than the
                    # per-stream ceiling — sleep off any surplus
                    budget += n / (pace_mbps * 1e6)
                    now = time.perf_counter()
                    if budget > now:
                        time.sleep(budget - now)
            finally:
                done.wait()

        threads = []
        t0 = time.perf_counter()
        for (c, a), blob in zip(pairs, slices):
            threads.append(
                threading.Thread(target=write, args=(c, blob), daemon=True)
            )
            threads.append(
                threading.Thread(
                    target=drain, args=(a, len(blob)), daemon=True
                )
            )
        for t in threads:
            t.start()
        done.wait()
        dt = time.perf_counter() - t0
        for c, a in pairs:
            c.close()
            a.close()
        return dt

    single: list[float] = []
    striped: list[float] = []
    for _ in range(max(3, args.reps // 3)):
        single.append(leg(1))
        striped.append(leg(n_payload))
    s, m = statistics.median(single), statistics.median(striped)
    return {
        "bench": "wire",
        "lever": "intra_chunk",
        "model": f"per-stream drains paced at {pace_mbps:g} MB/s",
        "frame_mb": round(len(body) / 1e6, 1),
        "payload_streams": n_payload,
        "single_stream_s": round(s, 4),
        "striped_s": round(m, 4),
        "speedup": round(s / m, 2),
    }


def _bench_wire_congestion() -> dict:
    """Lever (c): the stripe scheduler's closed loop under a FAKE clock —
    a 3-stream endpoint where stream 2 drains at 15% (the chaos ``delay``
    shape), then heals. Deterministic by construction (no wall clock, no
    RNG): the record carries a replay check and the windows-to-shed the
    acceptance bar asks for."""
    from akka_allreduce_tpu.control.stripes import StripeScheduler

    degraded = 2
    frame = 1 << 20

    def run() -> tuple[list[float], dict]:
        sched = StripeScheduler(3)
        fair = 1.0 / 3.0
        shares: list[float] = []
        backlog = [0, 0, 0]  # the simulated sockets' unsent bytes
        windows_to_half = None
        restored_at = None
        for w in range(40):
            now = w * sched.window_s
            for _ in range(12):
                idx = sched.pick(frame, now)
                backlog[idx] += frame
            healed = w >= 20
            for i in range(3):
                # per-window drain capacity: healthy streams clear their
                # queue (backlog included — a healed stream catches up),
                # the degraded one moves 15% of a fair window
                cap = (16 << 20) if (i != degraded or healed) else int(
                    0.15 * (4 << 20)
                )
                sent = min(backlog[i], cap)
                backlog[i] -= sent
                sched.note_sent(i, sent, now)
            share = sched.share(degraded)
            shares.append(round(share, 4))
            if windows_to_half is None and share <= fair / 2.0:
                windows_to_half = w + 1
            if (
                windows_to_half is not None
                and restored_at is None
                and healed
                and share >= fair * 0.9
            ):
                restored_at = w + 1
        return shares, {
            "windows_to_half_share": windows_to_half,
            "restored_by_window": restored_at,
            "final_weights": sched.snapshot()["weights"],
            "sheds": sched.sheds,
            "restores": sched.restores,
        }

    shares_a, rec = run()
    shares_b, _ = run()
    return {
        "bench": "wire",
        "lever": "congestion",
        "degraded_stream": degraded,
        "share_trajectory": shares_a[:12],
        "deterministic": shares_a == shares_b,
        **rec,
    }


def _cmd_chaos(argv: list[str]) -> int:
    """Chaos harness: a real master + N node OS processes over loopback,
    every transport armed with the SAME seeded fault schedule (the master
    distributes the spec via Welcome), invariants summarized at the end.
    ``make chaos`` runs the fixed-seed 30-second variant (RESILIENCE.md)."""
    p = argparse.ArgumentParser(
        "chaos",
        description="run a tiny cluster under seeded fault injection and "
        "report what happened (chaos events vs rounds completed)",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument(
        "--spec",
        default="drop:p=0.05;delay:ms=10;corrupt:p=0.02",
        help="fault spec (see RESILIENCE.md for the grammar)",
    )
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument(
        "--rounds", type=int, default=50,
        help="line-round budget; ignored when --duration is set",
    )
    p.add_argument(
        "--duration", type=float, default=None,
        help="run open-ended for this many seconds instead of a round "
        "budget (the 30s soak `make chaos` uses)",
    )
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome); "
        "2 makes the drill exercise the multi-stream reassembly path "
        "under every injected fault",
    )
    p.add_argument("--out-dir", default="chaos_run")
    _add_drill_gossip_flags(p)
    _add_drill_lever_flags(p)
    args = p.parse_args(argv)
    # fail fast on a malformed spec BEFORE spawning anything — a parse
    # error inside the master subprocess would surface as an opaque
    # "never reported its endpoint" failure here
    from akka_allreduce_tpu.control.chaos import parse_spec

    try:
        parse_spec(args.spec)
    except ValueError as e:
        p.error(str(e))

    import json
    import os
    import signal as _signal
    import subprocess

    os.makedirs(args.out_dir, exist_ok=True)
    metrics_path = os.path.join(args.out_dir, "rounds.jsonl")
    master_log = os.path.join(args.out_dir, "chaos-master.jsonl")
    stale = [f for f in os.listdir(args.out_dir) if f.endswith(".jsonl")]
    for f in stale:  # MetricsLogger appends; never mix two runs' records
        os.remove(os.path.join(args.out_dir, f))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)
    rounds = -1 if args.duration else args.rounds
    wedged = False
    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", str(rounds), "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        "--chaos-seed", str(args.seed), "--chaos-spec", args.spec,
        "--chaos-log", master_log, "--metrics-out", metrics_path,
        *_drill_gossip_args(args),
        *_drill_lever_args(args),
    )
    nodes = []
    t0 = time.perf_counter()
    master_done = False
    try:
        seed_ep = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("master never reported its endpoint")
        for k in range(args.nodes):
            nodes.append(
                spawn(
                    "cluster-node", "--seed", seed_ep, "--node-id", str(k),
                    "--chaos-log",
                    os.path.join(args.out_dir, f"chaos-node{k}.jsonl"),
                )
            )
        try:
            if args.duration:
                time.sleep(args.duration)
                master.send_signal(_signal.SIGTERM)
                master.wait(timeout=30)
                # the Shutdown broadcast is racing any mid-rejoin node:
                # give every node a grace window to exit (and flush its
                # chaos log) before the finally-kill
                for n in nodes:
                    try:
                        n.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
            else:
                out, _ = master.communicate(timeout=600)
                master_done = "master done" in out
                for n in nodes:
                    try:
                        n.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        n.kill()
        except subprocess.TimeoutExpired:
            # a wedged cluster is a RESULT for this harness, not a crash:
            # fall through to the summary (which will report the wedge and
            # exit non-zero), never a bare traceback
            wedged = True
    finally:
        for proc in [master, *nodes]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    elapsed = time.perf_counter() - t0
    rounds_completed = 0
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            rounds_completed = sum(
                1
                for ln in f
                if ln.strip() and json.loads(ln).get("kind") == "round"
            )
    events: dict[str, int] = {}
    logs = sorted(
        f for f in os.listdir(args.out_dir) if f.startswith("chaos-")
    )
    for f in logs:
        with open(os.path.join(args.out_dir, f)) as fh:
            for ln in fh:
                if ln.strip():
                    fault = json.loads(ln)["fault"]
                    events[fault] = events.get(fault, 0) + 1
    from akka_allreduce_tpu.control.chaos import CRASH_EXIT_CODE

    summary = {
        "seed": args.seed,
        "spec": args.spec,
        "elapsed_s": round(elapsed, 1),
        "rounds_completed": rounds_completed,
        "master_done": master_done or None,
        "wedged": wedged or None,
        "chaos_events": events,
        "chaos_logs": logs,
        "node_exits": [n.returncode for n in nodes],
        "injected_crashes": sum(
            1 for n in nodes if n.returncode == CRASH_EXIT_CODE
        ),
    }
    print(json.dumps(summary))
    # pass = the cluster made progress UNDER chaos without wedging; with a
    # round budget the budget must also have finished
    ok = (
        not wedged
        and rounds_completed > 0
        and (args.duration is not None or master_done)
    )
    return 0 if ok else 1


def _blobs_match_replicas(
    state_dirs, victim: int, restore: dict, n_nodes: int, failures: list
) -> bool | None:
    """Byte-identity for the chaos-recover drill, against the RESTORE
    record's own leaf->sha evidence (printed by the node at restore time —
    immune to the node's later saves/prunes racing this check): every
    restored blob must exist on some replica with bytes that hash back to
    its content-addressed name (the same verify gate the restore itself
    passed — hash equality IS byte equality here), and when the victim's
    copy is still on disk it is compared raw as well."""
    from akka_allreduce_tpu.control.statetransfer import ChunkStore, npy_sha

    shas = set(restore.get("leaves", {}).values())
    if not shas:
        failures.append("restore record carries no leaf evidence")
        return None
    own = ChunkStore(state_dirs[victim])
    ok = True
    for sha in sorted(shas):
        replica_bytes = None
        for k in range(n_nodes):
            if k == victim:
                continue
            peer = ChunkStore(state_dirs[k])
            try:
                # the replicas are LIVE and pruning; a blob vanishing
                # between has() and read() is the next peer's problem,
                # not a harness crash
                if peer.has(sha):
                    replica_bytes = peer.read(sha)
                    break
            except FileNotFoundError:
                continue
        if replica_bytes is None:
            ok = False
            failures.append(f"blob {sha[:12]} held by no replica")
            continue
        if npy_sha(replica_bytes) != sha:
            ok = False
            failures.append(f"replica blob {sha[:12]} fails content hash")
        try:
            mine = own.read(sha) if own.has(sha) else None
        except FileNotFoundError:  # pruned between has() and read()
            mine = None
        if mine is not None and mine != replica_bytes:
            ok = False
            failures.append(f"blob {sha[:12]} differs from replica")
    return ok


def _cmd_chaos_recover(argv: list[str]) -> int:
    """Crash + disk-loss recovery drill (RESILIENCE.md "Recovery", ISSUE 6
    acceptance): a real master + N state-armed node processes run a round
    budget under a SEEDED chaos crash of one node; the harness then deletes
    the crashed node's checkpoint directory (disk loss) and respawns it.
    The node must rejoin, pull its state back from live peer replicas
    (``RESTORE {"source": "peer", ...}``), keep contributing, and the
    budget must finish — with the restored blobs byte-identical to the
    replica copies. ``make chaos-recover`` runs the fixed-seed variant;
    tests/test_peer_restore.py wires it into tier-1."""
    p = argparse.ArgumentParser(
        "chaos-recover",
        description="seeded crash + checkpoint-dir loss; assert the node "
        "recovers via peer restore and the round budget completes",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument(
        "--crash-round", type=int, default=25,
        help="round at which the victim's seeded crash fires (several "
        "save/replicate cycles must fit before it — see --state-every)",
    )
    p.add_argument(
        "--min-post-rounds", type=int, default=40,
        help="full-membership rounds that must complete AFTER the peer "
        "restore before the run is allowed to finish (the post-recovery "
        "half of the training budget)",
    )
    p.add_argument(
        "--phase-timeout", type=float, default=240.0,
        help="wall-clock bound on each recovery phase",
    )
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    p.add_argument("--state-every", type=int, default=5)
    p.add_argument("--out-dir", default="chaos_recover_run")
    _add_drill_gossip_flags(p)
    _add_drill_lever_flags(p)
    args = p.parse_args(argv)
    if args.nodes < 3:
        p.error("need >= 3 nodes: the victim plus at least 2 replica holders")

    import json
    import os
    import shutil
    import signal as _signal
    import subprocess

    from akka_allreduce_tpu.control.chaos import CRASH_EXIT_CODE

    victim = args.nodes - 1
    spec = f"crash:node={victim},at=round{args.crash_round}"
    os.makedirs(args.out_dir, exist_ok=True)
    metrics_path = os.path.join(args.out_dir, "rounds.jsonl")
    if os.path.exists(metrics_path):
        os.remove(metrics_path)  # MetricsLogger appends; one run per file
    state_dirs = [
        os.path.join(args.out_dir, f"state{k}") for k in range(args.nodes)
    ]
    for d in state_dirs:
        if os.path.isdir(d):
            shutil.rmtree(d)  # a fresh drill must not inherit old state
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)

    def spawn_node(seed_ep, k):
        return spawn(
            "cluster-node", "--seed", seed_ep, "--node-id", str(k),
            "--state-dir", state_dirs[k],
            "--state-every", str(args.state_every),
        )

    failures: list[str] = []
    restore = None
    crash_exit = None
    master_done = False
    byte_identical = None
    reborn = None
    reborn_lines: list[str] = []
    rounds_at_crash = rounds_at_done = 0

    def full_rounds() -> int:
        return _drill_full_rounds(metrics_path, args.nodes)

    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", "-1", "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--metrics-out", metrics_path,
        *_drill_gossip_args(args),
        *_drill_lever_args(args),
    )
    nodes = []
    try:
        seed_ep = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("master never reported its endpoint")
        nodes = [spawn_node(seed_ep, k) for k in range(args.nodes)]
        # phase 1: the seeded crash fires (deterministic round trigger; the
        # run is open-ended, so no machine is "too fast" for the drill)
        try:
            crash_exit = nodes[victim].wait(timeout=args.phase_timeout)
        except subprocess.TimeoutExpired:
            failures.append("victim never crashed (chaos round not reached)")
        if crash_exit is not None and crash_exit != CRASH_EXIT_CODE:
            failures.append(
                f"victim exited {crash_exit}, not the chaos crash "
                f"{CRASH_EXIT_CODE}"
            )
        rounds_at_crash = full_rounds()
        # phase 2: the disk dies with the process
        shutil.rmtree(state_dirs[victim], ignore_errors=True)
        # phase 2.5 (the deflake gate): wait for the MASTER to have
        # OBSERVED the death — a reduced-membership round record in its
        # metrics JSONL proves the victim was expelled and the grid
        # re-organized. Respawning before that races the detector: the
        # victim's id still reads as a LIVE member, so the reborn
        # process's preferred id is "taken" and it gets minted a fresh
        # id whose checkpoint history is empty — the restore then misses
        # through no fault of the recovery path (the historical flake).
        if not failures:
            await_phase(
                lambda: _drill_full_rounds(metrics_path, args.nodes - 1) >= 1,
                "the master's expulsion of the victim "
                "(reduced-membership rounds in the metrics log)",
            )
        # phase 3: same identity, empty disk — recovery must come from
        # peers; its stdout is pumped on a thread so RESTORE is observable
        # while the cluster keeps running
        if not failures:
            reborn = spawn_node(seed_ep, victim)
            pump = _drill_pump(reborn, reborn_lines)
            await_phase(
                lambda: any(
                    ln.startswith("RESTORE ") for ln in list(reborn_lines)
                ),
                "the respawned node's restore report",
            )
            for line in list(reborn_lines):
                if line.startswith("RESTORE "):
                    restore = json.loads(line[len("RESTORE "):])
            # byte-identity is checked NOW, against the RESTORED step's
            # manifest, while its blobs and the replicas' copies are all
            # still on disk — the node keeps saving (and pruning) after
            # this, and the FINAL save's replication is asynchronous, so a
            # shutdown-time check against `latest()` would race both
            if restore is not None and restore.get("complete"):
                byte_identical = _blobs_match_replicas(
                    state_dirs, victim, restore, args.nodes, failures
                )
            # phase 4: the post-recovery training budget — min_post_rounds
            # MORE full-membership rounds with the restored node in the line
            target = full_rounds() + args.min_post_rounds
            await_phase(
                lambda: full_rounds() >= target,
                f"{args.min_post_rounds} full-membership rounds post-restore",
            )
        rounds_at_done = full_rounds()
        # phase 5: end the open-ended run gracefully (Shutdown broadcast)
        master.send_signal(_signal.SIGTERM)
        try:
            out_master, _ = master.communicate(timeout=60)
            master_done = "master done" in out_master
        except subprocess.TimeoutExpired:
            failures.append("master did not shut down on SIGTERM")
        for n in (n for i, n in enumerate(nodes) if i != victim):
            try:
                n.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                n.kill()
        if reborn is not None:
            # its stdout is owned by the pump thread — wait, don't
            # communicate (two readers on one pipe)
            try:
                reborn.wait(timeout=30)
            except subprocess.TimeoutExpired:
                reborn.kill()
            pump.join(timeout=10)
    finally:
        for proc in [master, *nodes, *([reborn] if reborn else [])]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()

    post_rounds = 0
    for line in reborn_lines:
        if line.startswith("RESTORE ") and restore is None:
            restore = json.loads(line[len("RESTORE "):])
        if "shut down" in line and " rounds" in line:
            try:
                post_rounds = int(line.split(":")[-1].split()[0])
            except ValueError:
                pass
    if restore is None:
        failures.append("respawned node never reported a restore")
    else:
        if restore.get("source") != "peer":
            failures.append(f"restore source {restore.get('source')!r} != 'peer'")
        if not restore.get("complete"):
            failures.append("peer restore incomplete")
        elif byte_identical is None:
            failures.append("byte-identity was never checked")
    if not master_done:
        failures.append("run did not finish cleanly")
    if reborn is not None and reborn.returncode not in (0, None):
        failures.append(f"respawned node exited {reborn.returncode}")
    if not post_rounds:
        failures.append("no post-restore round progress at the reborn node")

    # torn-tolerant via the shared reader: when the master had to be
    # killed (a failure path), its metrics writer may have died mid-append
    # — the summary must still come out instead of a JSON traceback
    rounds_completed = sum(
        1
        for rec in _drill_jsonl_records(metrics_path)
        if rec.get("kind") == "round"
    )
    summary = {
        "seed": args.seed,
        "spec": spec,
        "rounds_completed": rounds_completed,
        "full_rounds_at_crash": rounds_at_crash,
        "full_rounds_post_restore": rounds_at_done - rounds_at_crash,
        "master_done": master_done,
        "crash_exit": crash_exit,
        "restore": restore,
        "post_restore_rounds": post_rounds,
        "byte_identical": byte_identical,
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def _cmd_chaos_gossip(argv: list[str]) -> int:
    """Decentralized-membership drill (RESILIENCE.md "Tier 6",
    ``make chaos-gossip``): a real master + N node processes run under
    SWIM gossip membership while a SEEDED ONE-DIRECTIONAL partition cuts
    one node's sends TO the master (``partition:from=K,to=m``) — the
    exact asymmetric loss that makes a hub detector read a healthy node
    as dead. Pass requires:

    - ZERO expulsions while the bad link is down (indirect probes through
      the other nodes keep vouching for the victim — full-membership
      rounds keep completing throughout);
    - after the window heals, a node SIGKILLed for real IS expelled by
      the gossip verdict and the grid reorganizes (the detector still
      detects — it just needs more than one vantage point to convict).
    """
    p = argparse.ArgumentParser(
        "chaos-gossip",
        description="seeded asymmetric partition of the master's inbound "
        "link under gossip membership; assert zero false expulsions, "
        "then a real kill is still detected",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument(
        "--partition-at", type=float, default=6.0,
        help="seconds (per-process clock) until the one-way partition",
    )
    p.add_argument(
        "--partition-for", type=float, default=6.0,
        help="how long the bad link stays down",
    )
    p.add_argument(
        "--min-post-rounds", type=int, default=10,
        help="reduced-membership rounds required after the real kill",
    )
    p.add_argument("--phase-timeout", type=float, default=240.0)
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument("--gossip-interval", type=float, default=0.25)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    _add_drill_lever_flags(p)
    p.add_argument("--out-dir", default="chaos_gossip_run")
    args = p.parse_args(argv)
    if args.nodes < 4:
        # th=0.66 must stay satisfiable by the reporters the master can
        # hear while ONE node's completions are cut: need
        # ceil(0.66*N) <= N-1, and >= 2 relays for indirect probes
        p.error("need >= 4 nodes (threshold headroom + indirect relays)")

    import json
    import os
    import signal as _signal
    import subprocess

    victim = args.nodes - 1  # the bad-link node (stays healthy)
    killed = args.nodes - 2  # the really-dead node of phase 2
    spec = (
        f"partition:from={victim},to=m,"
        f"at={args.partition_at:g}s,heal={args.partition_for:g}s"
    )
    os.makedirs(args.out_dir, exist_ok=True)
    metrics_path = os.path.join(args.out_dir, "rounds.jsonl")
    stale = [f for f in os.listdir(args.out_dir) if f.endswith(".jsonl")]
    for f in stale:
        os.remove(os.path.join(args.out_dir, f))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)
    failures: list[str] = []
    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    def full_rounds() -> int:
        return _drill_full_rounds(metrics_path, args.nodes)

    def reduced_rounds() -> int:
        return _drill_full_rounds(metrics_path, args.nodes - 1)

    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", "-1", "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        *_drill_lever_args(args),
        "--gossip", "--gossip-interval", str(args.gossip_interval),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--chaos-log", os.path.join(args.out_dir, "chaos-master.jsonl"),
        "--metrics-out", metrics_path,
    )
    nodes = []
    master_done = False
    master_lines: list[str] = []
    rounds_before_partition = rounds_after_heal = 0
    false_expulsions = kill_detected = None
    detect_s = None
    try:
        seed_ep = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("master never reported its endpoint")
        t_spawn = time.monotonic()
        for k in range(args.nodes):
            nodes.append(
                spawn(
                    "cluster-node", "--seed", seed_ep, "--node-id", str(k),
                    "--chaos-log",
                    os.path.join(args.out_dir, f"chaos-node{k}.jsonl"),
                )
            )
        # phase 1: a healthy baseline before the bad link goes down
        await_phase(
            lambda: full_rounds() >= 5, "pre-partition full-membership rounds"
        )
        rounds_before_partition = full_rounds()
        # phase 2: full-membership rounds must KEEP accumulating through
        # the one-way partition — gated on observed round records, not a
        # wall anchor: the partition triggers are per-process clocks
        # (each injector's t0 is its process start), and on a loaded box
        # the jax imports alone can eat most of a wall-anchored window,
        # turning a progress comparison into a vacuous 6 -> 6
        await_phase(
            lambda: full_rounds() >= rounds_before_partition + 8,
            "full-membership rounds continuing through the one-way "
            "partition (a stall here means the bad link wedged the line)",
        )
        # ...and the kill phase must not overlap the partition window:
        # ride out whatever remains of it (per-process t0 >= t_spawn, so
        # this bounds every process's window from above) plus several
        # suspicion windows of post-heal slack
        window_end = (
            t_spawn + args.partition_at + args.partition_for
            + 8 * args.gossip_interval
        )
        while time.monotonic() < window_end:
            time.sleep(0.2)
        rounds_after_heal = full_rounds()
        false_expulsions = reduced_rounds()
        if false_expulsions:
            failures.append(
                f"{false_expulsions} reduced-membership round(s) during the "
                "one-way partition: a healthy node was expelled"
            )
        # phase 3: a REAL death must still be detected by the ring
        t_kill = time.monotonic()
        nodes[killed].kill()
        target = args.min_post_rounds
        kill_detected = await_phase(
            lambda: reduced_rounds() >= target,
            f"{target} reduced-membership rounds after the real kill",
        )
        detect_s = round(time.monotonic() - t_kill, 2)
        # phase 4: graceful end (Shutdown broadcast flushes every log)
        master.send_signal(_signal.SIGTERM)
        try:
            out_master, _ = master.communicate(timeout=60)
            master_lines = out_master.splitlines()
            master_done = any("master done" in ln for ln in master_lines)
        except subprocess.TimeoutExpired:
            failures.append("master did not shut down on SIGTERM")
        for i, n in enumerate(nodes):
            if i == killed:
                continue
            try:
                n.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                n.kill()
    finally:
        for proc in [master, *nodes]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()

    # the master's exit snapshot carries the gossip counters (expulsions
    # must be exactly 1: the killed node — never the bad-link victim)
    gossip_metrics = {}
    for rec in _drill_jsonl_records(metrics_path):
        if (
            rec.get("kind") == "metrics_snapshot"
            and rec.get("role") == "master"
        ):
            gossip_metrics = {
                k: v
                for k, v in rec.get("metrics", {}).items()
                if k.startswith("gossip.")
            }
    if gossip_metrics.get("gossip.expulsions") != 1:
        failures.append(
            "expected exactly 1 gossip expulsion (the killed node), got "
            f"{gossip_metrics.get('gossip.expulsions')!r}"
        )
    if not master_done:
        failures.append("run did not finish cleanly")
    summary = {
        "seed": args.seed,
        "spec": spec,
        "full_rounds_pre_partition": rounds_before_partition,
        "full_rounds_post_heal": rounds_after_heal,
        "false_expulsions": false_expulsions,
        "kill_detected": bool(kill_detected),
        "reduced_rounds_post_kill": reduced_rounds(),
        "detect_plus_rounds_s": detect_s,
        "gossip": gossip_metrics,
        "master_done": master_done,
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def _cmd_chaos_scale(argv: list[str]) -> int:
    """Pod-scale control-plane drill (RESILIENCE.md "Scale",
    ``make chaos-scale``): the largest real-process grid the box allows —
    a leader + warm standby + an RxC pod of nodes bootstrapped from GRID
    COORDINATES (``--grid``/``--process-index``, node id = coordinate)
    and sharded into ``--line-shards`` free-running LineMasters — runs a
    partition + leader kill + node kill sequence:

    - phase 1: EVERY shard completes rounds at its full membership
      (per-shard round records under distinct line ids);
    - phase 2: a seeded ONE-WAY partition cuts one node's master-bound
      sends; gossip's indirect path must keep it in — zero re-shards;
    - phase 3: the leader is SIGKILLed; the warm standby takes over
      (epoch >= 2) and — because shard assignment is a pure function of
      the view — rebuilds the SAME shard layout, every shard resuming
      its own sequence;
    - phase 4: a node is SIGKILLed; its coordinate-anchored shard
      shrinks by exactly one while every other shard keeps its size and
      rounds keep completing;
    - phase 5: graceful SIGTERM end; node exits clean.

    The summary JSON also records the deterministic Fabric's measured
    sim rate on this box (nodes/sec — the 256..1024-node sim arms'
    cost evidence, tests/test_gossip_scale.py).
    """
    p = argparse.ArgumentParser(
        "chaos-scale",
        description="grid-coordinate pod bootstrap + hierarchical shard "
        "drill: partition, leader kill, node kill — per-shard rounds "
        "must survive all three",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument(
        "--grid", default="2x8", metavar="RxC",
        help="pod layout; every coordinate is spawned as a real process",
    )
    p.add_argument("--line-shards", type=int, default=4)
    p.add_argument(
        "--partition-at", type=float, default=6.0,
        help="seconds (per-process clock) until the one-way partition",
    )
    p.add_argument(
        "--partition-for", type=float, default=6.0,
        help="how long the bad link stays down",
    )
    p.add_argument(
        "--min-shard-rounds", type=int, default=5,
        help="full-membership rounds required per shard per phase",
    )
    p.add_argument(
        "--min-post-rounds", type=int, default=8,
        help="post-node-kill rounds required in the shrunken shard",
    )
    p.add_argument("--phase-timeout", type=float, default=240.0)
    p.add_argument("--size", type=int, default=32768)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument("--gossip-interval", type=float, default=0.25)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    _add_drill_lever_flags(p)
    p.add_argument("--out-dir", default="chaos_scale_run")
    args = p.parse_args(argv)

    import json
    import os
    import signal as _signal
    import subprocess

    from akka_allreduce_tpu.control import pod as _pod
    from akka_allreduce_tpu.control.simfabric import sim_rate

    try:
        rows, cols = _pod.parse_grid(args.grid)
    except ValueError as e:
        p.error(str(e))
    n_nodes = rows * cols
    blocks = _pod.coordinate_shard_assignment(
        range(n_nodes), rows, cols, args.line_shards
    )
    sizes = {lid: len(b) for lid, b in enumerate(blocks)}
    if min(sizes.values()) < 3:
        # th=0.66 must stay satisfiable inside the partitioned node's
        # shard: ceil(0.66*size) <= size-1 needs size >= 3
        p.error(
            f"shard sizes {sorted(sizes.values())} too small for the "
            "partition phase: need >= 3 nodes per shard (use a larger "
            "--grid or fewer --line-shards)"
        )
    victim_link = blocks[0][-1]  # the bad-link node (stays healthy)
    killed = n_nodes - 1  # the really-dead node (last shard shrinks)
    killed_line = len(blocks) - 1
    sizes_post_kill = dict(sizes)
    sizes_post_kill[killed_line] -= 1
    spec = (
        f"partition:from={victim_link},to=m,"
        f"at={args.partition_at:g}s,heal={args.partition_for:g}s"
    )
    os.makedirs(args.out_dir, exist_ok=True)
    leader_metrics = os.path.join(args.out_dir, "rounds-leader.jsonl")
    standby_metrics = os.path.join(args.out_dir, "rounds-standby.jsonl")
    stale = [f for f in os.listdir(args.out_dir) if f.endswith(".jsonl")]
    for f in stale:
        os.remove(os.path.join(args.out_dir, f))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)
    failures: list[str] = []
    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    def shard_rounds(path, expected: dict[int, int]) -> dict[int, int]:
        """Per-line count of round records at the line's EXPECTED full
        size (shard assignment is pure in the view, so line id -> size
        is stable across reorganizations of the same membership)."""
        per = {lid: 0 for lid in expected}
        for rec in _drill_jsonl_records(path):
            if rec.get("kind") != "round":
                continue
            lid = rec.get("line")
            if lid in per and rec.get("workers") == expected[lid]:
                per[lid] += 1
        return per

    def reshard_anomalies(path) -> int:
        """Round records whose (line, size) does not match the full
        layout — a healthy-node expulsion would show here first."""
        return sum(
            1
            for rec in _drill_jsonl_records(path)
            if rec.get("kind") == "round"
            and rec.get("workers") != sizes.get(rec.get("line"))
        )

    leader = spawn(
        "cluster-master", "--port", "0", "--nodes", str(n_nodes),
        "--grid", args.grid, "--line-shards", str(args.line_shards),
        "--rounds", "-1", "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        *_drill_lever_args(args),
        "--gossip", "--gossip-interval", str(args.gossip_interval),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--chaos-log", os.path.join(args.out_dir, "chaos-leader.jsonl"),
        "--metrics-out", leader_metrics,
    )
    standby = None
    nodes: list = []
    standby_lines: list[str] = []
    takeover = None
    standby_done = False
    rounds_before_partition: dict[int, int] = {}
    rounds_after_heal: dict[int, int] = {}
    anomalies_pre_kill = None
    node_exits: dict = {}
    try:
        seed_ep = None
        for line in leader.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("leader never reported its endpoint")
        standby = spawn(
            "cluster-standby", "--seed", seed_ep,
            "--heartbeat", str(args.heartbeat),
            "--metrics-out", standby_metrics,
        )
        standby_ep = None
        for line in standby.stdout:
            if line.startswith("standby listening on "):
                standby_ep = line.split()[3]
                break
        if standby_ep is None:
            raise RuntimeError("standby never reported its endpoint")
        standby_pump = _drill_pump(standby, standby_lines)
        t_spawn = time.monotonic()
        for k in range(n_nodes):
            nodes.append(
                spawn(
                    "cluster-node", "--seed", seed_ep,
                    "--grid", args.grid, "--process-index", str(k),
                    "--chaos-log",
                    os.path.join(args.out_dir, f"chaos-node{k}.jsonl"),
                )
            )
        # phase 1: EVERY shard completes rounds at full membership
        await_phase(
            lambda: min(
                shard_rounds(leader_metrics, sizes).values()
            )
            >= args.min_shard_rounds,
            "pre-partition full-membership rounds on every shard",
        )
        rounds_before_partition = shard_rounds(leader_metrics, sizes)
        # phase 2: rounds keep accumulating per shard THROUGH the one-way
        # partition (round-record gated, like chaos-gossip), and no
        # re-shard happens (the indirect path keeps the victim in)
        def _partition_progress() -> int:
            per = shard_rounds(leader_metrics, sizes)  # ONE parse per poll
            return min(
                per[lid] - rounds_before_partition.get(lid, 0)
                for lid in sizes
            )

        await_phase(
            lambda: _partition_progress() >= args.min_shard_rounds,
            "per-shard rounds continuing through the one-way partition",
        )
        window_end = (
            t_spawn + args.partition_at + args.partition_for
            + 8 * args.gossip_interval
        )
        while time.monotonic() < window_end:
            time.sleep(0.2)
        rounds_after_heal = shard_rounds(leader_metrics, sizes)
        anomalies_pre_kill = reshard_anomalies(leader_metrics)
        if anomalies_pre_kill:
            failures.append(
                f"{anomalies_pre_kill} off-layout round record(s) during "
                "the partition window: a healthy node was expelled or a "
                "shard re-split"
            )
        # phase 3: SIGKILL the LEADER; the warm standby must take over
        # and rebuild the SAME shard layout from the replicated view
        leader.send_signal(_signal.SIGKILL)
        leader.wait()
        await_phase(
            lambda: any(
                ln.startswith("TAKEOVER ") for ln in list(standby_lines)
            ),
            "the standby's TAKEOVER line",
        )
        for ln in list(standby_lines):
            if ln.startswith("TAKEOVER "):
                takeover = json.loads(ln[len("TAKEOVER "):])
        await_phase(
            lambda: min(
                shard_rounds(standby_metrics, sizes).values()
            )
            >= args.min_shard_rounds,
            "post-takeover rounds on every shard (same layout)",
        )
        # phase 4: SIGKILL a node — its coordinate-anchored shard shrinks
        # by one, the other shards keep their sizes, rounds continue
        nodes[killed].send_signal(_signal.SIGKILL)
        nodes[killed].wait()

        def _post_kill_progress() -> int:
            per = shard_rounds(standby_metrics, sizes_post_kill)
            return min(per[lid] for lid in sizes_post_kill)

        await_phase(
            lambda: _post_kill_progress() >= args.min_post_rounds,
            "post-node-kill rounds (shrunken shard included)",
        )
        # phase 5: graceful end at the promoted master
        standby.send_signal(_signal.SIGTERM)
        try:
            standby.wait(timeout=60)
        except subprocess.TimeoutExpired:
            failures.append("promoted standby did not shut down on SIGTERM")
        standby_pump.join(timeout=10)
        standby_done = any("master done" in ln for ln in standby_lines)
        for k, n in enumerate(nodes):
            if k == killed:
                node_exits[k] = n.returncode
                continue
            try:
                n.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                # a survivor that wedges in its shutdown path is exactly
                # the defect class this drill exists to catch — record
                # it, don't let the cleanup kill() read as a clean exit
                n.kill()
                n.wait()
                failures.append(
                    f"node {k} did not exit within 30s of the Shutdown "
                    "broadcast (killed)"
                )
            node_exits[k] = n.returncode
            if n.returncode not in (0, None):
                failures.append(f"node {k} exited {n.returncode}")
    finally:
        for proc in [leader, standby, *nodes]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()

    if takeover is None:
        failures.append("standby never took over")
    elif takeover.get("epoch", 0) < 2:
        failures.append(f"takeover did not bump the epoch: {takeover}")
    if not standby_done:
        failures.append("run did not finish cleanly")
    summary = {
        "seed": args.seed,
        "grid": args.grid,
        "line_shards": args.line_shards,
        "shard_sizes": {str(k): v for k, v in sorted(sizes.items())},
        "spec": spec,
        "shard_rounds_pre_partition": {
            str(k): v for k, v in sorted(rounds_before_partition.items())
        },
        "shard_rounds_post_heal": {
            str(k): v for k, v in sorted(rounds_after_heal.items())
        },
        "reshard_anomalies_pre_kill": anomalies_pre_kill,
        "takeover": takeover,
        "shard_rounds_under_standby": {
            str(k): v
            for k, v in sorted(shard_rounds(standby_metrics, sizes).items())
        },
        "shard_rounds_post_kill": {
            str(k): v
            for k, v in sorted(
                shard_rounds(standby_metrics, sizes_post_kill).items()
            )
        },
        "node_exits": {str(k): v for k, v in sorted(node_exits.items())},
        "standby_done": standby_done,
        "sim": sim_rate(256, 5.0),
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def _cmd_chaos_failover(argv: list[str]) -> int:
    """Master-kill failover drill (RESILIENCE.md "Tier 4", ISSUE 7
    acceptance): a real leader + warm standby + N state-armed nodes run an
    open-ended round budget; a SEEDED chaos crash (``crash:node=m``) kills
    the leader mid-round. The standby must take over within one lease
    window (TAKEOVER line), rounds must resume under the bumped epoch with
    no round applied twice (every node's ``dup_flushes`` stays 0 — the
    cross-epoch dedup), and a node killed+disk-wiped AFTER the failover
    must still restore from peers via the REPLICATED holder registry. The
    run then ends gracefully via SIGTERM at the promoted master. ``make
    chaos-failover`` runs the fixed-seed variant; exit 0 iff every
    assertion holds."""
    p = argparse.ArgumentParser(
        "chaos-failover",
        description="seeded leader kill mid-round; assert warm-standby "
        "takeover, epoch fencing, cross-epoch round dedup, and a "
        "post-failover peer restore",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument(
        "--crash-round", type=int, default=25,
        help="round at which the leader's seeded crash fires",
    )
    p.add_argument(
        "--min-post-rounds", type=int, default=40,
        help="full-membership rounds that must complete under the standby "
        "AFTER the post-failover peer restore (the drill's round budget)",
    )
    p.add_argument(
        "--extra-spec", default="",
        help="additional chaos faults layered onto the leader kill "
        "(e.g. 'drop:p=0.02')",
    )
    p.add_argument(
        "--phase-timeout", type=float, default=240.0,
        help="wall-clock bound on each drill phase",
    )
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    p.add_argument("--state-every", type=int, default=5)
    p.add_argument("--out-dir", default="chaos_failover_run")
    _add_drill_gossip_flags(p)
    _add_drill_lever_flags(p)
    args = p.parse_args(argv)
    if args.nodes < 3:
        p.error("need >= 3 nodes: a restore victim plus 2 replica holders")

    import json
    import os
    import re
    import shutil
    import signal as _signal
    import subprocess

    from akka_allreduce_tpu.control.chaos import CRASH_EXIT_CODE, parse_spec

    spec = f"crash:node=m,at=round{args.crash_round}"
    if args.extra_spec:
        spec = f"{spec};{args.extra_spec}"
    try:
        parse_spec(spec)
    except ValueError as e:
        p.error(str(e))
    os.makedirs(args.out_dir, exist_ok=True)
    leader_metrics = os.path.join(args.out_dir, "rounds-leader.jsonl")
    standby_metrics = os.path.join(args.out_dir, "rounds-standby.jsonl")
    for f in (leader_metrics, standby_metrics):
        if os.path.exists(f):
            os.remove(f)  # MetricsLogger appends; one run per file
    state_dirs = [
        os.path.join(args.out_dir, f"state{k}") for k in range(args.nodes)
    ]
    for d in state_dirs:
        if os.path.isdir(d):
            shutil.rmtree(d)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)

    def spawn_node(seed_ep, k):
        return spawn(
            "cluster-node", "--seed", seed_ep, "--node-id", str(k),
            "--state-dir", state_dirs[k],
            "--state-every", str(args.state_every),
        )

    pump = _drill_pump

    def full_rounds(path) -> int:
        return _drill_full_rounds(path, args.nodes)

    failures: list[str] = []
    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    victim = args.nodes - 1
    crash_exit = None
    rounds_at_crash = 0
    takeover = None
    restore = None
    standby_done = False
    dup_flushes: dict[int, int] = {}
    node_exits: dict[int, int | None] = {}
    standby_lines: list[str] = []
    reborn_lines: list[str] = []
    reborn = None

    leader = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", "-1", "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--chaos-log", os.path.join(args.out_dir, "chaos-leader.jsonl"),
        "--metrics-out", leader_metrics,
        *_drill_gossip_args(args),
        *_drill_lever_args(args),
    )
    standby = None
    nodes = []
    try:
        seed_ep = None
        for line in leader.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("leader never reported its endpoint")
        standby = spawn(
            "cluster-standby", "--seed", seed_ep,
            "--heartbeat", str(args.heartbeat),
            "--metrics-out", standby_metrics,
        )
        standby_ep = None
        for line in standby.stdout:
            if line.startswith("standby listening on "):
                standby_ep = line.split()[3]
                break
        if standby_ep is None:
            raise RuntimeError("standby never reported its endpoint")
        standby_pump = pump(standby, standby_lines)
        nodes = [spawn_node(seed_ep, k) for k in range(args.nodes)]
        # phase 1: the seeded master kill fires (round trigger mid-run)
        try:
            crash_exit = leader.wait(timeout=args.phase_timeout)
        except subprocess.TimeoutExpired:
            failures.append("leader never crashed (chaos round not reached)")
        if crash_exit is not None and crash_exit != CRASH_EXIT_CODE:
            failures.append(
                f"leader exited {crash_exit}, not the chaos crash "
                f"{CRASH_EXIT_CODE}"
            )
        rounds_at_crash = full_rounds(leader_metrics)
        # phase 2: the standby's lease expires and it takes over
        if not failures:
            await_phase(
                lambda: any(
                    ln.startswith("TAKEOVER ") for ln in list(standby_lines)
                ),
                "the standby's TAKEOVER line",
            )
            for ln in list(standby_lines):
                if ln.startswith("TAKEOVER "):
                    takeover = json.loads(ln[len("TAKEOVER "):])
        # phase 3: rounds resume under the new epoch with full membership
        if not failures:
            await_phase(
                lambda: full_rounds(standby_metrics) >= 5,
                "post-takeover full-membership rounds",
            )
        # phase 4: kill a NODE after the failover, wipe its disk, respawn
        # it at the promoted master — the restore must find peer holders
        # via the registry the digest replicated (plus re-adverts)
        if not failures:
            nodes[victim].send_signal(_signal.SIGKILL)
            nodes[victim].wait()
            node_exits[victim] = nodes[victim].returncode
            shutil.rmtree(state_dirs[victim], ignore_errors=True)
            # phase 4.5 — the chaos-recover deflake applied here too:
            # respawn only after the PROMOTED master demonstrably expelled
            # the victim (a reduced-membership round in its metrics). A
            # join that races the detector reads the victim's id as a
            # LIVE member and mints the reborn node a FRESH id with no
            # checkpoint history — its restore then honestly reports
            # 'none' while the replicas sit on live peers under the old
            # id.
            await_phase(
                lambda: _drill_full_rounds(standby_metrics, args.nodes - 1)
                >= 1,
                "the promoted master's observed expulsion of the victim",
            )
        if not failures:
            reborn = spawn_node(standby_ep, victim)
            reborn_pump = pump(reborn, reborn_lines)
            await_phase(
                lambda: any(
                    ln.startswith("RESTORE ") for ln in list(reborn_lines)
                ),
                "the respawned node's restore report",
            )
            for ln in list(reborn_lines):
                if ln.startswith("RESTORE "):
                    restore = json.loads(ln[len("RESTORE "):])
        # phase 5: the drill's round budget completes under the standby
        if not failures:
            target = full_rounds(standby_metrics) + args.min_post_rounds
            await_phase(
                lambda: full_rounds(standby_metrics) >= target,
                f"{args.min_post_rounds} full-membership rounds "
                "post-restore",
            )
        # phase 6: graceful end at the PROMOTED master
        standby.send_signal(_signal.SIGTERM)
        try:
            standby.wait(timeout=60)
        except subprocess.TimeoutExpired:
            failures.append("promoted standby did not shut down on SIGTERM")
        standby_pump.join(timeout=10)
        standby_done = any("master done" in ln for ln in standby_lines)
        for k, n in enumerate(nodes):
            if k == victim:
                continue
            try:
                out, _ = n.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                n.kill()
                out = ""
            node_exits[k] = n.returncode
            m = re.search(r"dup_flushes=(\d+)", out or "")
            if m:
                dup_flushes[k] = int(m.group(1))
        if reborn is not None:
            try:
                reborn.wait(timeout=30)
            except subprocess.TimeoutExpired:
                reborn.kill()
            reborn_pump.join(timeout=10)
            node_exits[f"{victim}-reborn"] = reborn.returncode
            for ln in reborn_lines:
                m = re.search(r"dup_flushes=(\d+)", ln)
                if m:
                    dup_flushes[victim] = int(m.group(1))
    finally:
        for proc in [leader, standby, *nodes, *([reborn] if reborn else [])]:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()

    # assertions over the collected evidence
    if takeover is None:
        failures.append("standby never took over")
    elif takeover.get("epoch", 0) < 2:
        failures.append(f"takeover did not bump the epoch: {takeover}")
    if restore is None:
        failures.append("respawned node never reported a restore")
    else:
        if restore.get("source") != "peer":
            failures.append(
                f"post-failover restore source {restore.get('source')!r} "
                "!= 'peer' (replicated registry not consulted?)"
            )
        if not restore.get("complete"):
            failures.append("post-failover peer restore incomplete")
    if not standby_done:
        failures.append("promoted standby did not finish cleanly")
    for k, dups in sorted(dup_flushes.items()):
        if dups:
            failures.append(
                f"node {k} applied {dups} round(s) twice across the "
                "failover (cross-epoch dedup broken)"
            )
    if len(dup_flushes) < args.nodes:
        failures.append(
            f"dup-flush evidence from only {sorted(dup_flushes)} of "
            f"{args.nodes} node(s)"
        )
    for k, rc in sorted(node_exits.items(), key=str):
        if k == victim:  # SIGKILLed by the drill itself
            continue
        if rc not in (0, None):
            failures.append(f"node {k} exited {rc}")

    summary = {
        "seed": args.seed,
        "spec": spec,
        "crash_exit": crash_exit,
        "full_rounds_at_crash": rounds_at_crash,
        "takeover": takeover,
        "rounds_under_standby": full_rounds(standby_metrics),
        "restore": restore,
        "dup_flushes": dup_flushes,
        "node_exits": {str(k): v for k, v in sorted(node_exits.items(), key=lambda kv: str(kv[0]))},
        "standby_done": standby_done,
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def _cmd_chaos_adapt(argv: list[str]) -> int:
    """Adaptive-degradation drill (RESILIENCE.md "Tier 5", ISSUE 8
    acceptance): a real master running the AdaptiveController + N nodes
    with IDENTICAL payloads run an open-ended budget; a SEEDED staged
    straggler (a windowed targeted ``delay`` + a ``stall`` burst inside
    it) slows one node's sends. The controller must DEGRADE (lower
    th_reduce, f16 -> int8 wire) within K rounds of the straggler's
    onset, HOLD without oscillation (total mode transitions bounded),
    RESTORE to full fidelity after the heal, and every node's reduced
    values must stay within the EF error budget (identical payloads =>
    the true average is the payload itself; ``--uniform-check`` measures
    the deviation). ``make chaos-adapt`` runs the fixed-seed variant;
    exit 0 iff every assertion holds."""
    p = argparse.ArgumentParser(
        "chaos-adapt",
        description="seeded staged straggler; assert the adaptive "
        "controller degrades, holds, restores, and stays inside the EF "
        "error budget",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument(
        "--straggle-at", type=int, default=30,
        help="round at which the straggler's delay window opens",
    )
    p.add_argument(
        "--heal-at", type=int, default=150,
        help="round at which the straggler's delay window closes",
    )
    p.add_argument(
        "--delay-ms", type=float, default=400.0,
        help="the straggler's per-send hold inside the window",
    )
    p.add_argument(
        "--stall-for", type=float, default=0.25,
        help="layer a stall burst of this many seconds 20 rounds into the "
        "straggle window (0 = delay only). The default stays under the "
        "phi detector's expulsion point (~0.35s at heartbeat 0.1 with "
        "min_std 0.05) — a slow-but-alive burst, which is the "
        "controller's case; longer values exercise expulsion/rejoin "
        "churn instead",
    )
    p.add_argument(
        "--k-rounds", type=int, default=60,
        help="the controller must first degrade within this many rounds "
        "of the straggle round",
    )
    p.add_argument(
        "--max-transitions", type=int, default=6,
        help="total mode transitions allowed (no-oscillation bound: "
        "2 degrades + 2 restores + slack)",
    )
    p.add_argument(
        "--err-budget", type=float, default=0.15,
        help="max |reduced average - payload| any node may observe "
        "(int8 quantization step ~max|x|/127 with EF; see RESILIENCE.md)",
    )
    p.add_argument(
        "--post-rounds", type=int, default=40,
        help="full-membership rounds that must complete AFTER the restore",
    )
    p.add_argument("--phase-timeout", type=float, default=240.0)
    p.add_argument("--size", type=int, default=65536)
    p.add_argument("--chunk", type=int, default=8192)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.1)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    p.add_argument("--adapt-window", type=int, default=6)
    p.add_argument("--adapt-dwell", type=int, default=12)
    p.add_argument("--adapt-lag", type=int, default=8)
    p.add_argument("--out-dir", default="chaos_adapt_run")
    _add_drill_gossip_flags(p)
    _add_drill_lever_flags(p)
    args = p.parse_args(argv)

    import json
    import os
    import signal as _signal
    import re
    import subprocess

    from akka_allreduce_tpu.control.chaos import parse_spec

    straggler = args.nodes - 1
    spec = (
        f"delay:node={straggler},ms={args.delay_ms:g},"
        f"at=round{args.straggle_at},for=round{args.heal_at}"
    )
    if args.stall_for > 0:
        spec += (
            f";stall:node={straggler},at=round{args.straggle_at + 20},"
            f"for={args.stall_for:g}s"
        )
    try:
        parse_spec(spec)
    except ValueError as e:
        p.error(str(e))
    os.makedirs(args.out_dir, exist_ok=True)
    metrics_path = os.path.join(args.out_dir, "rounds.jsonl")
    adapt_log = os.path.join(args.out_dir, "adapt-decisions.jsonl")
    for f in (metrics_path, adapt_log):
        if os.path.exists(f):
            os.remove(f)  # MetricsLogger appends; one run per file
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)

    failures: list[str] = []
    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    def adapt_events() -> list[dict]:
        out = []
        if not os.path.exists(metrics_path):
            return out
        with open(metrics_path) as f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue  # torn last line of a live writer
                if rec.get("kind") == "adapt":
                    out.append(rec)
        return out

    def full_rounds() -> int:
        return _drill_full_rounds(metrics_path, args.nodes)

    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", "-1", "--size", str(args.size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--chaos-log", os.path.join(args.out_dir, "chaos-master.jsonl"),
        "--metrics-out", metrics_path,
        "--adapt", "--adapt-window", str(args.adapt_window),
        "--adapt-dwell", str(args.adapt_dwell),
        "--adapt-lag", str(args.adapt_lag),
        "--adapt-log", adapt_log,
        *_drill_gossip_args(args),
        *_drill_lever_args(args),
    )
    nodes = []
    node_out: dict[int, str] = {}
    master_done = False
    try:
        seed_ep = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("master never reported its endpoint")
        nodes = [
            spawn(
                "cluster-node", "--seed", seed_ep, "--node-id", str(k),
                # IDENTICAL payloads on every node: the reduced average
                # must equal the payload, so deviation == wire error
                "--data-seed", "7", "--uniform-check",
                "--chaos-log",
                os.path.join(args.out_dir, f"chaos-node{k}.jsonl"),
            )
            for k in range(args.nodes)
        ]
        # phase 1: the straggler window opens and the controller degrades
        await_phase(
            lambda: any(e["to"] > e["from"] for e in adapt_events()),
            "the controller's first degrade decision",
        )
        first_degrade = next(
            (e for e in adapt_events() if e["to"] > e["from"]), None
        )
        if first_degrade is not None:
            lateness = first_degrade["round"] - args.straggle_at
            if lateness > args.k_rounds:
                failures.append(
                    f"controller degraded {lateness} rounds after the "
                    f"straggle round (budget {args.k_rounds})"
                )
        # phase 2: after the heal the controller walks back to level 0
        if not failures:
            await_phase(
                lambda: any(
                    e["to"] == 0 and e["from"] == 1 for e in adapt_events()
                ),
                "the controller's restore to full fidelity",
            )
        # phase 3: the post-restore round budget completes at level 0
        if not failures:
            target = full_rounds() + args.post_rounds
            await_phase(
                lambda: full_rounds() >= target,
                f"{args.post_rounds} full-membership rounds post-restore",
            )
        master.send_signal(_signal.SIGTERM)
        try:
            out, _ = master.communicate(timeout=60)
            master_done = "master done" in out
        except subprocess.TimeoutExpired:
            failures.append("master did not shut down on SIGTERM")
        for k, n in enumerate(nodes):
            try:
                node_out[k], _ = n.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                n.kill()
                node_out[k] = ""
    finally:
        for proc in [master, *nodes]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    events = adapt_events()
    degrades = sum(1 for e in events if e["to"] > e["from"])
    restores = sum(1 for e in events if e["to"] < e["from"])
    max_errs: dict[int, float] = {}
    for k, out in node_out.items():
        m = re.search(r"max_err=([0-9.eE+-]+)", out or "")
        if m:
            max_errs[k] = float(m.group(1))
    # assertions over the collected evidence
    if not events:
        failures.append("controller never made a transition")
    if degrades + restores > args.max_transitions:
        failures.append(
            f"{degrades + restores} mode transitions > bound "
            f"{args.max_transitions} (oscillation)"
        )
    if events and events[-1]["to"] != 0:
        failures.append(
            f"controller ended at level {events[-1]['to']}, not restored"
        )
    if not any(e.get("policy", "").startswith("int8") for e in events):
        failures.append("controller never reached the int8 wire mode")
    if len(max_errs) < args.nodes:
        failures.append(
            f"max_err evidence from only {sorted(max_errs)} of "
            f"{args.nodes} node(s)"
        )
    for k, err in sorted(max_errs.items()):
        if err > args.err_budget:
            failures.append(
                f"node {k} reduced-value error {err:.4f} exceeds the EF "
                f"budget {args.err_budget}"
            )
    if not master_done:
        failures.append("master did not finish cleanly")
    decision_log = None
    if os.path.exists(adapt_log):
        with open(adapt_log) as f:
            decision_log = [json.loads(ln) for ln in f if ln.strip()]

    summary = {
        "seed": args.seed,
        "spec": spec,
        "rounds_completed": full_rounds(),
        "adapt_events": events,
        "decision_log": decision_log,
        "degrades": degrades,
        "restores": restores,
        "max_err": max_errs,
        "err_budget": args.err_budget,
        "master_done": master_done,
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def _cmd_chaos_train_node(argv: list[str]) -> int:
    """Tier-7 node role (RESILIENCE.md "Tier 7 — workload resilience"):
    one REAL trainer family, ElasticTrainer-wrapped, riding the TCP
    cluster. The cluster's membership view drives the wrapper's
    snapshot -> rebuild -> restore re-mesh between steps, and the
    leader's RoundPolicy wire stamp drives the trainer's ICI compress
    mode through the same factory rebuild path — the ``chaos-train``
    drill spawns one of these per cluster node."""
    p = argparse.ArgumentParser(
        "chaos-train-node",
        description="training node driving an ElasticTrainer-wrapped real "
        "trainer; membership re-meshes and RoundPolicy compress changes "
        "follow the cluster",
    )
    p.add_argument("--seed", required=True, help="master host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--node-id", type=int, required=True,
        help="this node's id AND its device-group index (the drill "
        "assigns 0..nodes-1 so every process re-meshes identically)",
    )
    p.add_argument(
        "--nodes", type=int, required=True,
        help="planned cluster size: the local virtual-device mesh is "
        "partitioned into this many node device groups",
    )
    p.add_argument(
        "--family", choices=("dp", "zero1", "fsdp", "pipeline"),
        default="dp",
        help="which real trainer family rides the elastic cycle "
        "(train/zoo.py)",
    )
    p.add_argument("--model-seed", type=int, default=0)
    p.add_argument("--elastic-rate", type=float, default=0.5)
    p.add_argument(
        "--min-nodes", type=int, default=1,
        help="below this many live nodes the learner PAUSES (holds "
        "position) instead of stepping — recovery resumes it",
    )
    p.add_argument(
        "--max-steps", type=int, default=0,
        help="0 = train until the master broadcasts Shutdown",
    )
    p.add_argument(
        "--warmup-steps", type=int, default=8,
        help="local steps taken BEFORE joining the cluster (compile + a "
        "real loss trajectory first; rounds only start once every node "
        "joined, so a round-triggered kill lands mid-training)",
    )
    p.add_argument("--metrics-out", default=None, help="per-step JSONL path")
    p.add_argument("--chaos-log", default=None, metavar="FILE")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    # the drill is the operator here: opt into the old-jax shims BEFORE
    # any mesh is built (a no-op on modern jax — see _jax_compat)
    import akka_allreduce_tpu._jax_compat  # noqa: F401
    import asyncio

    import jax
    import numpy as np

    from akka_allreduce_tpu.control.cluster import Endpoint
    from akka_allreduce_tpu.train import ElasticClusterNode
    from akka_allreduce_tpu.train import zoo
    from akka_allreduce_tpu.utils.metrics import MetricsLogger

    per = zoo.devices_per_node(args.family)
    devices = jax.devices()
    if len(devices) < args.nodes * per:
        raise SystemExit(
            f"{args.family} needs {args.nodes * per} devices "
            f"({per}/node), have {len(devices)}: set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={args.nodes * per}"
        )
    assignment = {
        n: devices[n * per : (n + 1) * per] for n in range(args.nodes)
    }
    elastic = zoo.make_elastic(
        args.family, assignment,
        seed=args.model_seed, min_nodes=args.min_nodes,
    )
    ds = zoo.dataset_for(args.family)
    step_seq = {"i": 0}

    def batches(trainer):
        # this node's OWN data shard: the seed offset folds the node id,
        # the batch geometry follows the LIVE trainer (re-mesh aware)
        step_seq["i"] += 1
        return zoo.batch_for(
            args.family, ds, elastic,
            seed_offset=args.node_id * 100_003 + step_seq["i"],
        )

    logger = MetricsLogger(args.metrics_out) if args.metrics_out else None

    def on_step(m) -> None:
        if logger is None:
            return
        logger.log_event(
            kind="train_step",
            step=m.step,
            loss=round(float(m.loss), 6),
            contributors=float(m.contributors),
            generation=elastic.generation,
            members=list(elastic.member_nodes),
            n_devices=elastic.n_devices,
            compress=elastic.compress_mode or "full",
            # pipeline restage evidence (the drill pins the gcd rule)
            stages=getattr(elastic.trainer, "stages", None),
        )

    async def run() -> int:
        cnode = ElasticClusterNode(
            Endpoint.parse(args.seed),
            elastic,
            batches,
            elastic_rate=args.elastic_rate,
            host=args.host,
            port=args.port,
            preferred_node_id=args.node_id,
            on_step=on_step,
            # real OS process: the chaos `crash` fault may os._exit here
            # (the drill's seeded mid-step node kill)
            allow_crash=True,
            chaos_log=args.chaos_log,
        )
        t0 = time.perf_counter()
        steps = await cnode.run(
            args.max_steps or None, warmup_steps=args.warmup_steps
        )
        dt = time.perf_counter() - t0
        losses = cnode.losses
        trend = (
            f"loss {losses[0]:.4f} -> {losses[-1]:.4f}"
            if losses
            else "no steps taken"
        )
        print(
            f"trained {steps} steps in {dt:.1f}s "
            f"({cnode.rounds_applied} sync rounds applied) "
            f"remeshes={cnode.remeshes} "
            f"compress_changes={cnode.compress_changes} "
            f"generation={elastic.generation} "
            f"final_compress={elastic.compress_mode or 'full'}; {trend}",
            flush=True,
        )
        return 0

    rc = asyncio.run(run())
    if logger is not None:
        logger.close()
    return rc


def _cmd_chaos_train(argv: list[str]) -> int:
    """Workload-resilience drill (RESILIENCE.md "Tier 7", ISSUE 14
    acceptance): a real master + N ``chaos-train-node`` processes — each
    driving an ElasticTrainer-wrapped REAL trainer of one family — run an
    open-ended round budget; a SEEDED ``crash:node=K,at=roundN`` kills
    one node mid-train-step. The drill asserts, from the processes' own
    evidence: the crash was the injected one (exit 23); every survivor
    re-meshed (snapshot -> rebuild over the survivors' devices ->
    restore) and its loss trajectory RESUMED within the pinned band (the
    restore lost no optimizer state); rounds kept completing at the
    reduced membership (zero wedged rounds); and the run finished
    gracefully. ``make chaos-train`` runs the fixed-seed pipeline arm —
    the restage case."""
    p = argparse.ArgumentParser(
        "chaos-train",
        description="seeded mid-step node kill under a real trainer "
        "family; assert loss-curve continuity across the re-mesh, zero "
        "wedged rounds, graceful completion",
    )
    p.add_argument("--seed", type=int, default=1234, help="chaos seed")
    p.add_argument(
        "--family", choices=("dp", "zero1", "fsdp", "pipeline"),
        default="pipeline",
    )
    p.add_argument(
        "--nodes", type=int, default=0,
        help="cluster size (0 = family default: 4 for pipeline — enough "
        "devices that a node loss RESTAGES the trunk — else 3)",
    )
    p.add_argument(
        "--kill-at-round", type=int, default=30,
        help="allreduce round at which the victim's seeded crash fires",
    )
    p.add_argument(
        "--post-rounds", type=int, default=25,
        help="survivor-membership rounds that must complete AFTER the "
        "kill (the zero-wedged-rounds evidence)",
    )
    p.add_argument(
        "--post-steps", type=int, default=6,
        help="post-re-mesh train steps each survivor must log (the "
        "loss-continuity sample)",
    )
    p.add_argument(
        "--warmup-steps", type=int, default=8,
        help="per-node local steps BEFORE joining (rounds, and so the "
        "round-triggered kill, start only once every node joined — the "
        "victim dies mid-training, not mid-compile)",
    )
    p.add_argument(
        "--loss-band", type=float, default=0.35,
        help="pinned continuity band: each survivor's median loss over "
        "its first post-re-mesh steps must stay within (1 + band) x its "
        "median over the last pre-kill steps (+0.05 absolute slack for "
        "near-converged curves) — a restore that lost optimizer state "
        "resets the curve and blows this bar",
    )
    p.add_argument("--phase-timeout", type=float, default=300.0)
    p.add_argument("--th", type=float, default=0.66)
    p.add_argument("--heartbeat", type=float, default=0.25)
    p.add_argument("--chunk", type=int, default=16384)
    p.add_argument(
        "--streams", type=int, default=1,
        help="data-plane sockets per endpoint (distributed via Welcome)",
    )
    p.add_argument(
        "--adapt", action="store_true",
        help="also run the leader's AdaptiveController (the ICI "
        "compress-follows-policy plumbing is live either way; the "
        "dedicated pin lives in tests/test_chaos_train.py)",
    )
    p.add_argument("--out-dir", default="chaos_train_run")
    _add_drill_gossip_flags(p)
    _add_drill_lever_flags(p)
    args = p.parse_args(argv)

    import json
    import os
    import re
    import signal as _signal
    import statistics
    import subprocess

    from akka_allreduce_tpu.control.chaos import parse_spec

    nodes = args.nodes or (4 if args.family == "pipeline" else 3)
    victim = nodes - 1
    spec = f"crash:node={victim},at=round{args.kill_at_round}"
    try:
        parse_spec(spec)
    except ValueError as e:
        p.error(str(e))
    os.makedirs(args.out_dir, exist_ok=True)
    metrics_path = os.path.join(args.out_dir, "rounds.jsonl")
    node_jsonl = {
        k: os.path.join(args.out_dir, f"train-node{k}.jsonl")
        for k in range(nodes)
    }
    for f in (metrics_path, *node_jsonl.values()):
        if os.path.exists(f):
            os.remove(f)  # MetricsLogger appends; one run per file

    # size the cluster's data plane to the family model (the elastic-
    # averaging payload IS the flat params) — built on one device, cheap;
    # the parent opts into the old-jax shims exactly like the node role
    import akka_allreduce_tpu._jax_compat  # noqa: F401
    from akka_allreduce_tpu.train import zoo

    size = zoo.family_param_count(args.family)
    print(f"{args.family}: {size} params -> data_size {size}", flush=True)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # every node process simulates the SAME global device set locally
        # (node k owns device group k), so their re-meshes agree
        "XLA_FLAGS": "--xla_force_host_platform_device_count="
        f"{nodes * zoo.devices_per_node(args.family)}",
    }
    spawn = _drill_spawn(env)

    failures: list[str] = []
    await_phase = _drill_phase_waiter(args.phase_timeout, failures)

    def node_steps(k: int) -> list[dict]:
        return [
            r
            for r in _drill_jsonl_records(node_jsonl[k])
            if r.get("kind") == "train_step"
        ]

    def survivor_rounds() -> int:
        return _drill_full_rounds(metrics_path, nodes - 1)

    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(nodes),
        "--rounds", "-1", "--size", str(size),
        "--chunk", str(args.chunk), "--th", str(args.th),
        "--heartbeat", str(args.heartbeat),
        "--streams", str(args.streams),
        "--chaos-seed", str(args.seed), "--chaos-spec", spec,
        "--chaos-log", os.path.join(args.out_dir, "chaos-master.jsonl"),
        "--metrics-out", metrics_path,
        *(["--adapt"] if args.adapt else []),
        *_drill_gossip_args(args),
        *_drill_lever_args(args),
    )
    procs: list = []
    node_out: dict[int, str] = {}
    master_done = False
    victim_rc: int | None = None
    try:
        seed_ep = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed_ep = line.split()[-1]
                break
        if seed_ep is None:
            raise RuntimeError("master never reported its endpoint")
        procs = [
            spawn(
                "chaos-train-node", "--seed", seed_ep,
                "--node-id", str(k), "--nodes", str(nodes),
                "--family", args.family,
                "--warmup-steps", str(args.warmup_steps),
                "--metrics-out", node_jsonl[k],
                "--chaos-log",
                os.path.join(args.out_dir, f"chaos-node{k}.jsonl"),
            )
            for k in range(nodes)
        ]
        # phase 1: every node trained its warm-up trajectory (these steps
        # run BEFORE the join, so the round-triggered kill cannot fire
        # until every node is genuinely training)
        warm = max(1, args.warmup_steps)
        await_phase(
            lambda: all(len(node_steps(k)) >= warm for k in range(nodes)),
            "every node's warm-up trajectory",
        )
        # phase 2: the seeded crash takes the victim down (exit 23)
        if not failures:
            await_phase(
                lambda: procs[victim].poll() is not None,
                f"the seeded crash of node {victim}",
            )
            victim_rc = procs[victim].poll()
        # phase 3: every survivor re-meshed to the surviving membership
        survivors = [k for k in range(nodes) if k != victim]
        want = sorted(survivors)

        def remeshed(k: int) -> bool:
            return any(
                r["generation"] >= 1 and r.get("members") == want
                for r in node_steps(k)
            )

        if not failures:
            await_phase(
                lambda: all(remeshed(k) for k in survivors),
                "every survivor's re-mesh to the surviving membership",
            )
        # phase 4: loss continuity sample + zero wedged rounds — the
        # reduced membership keeps completing rounds AND steps
        if not failures:

            def post_steps(k: int) -> int:
                return sum(
                    1 for r in node_steps(k) if r["generation"] >= 1
                )

            target = survivor_rounds() + args.post_rounds
            await_phase(
                lambda: survivor_rounds() >= target
                and all(
                    post_steps(k) >= args.post_steps for k in survivors
                ),
                f"{args.post_rounds} survivor-membership rounds and "
                f"{args.post_steps} post-re-mesh steps per survivor",
            )
        master.send_signal(_signal.SIGTERM)
        try:
            out, _ = master.communicate(timeout=60)
            master_done = "master done" in out
        except subprocess.TimeoutExpired:
            failures.append("master did not shut down on SIGTERM")
        for k, n in enumerate(procs):
            try:
                node_out[k], _ = n.communicate(timeout=90)
            except subprocess.TimeoutExpired:
                n.kill()
                node_out[k] = ""
    finally:
        for proc in [master, *procs]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    # -- assertions over the collected evidence ------------------------------
    if victim_rc is None:
        victim_rc = procs[victim].poll() if procs else None
    if victim_rc != 23:
        failures.append(
            f"victim exited {victim_rc}, not the chaos crash exit 23"
        )
    survivors = [k for k in range(nodes) if k != victim]
    continuity: dict[int, dict] = {}
    for k in survivors:
        steps = node_steps(k)
        pre = [r["loss"] for r in steps if r["generation"] == 0]
        post = [r["loss"] for r in steps if r["generation"] >= 1]
        if not pre or len(post) < args.post_steps:
            failures.append(
                f"node {k}: not enough steps for the continuity check "
                f"(pre={len(pre)}, post={len(post)})"
            )
            continue
        pre_med = statistics.median(pre[-args.post_steps:])
        post_med = statistics.median(post[: args.post_steps])
        bar = pre_med * (1.0 + args.loss_band) + 0.05
        continuity[k] = {
            "pre_median": round(pre_med, 4),
            "post_median": round(post_med, 4),
            "bar": round(bar, 4),
        }
        if not (post_med <= bar):
            failures.append(
                f"node {k}: post-re-mesh median loss {post_med:.4f} "
                f"exceeds the continuity bar {bar:.4f} "
                f"(pre-kill median {pre_med:.4f}, band {args.loss_band})"
            )
        if any(not np_isfinite(loss) for loss in pre + post):
            failures.append(f"node {k}: non-finite loss in the trajectory")
        if args.family == "pipeline":
            # the restage rule, end to end: at the surviving membership
            # the trunk must run at S' = gcd(live devices, n_layers)
            # stages (train/zoo.py pins n_layers=4; a DP-only fallback
            # would show stages == 1 here and is equally legal only when
            # the gcd says so)
            import math as _math

            n_live = len(survivors) * 2  # zoo: 2 devices per node
            want_pp = _math.gcd(n_live, 4)
            at_survivors = [
                r for r in steps if r.get("members") == sorted(survivors)
            ]
            bad = [
                r["stages"] for r in at_survivors if r["stages"] != want_pp
            ]
            if not at_survivors:
                failures.append(
                    f"node {k}: no steps at the surviving membership"
                )
            elif bad:
                failures.append(
                    f"node {k}: restaged to {bad[0]} stages, expected "
                    f"{want_pp} (gcd of {n_live} devices and 4 layers)"
                )
    summaries: dict[int, dict] = {}
    for k in survivors:
        out = node_out.get(k, "")
        m = re.search(
            r"trained (\d+) steps .*remeshes=(\d+) compress_changes=(\d+) "
            r"generation=(\d+) final_compress=(\S+);",
            out or "",
        )
        if not m:
            failures.append(f"node {k} never reported its summary line")
            continue
        summaries[k] = {
            "steps": int(m.group(1)),
            "remeshes": int(m.group(2)),
            "compress_changes": int(m.group(3)),
            "generation": int(m.group(4)),
            "final_compress": m.group(5),
        }
        if int(m.group(2)) < 1:
            failures.append(f"node {k} reported zero re-meshes")
    if not master_done:
        failures.append("master did not finish cleanly")

    summary = {
        "seed": args.seed,
        "family": args.family,
        "spec": spec,
        "nodes": nodes,
        "victim": victim,
        "victim_exit": victim_rc,
        "survivor_rounds": survivor_rounds(),
        "continuity": continuity,
        "loss_band": args.loss_band,
        "node_summaries": summaries,
        "master_done": master_done,
        "failures": failures,
    }
    print(json.dumps(summary))
    return 0 if not failures else 1


def np_isfinite(x) -> bool:
    """math.isfinite over drill-JSON floats (no numpy import needed in
    the drill parent's assertion path)."""
    import math

    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


def _cmd_obs(argv: list[str]) -> int:
    """Observability toolbox: run the 2-process trace demo, inspect flight
    dumps, merge per-process Perfetto traces (OBSERVABILITY.md)."""
    p = argparse.ArgumentParser(
        "obs",
        description="observability tools: trace demo, flight-dump inspect, "
        "trace merge",
    )
    sub = p.add_subparsers(dest="action", required=True)

    d = sub.add_parser(
        "demo",
        help="run a tiny local cluster (master + N node processes), emit a "
        "merged Perfetto trace + per-role metrics snapshots",
    )
    d.add_argument("--out-dir", default="trace_demo")
    d.add_argument("--nodes", type=int, default=2)
    d.add_argument("--rounds", type=int, default=3)
    d.add_argument("--size", type=int, default=65536)
    d.add_argument("--chunk", type=int, default=8192)

    i = sub.add_parser(
        "inspect", help="summarize a flight-recorder JSONL dump"
    )
    i.add_argument("file")

    m = sub.add_parser(
        "merge-trace",
        help="merge per-process Chrome/Perfetto trace files into one",
    )
    m.add_argument("--out", required=True)
    m.add_argument("inputs", nargs="+")

    args = p.parse_args(argv)
    import json

    if args.action == "merge-trace":
        from akka_allreduce_tpu.obs import trace as obs_trace

        out = obs_trace.merge_chrome_traces(args.inputs, args.out)
        print(f"merged {len(args.inputs)} trace file(s) into {out}")
        return 0

    if args.action == "inspect":
        lines = []
        with open(args.file) as f:
            for ln in f:
                if ln.strip():
                    lines.append(json.loads(ln))
        header = next(
            (l for l in lines if l.get("kind") == "flight_header"), {}
        )
        state = next((l for l in lines if l.get("kind") == "state"), {})
        metrics = next((l for l in lines if l.get("kind") == "metrics"), {})
        spans = [l for l in lines if l.get("kind") == "span"]
        events = [l for l in lines if l.get("kind") == "event"]
        print(
            json.dumps(
                {
                    "reason": header.get("reason"),
                    "pid": header.get("pid"),
                    "round_in_flight": state.get("worker.round_in_flight"),
                    "last_transport_stage": state.get("transport.last_stage"),
                    "stalled_round": state.get("watchdog.stalled_round"),
                    "spans": len(spans),
                    "events": len(events),
                    "rounds_completed": metrics.get("worker.rounds_completed"),
                    "dropped": {
                        k.removeprefix("transport.dropped."): v
                        for k, v in metrics.items()
                        if k.startswith("transport.dropped.") and v
                    },
                },
                indent=2,
            )
        )
        return 0

    # demo: one master + N nodes as real OS processes over loopback, each
    # writing its own Perfetto trace; merged at the end so one allreduce
    # round reads as a single timeline across every process
    return _run_obs_demo(args)


def _run_obs_demo(args) -> int:
    import json
    import os

    os.makedirs(args.out_dir, exist_ok=True)
    traces = [os.path.join(args.out_dir, "trace-master.json")]
    metrics_path = os.path.join(args.out_dir, "metrics-master.jsonl")
    for f in (metrics_path, *traces):
        if os.path.exists(f):
            os.remove(f)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    spawn = _drill_spawn(env)
    master = spawn(
        "cluster-master", "--port", "0", "--nodes", str(args.nodes),
        "--rounds", str(args.rounds), "--size", str(args.size),
        "--chunk", str(args.chunk), "--heartbeat", "0.1",
        "--trace-out", traces[0], "--metrics-out", metrics_path,
    )
    nodes = []
    try:
        seed = None
        for line in master.stdout:
            if line.startswith("master listening on "):
                seed = line.split()[-1]
                break
        if seed is None:
            raise RuntimeError("master never reported its endpoint")
        for k in range(args.nodes):
            t = os.path.join(args.out_dir, f"trace-node{k}.json")
            node_metrics = os.path.join(
                args.out_dir, f"metrics-node{k}.jsonl"
            )
            # MetricsLogger appends: stale files from a previous demo run
            # would mix two runs' records in one artifact
            for f in (t, node_metrics):
                if os.path.exists(f):
                    os.remove(f)
            traces.append(t)
            nodes.append(
                spawn(
                    "cluster-node", "--seed", seed, "--trace-out", t,
                    "--metrics-out", node_metrics,
                )
            )
        master.communicate(timeout=120)
        for n in nodes:
            n.communicate(timeout=60)
    finally:
        for proc in [master, *nodes]:
            if proc.poll() is None:
                proc.kill()

    from akka_allreduce_tpu.obs import trace as obs_trace

    merged = obs_trace.merge_chrome_traces(
        traces, os.path.join(args.out_dir, "trace.json")
    )
    with open(merged) as f:
        events = json.load(f)["traceEvents"]
    by_trace: dict[str, set] = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, set()).add(e["cat"])
    full = [
        t for t, cats in by_trace.items()
        if {"line_master", "worker", "transport"} <= cats
    ]
    print(
        f"demo: {len(events)} spans, {len(by_trace)} traces, "
        f"{len(full)} round trace(s) spanning line_master+worker+transport"
    )
    print(f"merged Perfetto trace: {merged} (open at https://ui.perfetto.dev)")
    print(f"metrics snapshots: {args.out_dir}/metrics-*.jsonl")
    return 0 if full else 1


COMMANDS = {
    "local-demo": _cmd_local_demo,
    "cluster-master": _cmd_cluster_master,
    "cluster-node": _cmd_cluster_node,
    "cluster-standby": _cmd_cluster_standby,
    "train-cluster-master": _cmd_train_cluster_master,
    "train-cluster-node": _cmd_train_cluster_node,
    "bench": _cmd_bench,
    "bench-suite": _cmd_bench_suite,
    "bench-mfu": _cmd_bench_mfu,
    "bench-checkpoint": _cmd_bench_checkpoint,
    "soak": _cmd_soak,
    "train-mlp": _cmd_train_mlp,
    "train-resnet": _cmd_train_resnet,
    "train-zero1": _cmd_train_zero1,
    "train-fsdp": _cmd_train_fsdp,
    "train-lm": _cmd_train_lm,
    "train-moe": _cmd_train_moe,
    "train-pp": _cmd_train_pp,
    "lm-generate": _cmd_lm_generate,
    "elastic-demo": _cmd_elastic_demo,
    "obs": _cmd_obs,
    "bench-wire": _cmd_bench_wire,
    "chaos": _cmd_chaos,
    "chaos-recover": _cmd_chaos_recover,
    "chaos-failover": _cmd_chaos_failover,
    "chaos-adapt": _cmd_chaos_adapt,
    "chaos-gossip": _cmd_chaos_gossip,
    "chaos-scale": _cmd_chaos_scale,
    "chaos-train": _cmd_chaos_train,
    "chaos-train-node": _cmd_chaos_train_node,
}


def main(argv: list[str] | None = None) -> int:
    # the axon TPU plugin overrides JAX_PLATFORMS at import time; re-assert
    # the user's explicit platform choice (ONE copy of the dance:
    # utils/platform.py)
    from akka_allreduce_tpu.utils import respect_env_platform

    respect_env_platform()
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("commands:", ", ".join(COMMANDS))
        return 0
    cmd = argv[0]
    if cmd not in COMMANDS:
        print(f"unknown command {cmd!r}; expected one of {sorted(COMMANDS)}")
        return 2
    return COMMANDS[cmd](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
