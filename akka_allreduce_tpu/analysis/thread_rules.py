"""THRD001/THRD002 — thread-vs-event-loop shared-state races.

The PR-9 review fixed, by hand, a class of bug the transport keeps inviting:
endpoint telemetry dicts mutated from sender *threads* while the event loop
read or mutated them concurrently, and pull-time collectors iterating those
dicts mid-mutation (fixed with a ``list()`` snapshot). These rules make that
review pass mechanical, on top of the ``contexts`` call-graph classifier:

- **THRD001** — a ``self`` attribute or module global is mutated from both a
  thread context and the event-loop context, and at least one mutation site
  is not inside a ``with <lock>:`` guard. Every cross-context site must hold
  the owning lock: one unguarded writer is enough to corrupt the rest.
- **THRD002** — iteration over a ``self`` collection that a *different*
  execution context mutates, without a ``list()``/``sorted()`` snapshot or a
  lock around the iteration (``RuntimeError: dictionary changed size`` is the
  friendly failure mode; silently skipping an entry is the real one).

Both rules only speak when the call graph *proves* two contexts touch the
same state — a function the classifier cannot reach from a thread target or
a coroutine stays silent (sync-anywhere), so an unresolvable callee can only
miss a finding, never invent one. ``__init__``-family constructors are
exempt: they run before any thread exists.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.contexts import (
    LOOP,
    THREAD,
    ContextMap,
    FuncInfo,
    _locked_body_walk,
    build_context_map,
)
from akka_allreduce_tpu.analysis.core import Finding

# collection-mutating method names: calling one of these on shared state IS
# a write, even though no assignment statement appears
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "add",
        "extend",
        "insert",
        "remove",
        "discard",
        "pop",
        "popleft",
        "popitem",
        "clear",
        "update",
        "setdefault",
    }
)

_SNAPSHOT_FUNCS = frozenset({"list", "tuple", "sorted", "set", "frozenset"})

_CONSTRUCTORS = frozenset({"__init__", "__post_init__", "__new__", "__init_subclass__"})


@dataclasses.dataclass(frozen=True)
class _Site:
    func: FuncInfo
    line: int
    locked: bool
    #: "assign" (rebind), "item" (subscript store/del), "method" (mutator call)
    kind: str


def _self_attr_base(node: ast.AST) -> str | None:
    """First attribute above ``self`` in a ``self.X[...]...`` chain."""
    chain: list[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]
    return None


def _flat_targets(target: ast.AST) -> Iterator[ast.AST]:
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flat_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _flat_targets(target.value)
    else:
        yield target


def _local_names(func: ast.AST) -> set[str]:
    """Names bound locally in ``func`` (so a bare-Name mutator call on one is
    not misread as touching a same-named module global)."""
    out: set[str] = set()
    args = getattr(func, "args", None)
    if args is not None:
        for a in (
            *args.posonlyargs,
            *args.args,
            *args.kwonlyargs,
            *( [args.vararg] if args.vararg else [] ),
            *( [args.kwarg] if args.kwarg else [] ),
        ):
            out.add(a.arg)
    for node, _ in _locked_body_walk(func):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for leaf in _flat_targets(t):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for leaf in _flat_targets(node.target):
                if isinstance(leaf, ast.Name):
                    out.add(leaf.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for leaf in _flat_targets(item.optional_vars):
                        if isinstance(leaf, ast.Name):
                            out.add(leaf.id)
    return out


def _collect_sites(
    info: FuncInfo,
    module_names: set[str],
) -> tuple[
    dict[str, list[_Site]],  # self attr -> mutation sites
    dict[str, list[_Site]],  # module global -> mutation sites
    dict[str, list[_Site]],  # self attr -> iteration sites
]:
    attr_muts: dict[str, list[_Site]] = {}
    global_muts: dict[str, list[_Site]] = {}
    iters: dict[str, list[_Site]] = {}

    declared_globals: set[str] = set()
    for node, _ in _locked_body_walk(info.node):
        if isinstance(node, ast.Global):
            declared_globals.update(node.names)
    locals_ = _local_names(info.node)

    def mut_attr(name: str, line: int, locked: bool, kind: str) -> None:
        attr_muts.setdefault(name, []).append(_Site(info, line, locked, kind))

    def mut_global(name: str, line: int, locked: bool, kind: str) -> None:
        global_muts.setdefault(name, []).append(_Site(info, line, locked, kind))

    def target_mut(t: ast.AST, line: int, locked: bool) -> None:
        if isinstance(t, ast.Attribute):
            base = _self_attr_base(t)
            if base is not None:
                mut_attr(base, line, locked, "assign")
        elif isinstance(t, ast.Subscript):
            base = _self_attr_base(t)
            if base is not None:
                mut_attr(base, line, locked, "item")
            elif isinstance(t.value, ast.Name) and (
                t.value.id in declared_globals
                or (t.value.id in module_names and t.value.id not in locals_)
            ):
                mut_global(t.value.id, line, locked, "item")
        elif isinstance(t, ast.Name) and t.id in declared_globals:
            mut_global(t.id, line, locked, "assign")

    def iter_site(expr: ast.AST, line: int, locked: bool) -> None:
        if isinstance(expr, ast.Call):
            fname = expr.func.id if isinstance(expr.func, ast.Name) else None
            if fname in _SNAPSHOT_FUNCS:
                return  # snapshotted — the PR-9 fix shape
            if (
                isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("items", "values", "keys")
            ):
                expr = expr.func.value
            else:
                return
        base = _self_attr_base(expr)
        if base is not None:
            iters.setdefault(base, []).append(_Site(info, line, locked, "iter"))

    for node, locked in _locked_body_walk(info.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in _flat_targets(t):
                    target_mut(leaf, node.lineno, locked)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node, ast.AnnAssign) and node.value is None:
                continue
            target_mut(node.target, node.lineno, locked)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                target_mut(t, node.lineno, locked)
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                obj = node.func.value
                base = _self_attr_base(obj)
                if base is not None:
                    mut_attr(base, node.lineno, locked, "method")
                elif isinstance(obj, ast.Name) and (
                    obj.id in declared_globals
                    or (obj.id in module_names and obj.id not in locals_)
                ):
                    mut_global(obj.id, node.lineno, locked, "method")
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            iter_site(node.iter, node.lineno, locked)
        elif isinstance(
            node, (ast.ListComp, ast.GeneratorExp, ast.DictComp, ast.SetComp)
        ):
            for gen in node.generators:
                iter_site(gen.iter, node.lineno, locked)
    return attr_muts, global_muts, iters


def _ctx_desc(ctx: frozenset[str]) -> str:
    if THREAD in ctx and LOOP in ctx:
        return "both thread and event-loop"
    if THREAD in ctx:
        return "thread"
    return "event-loop"


def _cross_context(sites: list[tuple[_Site, frozenset[str]]]) -> bool:
    has_thread = any(THREAD in ctx for _, ctx in sites)
    has_loop = any(LOOP in ctx for _, ctx in sites)
    return has_thread and has_loop


def check_thread_safety(
    trees: dict[str, ast.AST],
    config: ArlintConfig,
    *,
    root=None,
) -> list[Finding]:
    cmap: ContextMap = build_context_map(trees)
    findings: list[Finding] = []

    # -- group mutation/iteration sites by shared variable -------------------
    #    self attrs are shared per (path, class); globals per (path, name)
    attr_muts: dict[tuple[str, str, str], list[tuple[_Site, frozenset[str]]]] = {}
    glob_muts: dict[tuple[str, str], list[tuple[_Site, frozenset[str]]]] = {}
    attr_iters: dict[tuple[str, str, str], list[tuple[_Site, frozenset[str]]]] = {}

    for path in sorted(trees):
        idx = cmap.indexes[path]
        for qual in sorted(idx.funcs):
            info = idx.funcs[qual]
            if info.node.name in _CONSTRUCTORS:
                continue
            ctx = cmap.contexts_of(info.key)
            a_muts, g_muts, iters = _collect_sites(info, idx.module_names)
            if info.cls is not None:
                for name, sites in a_muts.items():
                    attr_muts.setdefault((path, info.cls, name), []).extend(
                        (s, ctx) for s in sites
                    )
                for name, sites in iters.items():
                    attr_iters.setdefault((path, info.cls, name), []).extend(
                        (s, ctx) for s in sites
                    )
            for name, sites in g_muts.items():
                glob_muts.setdefault((path, name), []).extend(
                    (s, ctx) for s in sites
                )

    # -- THRD001: unguarded cross-context mutation ----------------------------
    def thrd001(what: str, path: str, sites) -> None:
        colored = [(s, ctx) for s, ctx in sites if ctx]
        if not _cross_context(colored):
            return
        unguarded = sorted(
            ((s, ctx) for s, ctx in colored if not s.locked),
            key=lambda sc: sc[0].line,
        )
        for s, ctx in unguarded:
            other_color = LOOP if THREAD in ctx else THREAD
            others = sorted(
                (o for o, octx in colored if other_color in octx and o is not s),
                key=lambda o: o.line,
            )
            if others:
                other = others[0]
                counterpart = (
                    f"also mutated from {_ctx_desc(cmap.contexts_of(other.func.key))} "
                    f"context in {other.func.qualname} (line {other.line})"
                )
            else:
                counterpart = (
                    f"{s.func.qualname} is reachable from both contexts"
                )
            findings.append(
                Finding(
                    path,
                    s.line,
                    "THRD001",
                    f"{what} is mutated from both thread and event-loop "
                    f"context, and this {_ctx_desc(ctx)}-context site holds "
                    f"no lock ({counterpart}) — wrap every cross-context "
                    f"mutation in 'with <lock>:' (PR-9 endpoint-telemetry "
                    f"race class)",
                )
            )

    for (path, cls, name), sites in sorted(attr_muts.items()):
        thrd001(f"self.{name} (class {cls})", path, sites)
    for (path, name), sites in sorted(glob_muts.items()):
        thrd001(f"module global '{name}'", path, sites)

    # -- THRD002: unguarded iteration over cross-context-mutated state -------
    for (path, cls, name), isites in sorted(attr_iters.items()):
        msites = [
            (s, ctx)
            for s, ctx in attr_muts.get((path, cls, name), [])
            if ctx and s.kind in ("item", "method")
        ]
        if not msites:
            continue
        for it, ictx in sorted(isites, key=lambda sc: sc[0].line):
            if not ictx:
                continue
            cross = [
                (m, mctx)
                for m, mctx in msites
                if (THREAD in mctx and LOOP in ictx)
                or (LOOP in mctx and THREAD in ictx)
            ]
            if not cross:
                continue
            if it.locked and all(m.locked for m, _ in cross):
                continue
            m, mctx = min(cross, key=lambda mc: mc[0].line)
            findings.append(
                Finding(
                    path,
                    it.line,
                    "THRD002",
                    f"iteration over self.{name} (class {cls}) in "
                    f"{_ctx_desc(ictx)} context while {m.func.qualname} "
                    f"(line {m.line}) mutates it from {_ctx_desc(mctx)} "
                    f"context — snapshot with list(...) under the lock, or "
                    f"hold the lock across the loop (PR-9 collector fix "
                    f"class)",
                )
            )
    return findings
