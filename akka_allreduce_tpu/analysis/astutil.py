"""Shared AST helpers used by every rule module (leaf module: imports
nothing from the rest of the analyzer, so rules/contexts/det/life can all
depend on it without cycles)."""

from __future__ import annotations

import ast
from typing import Iterator


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> str | None:
    """The last identifier of a Name/Attribute/Subscript chain:
    ``self._recv_pool[i]`` -> ``_recv_pool``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def functions(tree: ast.AST) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def direct_body_walk(func: ast.AST) -> Iterator[ast.AST]:
    """Walk ``func``'s body WITHOUT descending into nested function
    definitions (code in a nested def does not run in this frame — an
    ``except`` or blocking call there belongs to the nested function's own
    execution context, which the rules visit separately)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
