"""arlint — the repo's own async-safety / buffer-aliasing / wire-exhaustiveness
static analyzer (``python -m akka_allreduce_tpu.analysis``).

Every rule targets a defect class this codebase has already paid for by hand
(ANALYSIS.md tells each story):

- **ASYNC001** — blocking call (``time.sleep``, ``subprocess.run``, sync
  socket/file IO) inside ``async def``: stalls the event loop that carries
  heartbeats and round traffic.
- **ASYNC002** — coroutine called but never awaited: the body silently never
  runs.
- **ASYNC003** — ``asyncio.create_task``/``ensure_future`` result dropped:
  the task can be garbage-collected mid-flight and its exception is lost.
- **ASYNC004** — ``except Exception:`` / bare ``except`` inside a coroutine
  without an ``asyncio.CancelledError`` escape: can swallow task cancellation
  (the PR-2 ``transport.stop()`` deadlock class).
- **BUF001** — ``np.frombuffer``/``memoryview`` view of a pooled/recycled
  buffer escaping its recycle scope (returned or stored on ``self``): the
  recv-ring aliasing class.
- **WIRE001** — wire-tag exhaustiveness: every tag in ``control/wire._TAGS``
  must have an encode arm, a decode arm, and an ``isinstance`` dispatch arm
  somewhere in the analyzed tree — and no arm may exist for an unknown tag.

No third-party dependencies: stdlib ``ast`` only, so it runs anywhere the
package imports. Suppress a finding inline with ``# arlint: disable=RULE``
(same line) or ``# arlint: disable-next=RULE`` (line above), or via the
checked-in baseline file (``[tool.arlint]`` in pyproject.toml).
"""

from __future__ import annotations

from akka_allreduce_tpu.analysis.config import ArlintConfig, load_config
from akka_allreduce_tpu.analysis.core import (
    Finding,
    analyze_paths,
    analyze_source,
)
from akka_allreduce_tpu.analysis.rules import FILE_RULES
from akka_allreduce_tpu.analysis.wire_rule import check_wire_exhaustiveness

ALL_RULES = (
    "ASYNC001",
    "ASYNC002",
    "ASYNC003",
    "ASYNC004",
    "BUF001",
    "WIRE001",
)

__all__ = [
    "ALL_RULES",
    "ArlintConfig",
    "FILE_RULES",
    "Finding",
    "analyze_paths",
    "analyze_source",
    "check_wire_exhaustiveness",
    "load_config",
]
