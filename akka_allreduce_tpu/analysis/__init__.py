"""arlint — the repo's own async-safety / buffer-aliasing / wire-contract /
thread-race / determinism static analyzer (``python -m
akka_allreduce_tpu.analysis``).

Every rule targets a defect class this codebase has already paid for by hand
(ANALYSIS.md tells each story):

- **ASYNC001** — blocking call (``time.sleep``, ``subprocess.run``, sync
  socket/file IO) inside ``async def``: stalls the event loop that carries
  heartbeats and round traffic.
- **ASYNC002** — coroutine called but never awaited: the body silently never
  runs.
- **ASYNC003** — ``asyncio.create_task``/``ensure_future`` result dropped:
  the task can be garbage-collected mid-flight and its exception is lost.
- **ASYNC004** — ``except Exception:`` / bare ``except`` inside a coroutine
  without an ``asyncio.CancelledError`` escape: can swallow task cancellation
  (the PR-2 ``transport.stop()`` deadlock class).
- **BUF001** — ``np.frombuffer``/``memoryview`` view of a pooled/recycled
  buffer escaping its recycle scope (returned or stored on ``self``): the
  recv-ring aliasing class.
- **DET001/002/003** — wall-clock reads, unseeded RNG, and unsorted-set
  iteration inside the modules declared deterministic via ``[tool.arlint]
  det-modules``: the byte-identical-replay discipline as a gate.
- **LIFE001** — ``observed_task`` handles / ``Thread`` objects / executors
  stored on ``self`` that no ``stop()``/``close()``-family method ever
  references: the PR-13 sender-thread leak class.
- **OBS001** — two-way drift between literal Registry metric names and the
  OBSERVABILITY.md metric table (``obs-doc`` config key).
- **THRD001/002** — v2's cross-function pass: an intra-package call graph
  classifies every function's execution context (event-loop / thread /
  sync-anywhere), then flags ``self``-attribute or module-global mutation
  from both contexts without a lock on every site, and unsnapshotted
  iteration over cross-context-mutated collections (the PR-9
  endpoint-telemetry race and collector fix).
- **WIRE001** — wire-tag exhaustiveness: every tag in ``control/wire._TAGS``
  must have an encode arm, a decode arm, and an ``isinstance`` dispatch arm
  somewhere in the analyzed tree — and no arm may exist for an unknown tag.
- **WIRE002** — version-skew contract: decode arms tolerate trailing bytes
  (no exact ``len(buf)`` equality), wire dataclasses keep new fields
  trailing-with-default, and tag ranges stay unique/contiguous and
  module-owned (``wire-owned`` config key).

No third-party dependencies: stdlib ``ast`` only, so it runs anywhere the
package imports. Suppress a finding inline with ``# arlint: disable=RULE``
(same line) or ``# arlint: disable-next=RULE`` (line above), or via the
checked-in baseline file (``[tool.arlint]`` in pyproject.toml).
"""

from __future__ import annotations

from akka_allreduce_tpu.analysis.config import ArlintConfig, load_config
from akka_allreduce_tpu.analysis.core import (
    Finding,
    analyze_paths,
    analyze_source,
)
from akka_allreduce_tpu.analysis.contexts import ContextMap, build_context_map
from akka_allreduce_tpu.analysis.rules import FILE_RULES
from akka_allreduce_tpu.analysis.wire_rule import (
    check_wire_exhaustiveness,
    check_wire_skew,
)

ALL_RULES = (
    "ASYNC001",
    "ASYNC002",
    "ASYNC003",
    "ASYNC004",
    "BUF001",
    "DET001",
    "DET002",
    "DET003",
    "LIFE001",
    "OBS001",
    "THRD001",
    "THRD002",
    "WIRE001",
    "WIRE002",
)

__all__ = [
    "ALL_RULES",
    "ArlintConfig",
    "ContextMap",
    "FILE_RULES",
    "Finding",
    "analyze_paths",
    "analyze_source",
    "build_context_map",
    "check_wire_exhaustiveness",
    "check_wire_skew",
    "load_config",
]
