"""OBS001 — two-way drift check between Registry metric names and the doc.

OBSERVABILITY.md's metric table is the operator contract: dashboards and the
chaos drills' assertions are written against it. Nothing ties it to the code
— a metric renamed in PR 13 or added in PR 15 drifts silently until someone
greps. This is the WIRE001 pattern applied to the obs layer:

- **forward**: every *literal* metric name created on a Registry —
  ``counter("x.y")`` / ``gauge`` / ``histogram`` / ``series``, including
  f-string names whose formatted fields become ``*`` wildcards — must match
  a row of the metric table (``<placeholder>`` and ``*`` in doc rows match
  any suffix);
- **reverse**: every documented row must have a creation site in the
  analyzed tree, except rows typed as collector-provided (pull-time names
  like ``transport.stage_seconds.<stage>`` have no creation call at all).

Names built from variables (``self.counter(name)`` pass-throughs inside the
registry) are invisible to the rule by design — the contract is enforced at
the literal call sites, which is where this repo creates every metric.

The rule activates only when ``[tool.arlint] obs-doc`` names the document;
the reverse check additionally needs a whole-tree scan (a single-file run
proves nothing about absence).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding

_FACTORIES = ("counter", "gauge", "histogram", "series")
_TOKEN = re.compile(r"`([^`]+)`")
_METRIC_SHAPE = re.compile(r"^[A-Za-z0-9_.*<>:-]+$")


def _pattern_regex(token: str) -> re.Pattern:
    out = []
    i = 0
    while i < len(token):
        ch = token[i]
        if ch == "<":
            end = token.find(">", i)
            if end == -1:
                out.append(re.escape(ch))
                i += 1
                continue
            out.append(".+")
            i = end + 1
        elif ch == "*":
            out.append(".+")
            i += 1
        else:
            out.append(re.escape(ch))
            i += 1
    return re.compile("".join(out))


def _probe(token: str) -> str:
    """Placeholders/wildcards replaced by a literal segment, for matching a
    doc pattern against a creation pattern (or vice versa)."""
    return re.sub(r"<[^>]*>|\*", "x", token)


def _creation_sites(
    trees: dict[str, ast.AST],
) -> list[tuple[str, bool, str, int]]:
    """(name_or_pattern, is_pattern, path, line) for every literal metric
    creation; f-string names contribute a ``*``-wildcard pattern."""
    out: list[tuple[str, bool, str, int]] = []
    for path in sorted(trees):
        for node in ast.walk(trees[path]):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fname = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else node.func.id
                if isinstance(node.func, ast.Name)
                else None
            )
            if fname not in _FACTORIES:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if "." in arg.value:  # dotted names only: skips unrelated
                    out.append((arg.value, False, path, node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                parts = []
                for piece in arg.values:
                    if isinstance(piece, ast.Constant):
                        parts.append(str(piece.value))
                    else:
                        parts.append("*")
                pattern = "".join(parts)
                if "." in pattern:
                    out.append((pattern, True, path, node.lineno))
    return out


def _doc_rows(text: str) -> list[tuple[str, int, str, bool]]:
    """(token, line_number, stripped_line, is_collector) for every metric
    token in the FIRST cell of a table row. A ``.suffix`` continuation token
    inherits the previous token's prefix (``a.b.tx`` / ``.rx`` documents
    ``a.b.rx``)."""
    rows: list[tuple[str, int, str, bool]] = []
    last_full: str | None = None
    for lineno, raw in enumerate(text.splitlines(), start=1):
        stripped = raw.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", ":", " "}:
            continue
        is_collector = "collector" in stripped.lower()
        for token in _TOKEN.findall(cells[0]):
            token = token.strip()
            if not _METRIC_SHAPE.match(token):
                continue
            if token.startswith("."):
                if last_full is None:
                    continue
                suffix_parts = token[1:].split(".")
                base_parts = last_full.split(".")
                if len(base_parts) <= len(suffix_parts):
                    continue
                token = ".".join(
                    base_parts[: -len(suffix_parts)] + suffix_parts
                )
            else:
                if "." not in token and "*" not in token:
                    continue
                last_full = token
            rows.append((token, lineno, stripped, is_collector))
    return rows


def check_obs_doc_drift(
    trees: dict[str, ast.AST],
    config: ArlintConfig,
    *,
    root: Path | None = None,
) -> list[Finding]:
    if config.obs_doc is None:
        return []
    doc_path = Path(config.obs_doc)
    if not doc_path.is_absolute():
        base = (
            config.source.parent
            if config.source is not None
            else (root if root is not None else Path.cwd())
        )
        doc_path = base / doc_path
    creations = _creation_sites(trees)
    if not doc_path.is_file():
        if not creations:
            return []
        name, _, path, line = creations[0]
        return [
            Finding(
                path,
                line,
                "OBS001",
                f"[tool.arlint] obs-doc names {config.obs_doc!r} but the "
                f"file does not exist — metric-name drift cannot be checked",
            )
        ]
    text = doc_path.read_text(encoding="utf-8")
    rows = _doc_rows(text)
    doc_regexes = [(tok, _pattern_regex(tok)) for tok, _, _, _ in rows]
    doc_name = doc_path.name
    try:
        doc_rel = doc_path.resolve().relative_to(
            (root or Path.cwd()).resolve()
        ).as_posix()
    except ValueError:
        doc_rel = doc_path.as_posix()

    findings: list[Finding] = []

    # forward: every creation matches some doc row
    for name, is_pattern, path, line in creations:
        subject = _probe(name) if is_pattern else name
        if any(rx.fullmatch(subject) for _, rx in doc_regexes):
            continue
        findings.append(
            Finding(
                path,
                line,
                "OBS001",
                f"metric '{name}' is created here but no {doc_name} metric-"
                f"table row matches it — document it (placeholder rows like "
                f"'a.b.<kind>' cover dynamic suffixes), or rename to a "
                f"documented family",
            )
        )

    # reverse: every non-collector doc row has a creation site — only
    # meaningful on a whole-tree scan (single-file absence proves nothing)
    if len(trees) > 1:
        exacts = {name for name, is_p, _, _ in creations if not is_p}
        pattern_rx = [
            _pattern_regex(name) for name, is_p, _, _ in creations if is_p
        ]
        for token, lineno, stripped, is_collector in rows:
            if is_collector:
                continue
            rx = _pattern_regex(token)
            probe = _probe(token)
            if (
                token in exacts
                or any(rx.fullmatch(e) for e in exacts)
                or any(prx.fullmatch(probe) for prx in pattern_rx)
            ):
                continue
            findings.append(
                Finding(
                    doc_rel,
                    lineno,
                    "OBS001",
                    f"documented metric '{token}' has no creation site in "
                    f"the analyzed tree — remove the row, fix the name, or "
                    f"mark the row collector-provided",
                    line_content=stripped,
                )
            )
    return findings
