"""LIFE001 — teardown completeness for spawned resources stored on ``self``.

PR 13's review found sender threads that outlived their transport because
``stop()`` tore down the streams but never joined the thread objects stored
on ``self`` — the process exited only because the threads were daemons, and
in-flight frames were silently dropped. The same shape recurs with
``observed_task`` handles (a task ``stop()`` never cancels keeps running
into torn-down state) and executors (``ThreadPoolExecutor`` without
``shutdown()`` leaks its worker threads).

The rule: an assignment ``self.X = observed_task(...)`` / ``create_task`` /
``ensure_future`` / ``threading.Thread(...)`` / ``ThreadPoolExecutor(...)``
inside a class requires ``self.X`` to be *referenced* in at least one
teardown-named method of the same class (``stop``/``close``/``shutdown``/
``teardown``/``aclose``/``__exit__``/``__aexit__``, prefix-matched, so
``stop_sync``/``close_now`` count). Referencing is enough — the rule does
not prove the reference cancels/joins correctly (a human can judge that at
the anchor line); it proves teardown *knows the resource exists*, which is
the invariant the PR-13 bug violated. The dynamic teardown idiom
``for a in ("_x_task", "_y_task"): getattr(self, a).cancel()`` counts too:
when a teardown calls getattr/setattr on ``self``, its string constants
are treated as attribute references. A class with no teardown method at all
is flagged at the spawn site: a spawned resource with no lifecycle owner is
exactly the defect.
"""

from __future__ import annotations

import ast

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding
from akka_allreduce_tpu.analysis.astutil import terminal_name

_SPAWN_CALLS = {
    "observed_task": "cancel (and optionally await) it",
    "create_task": "cancel (and optionally await) it",
    "ensure_future": "cancel (and optionally await) it",
    "Thread": "signal its loop to exit and join() it",
    "ThreadPoolExecutor": "shutdown() it",
    "ProcessPoolExecutor": "shutdown() it",
}

_TEARDOWN_PREFIXES = (
    "stop",
    "close",
    "shutdown",
    "teardown",
    "aclose",
    "dispose",
)
_TEARDOWN_EXACT = {"__exit__", "__aexit__", "__del__", "cancel_all"}


def _is_teardown_name(name: str) -> bool:
    return name in _TEARDOWN_EXACT or any(
        name.startswith(p) or name.startswith("_" + p)
        for p in _TEARDOWN_PREFIXES
    )


def _spawn_in(value: ast.AST) -> str | None:
    """Terminal spawn-call name found anywhere in an assigned value."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            tail = terminal_name(node.func)
            if tail in _SPAWN_CALLS:
                return tail
    return None


def rule_life001(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    findings = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        # attr -> (line, end_line, spawn kind) of the first offending store
        spawns: dict[str, tuple[int, int, str]] = {}
        teardown_refs: set[str] = set()
        has_teardown = False
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _is_teardown_name(method.name):
                has_teardown = True
                # dynamic-attr teardown idiom: `for a in ("_x", "_y"):
                # getattr(self, a).cancel()` references attributes by
                # string. When a getattr/setattr-on-self appears anywhere
                # in the teardown, every string constant in the method
                # counts as a reference — flow-tracking the loop variable
                # is not worth the machinery, and over-counting here can
                # only miss a finding, never invent one.
                dynamic_attr = False
                consts: set[str] = set()
                for node in ast.walk(method):
                    if (
                        isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                    ):
                        teardown_refs.add(node.attr)
                    elif (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id
                        in ("getattr", "setattr", "delattr", "hasattr")
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id == "self"
                    ):
                        dynamic_attr = True
                    elif isinstance(node, ast.Constant) and isinstance(
                        node.value, str
                    ):
                        consts.add(node.value)
                if dynamic_attr:
                    teardown_refs |= consts
                continue
            for node in ast.walk(method):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if node.value is None:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = _spawn_in(node.value)
                    if kind is not None and t.attr not in spawns:
                        spawns[t.attr] = (
                            node.lineno,
                            node.end_lineno or node.lineno,
                            kind,
                        )
        for attr, (line, end_line, kind) in sorted(spawns.items()):
            if attr in teardown_refs:
                continue
            why = (
                f"no stop()/close()-family method of class {cls.name} "
                f"references self.{attr}"
                if has_teardown
                else f"class {cls.name} has no stop()/close()-family "
                f"teardown method at all"
            )
            findings.append(
                Finding(
                    path,
                    line,
                    "LIFE001",
                    f"self.{attr} stores a {kind}(...) but {why} — teardown "
                    f"must {_SPAWN_CALLS[kind]} (PR-13 sender-thread leak "
                    f"class)",
                    end_line=end_line,
                )
            )
    return findings
