"""Execution-context classification over a bounded intra-package call graph.

The v1 rules are syntactically local; the defect classes PR 9/13/15 paid for
are not: a ``self`` attribute is benign until one mutation site runs on a
sender thread while another runs on the event loop. This module is the
substrate those rules (THRD001/002, and future epoch-fence/tenant-isolation
rules) sit on. It stays deliberately *bounded*:

- the call graph is intra-package only (edges resolve through module-level
  functions, ``self.method``, imported-module attributes, and nested defs —
  never through dynamic dispatch, instance attributes, or containers);
- context propagation is a plain BFS with two colors:

  * **loop** — every ``async def`` plus the sync functions they (transitively)
    call, plus callbacks handed to ``add_done_callback`` /
    ``call_soon[_threadsafe]`` / ``call_later`` / ``call_at`` (all run on the
    loop thread);
  * **thread** — every ``threading.Thread(target=...)`` target,
    ``executor.submit``/``loop.run_in_executor``/``asyncio.to_thread``
    callable (the learner-thread pattern included), and their transitive sync
    callees.

A function reached from neither color is *sync-anywhere*: it runs in its
caller's context, and nothing is known — the rules stay silent on it rather
than guess. Over-approximation is asymmetric on purpose: an unresolvable
callee drops the edge (missing an edge can only *miss* a finding, never
invent one).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from akka_allreduce_tpu.analysis.astutil import dotted_name, terminal_name

LOOP = "loop"
THREAD = "thread"

#: ``(path, qualname)`` — the identity of a function in the graph
FuncKey = tuple[str, str]

_THREAD_POOL_METHODS = ("submit",)
_LOOP_CALLBACK_METHODS = (
    "add_done_callback",
    "call_soon",
    "call_soon_threadsafe",
    "call_later",
    "call_at",
)


@dataclasses.dataclass
class FuncInfo:
    path: str
    qualname: str
    node: ast.FunctionDef | ast.AsyncFunctionDef
    #: qualname of the nearest enclosing class (``self`` in a closure nested
    #: inside a method still binds to that method's instance)
    cls: str | None
    is_async: bool

    @property
    def key(self) -> FuncKey:
        return (self.path, self.qualname)


class _ModuleIndex(ast.NodeVisitor):
    """Per-module function/scope/import tables built in one pass."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._stack: list[tuple[str, str]] = []  # ("class"|"func", name)
        self.funcs: dict[str, FuncInfo] = {}  # qualname -> info
        self.top_level: dict[str, str] = {}  # bare name -> qualname
        self.class_methods: dict[str, dict[str, str]] = {}
        self.by_name: dict[str, list[str]] = {}
        #: alias -> ("import", dotted) | ("from", base_module, name)
        self.aliases: dict[str, tuple] = {}
        #: names assigned at module level (global-collection candidates)
        self.module_names: set[str] = set()

    def _qual(self, name: str) -> str:
        return ".".join([n for _, n in self._stack] + [name])

    def _enclosing_class(self) -> str | None:
        parts: list[str] = []
        cls: str | None = None
        for kind, name in self._stack:
            parts.append(name)
            if kind == "class":
                cls = ".".join(parts)
        return cls

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self._stack:
            self.module_names.add(node.name)
        self._stack.append(("class", node.name))
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        direct_cls = (
            ".".join(n for _, n in self._stack)
            if self._stack and self._stack[-1][0] == "class"
            else None
        )
        info = FuncInfo(
            path=self.path,
            qualname=qual,
            node=node,
            cls=self._enclosing_class(),
            is_async=isinstance(node, ast.AsyncFunctionDef),
        )
        self.funcs[qual] = info
        self.by_name.setdefault(node.name, []).append(qual)
        if not self._stack:
            self.top_level[node.name] = qual
            self.module_names.add(node.name)
        if direct_cls is not None:
            self.class_methods.setdefault(direct_cls, {})[node.name] = qual
        self._stack.append(("func", node.name))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".", 1)[0]
            dotted = alias.name if alias.asname else alias.name.split(".")[0]
            self.aliases[name] = ("import", dotted)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            # relative import: resolve against this module's package parts
            parts = self.path[:-3].split("/")  # strip ".py"
            if parts and parts[-1] == "__init__":
                parts = parts[:-1]
            parts = parts[: -node.level] if node.level <= len(parts) else []
            base = ".".join(parts + ([node.module] if node.module else []))
        for alias in node.names:
            if alias.name == "*":
                continue
            self.aliases[alias.asname or alias.name] = (
                "from",
                base,
                alias.name,
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._stack:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.module_names.add(t.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if not self._stack and isinstance(node.target, ast.Name):
            self.module_names.add(node.target.id)
        self.generic_visit(node)


def _module_dotted(path: str) -> str:
    parts = path[:-3].split("/") if path.endswith(".py") else path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _locked_body_walk(
    func: ast.AST, lock_hints: tuple[str, ...] = ("lock", "cond", "mutex", "sem")
) -> Iterator[tuple[ast.AST, bool]]:
    """Like ``_direct_body_walk`` but yields ``(node, locked)`` where
    ``locked`` is True inside a ``with <something named like a lock>:``
    body. The guard test is the context expression's *terminal* name
    (``self._lock`` / ``sender.cond`` / ``ep.tx_mutex`` all count)."""

    def _is_lock(expr: ast.AST) -> bool:
        # `with lock:` and `with await lock.acquire_ctx():` style both
        # resolve through the terminal identifier of the expression
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Await):
            return _is_lock(expr.value)
        name = terminal_name(expr)
        if name is None:
            return False
        low = name.lower()
        return any(h in low for h in lock_hints)

    stack: list[tuple[ast.AST, bool]] = [
        (child, False) for child in ast.iter_child_nodes(func)
    ]
    while stack:
        node, locked = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node, locked
        if isinstance(node, (ast.With, ast.AsyncWith)):
            guarded = locked or any(
                _is_lock(item.context_expr) for item in node.items
            )
            for item in node.items:
                stack.append((item, locked))
            for child in node.body:
                stack.append((child, guarded))
        else:
            stack.extend(
                (child, locked) for child in ast.iter_child_nodes(node)
            )


@dataclasses.dataclass
class ContextMap:
    """The classified call graph for one analyzed tree."""

    indexes: dict[str, _ModuleIndex]
    funcs: dict[FuncKey, FuncInfo]
    edges: dict[FuncKey, set[FuncKey]]
    loop: set[FuncKey]
    thread: set[FuncKey]
    #: seed provenance for messages: key -> short reason string
    seeds: dict[FuncKey, str]

    def contexts_of(self, key: FuncKey) -> frozenset[str]:
        out = set()
        if key in self.loop:
            out.add(LOOP)
        if key in self.thread:
            out.add(THREAD)
        return frozenset(out)

    def info_for_node(self, path: str, node: ast.AST) -> FuncInfo | None:
        idx = self.indexes.get(path)
        if idx is None:
            return None
        for info in idx.funcs.values():
            if info.node is node:
                return info
        return None


def _resolve(
    expr: ast.AST,
    caller: FuncInfo | None,
    idx: _ModuleIndex,
    indexes: dict[str, _ModuleIndex],
    modmap: dict[str, str],
) -> FuncKey | None:
    """Resolve a callable expression to a function key, or None (bounded)."""
    if isinstance(expr, ast.Call):
        # functools.partial(f, ...) — the eventual callable is args[0]
        if terminal_name(expr.func) == "partial" and expr.args:
            return _resolve(expr.args[0], caller, idx, indexes, modmap)
        return None
    if isinstance(expr, ast.Name):
        name = expr.id
        if caller is not None:
            prefix = caller.qualname + "."
            nested = [
                q for q in idx.by_name.get(name, []) if q.startswith(prefix)
            ]
            if nested:
                return (idx.path, min(nested, key=len))
        if name in idx.top_level:
            return (idx.path, idx.top_level[name])
        cands = idx.by_name.get(name, [])
        if len(cands) == 1:
            return (idx.path, cands[0])
        alias = idx.aliases.get(name)
        if alias is not None and alias[0] == "from":
            _, base, orig = alias
            tpath = modmap.get(base)
            if tpath is not None and orig in indexes[tpath].top_level:
                return (tpath, indexes[tpath].top_level[orig])
        return None
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base == "self" and caller is not None and caller.cls is not None:
                methods = idx.class_methods.get(caller.cls, {})
                qual = methods.get(expr.attr)
                return (idx.path, qual) if qual is not None else None
            alias = idx.aliases.get(base)
            if alias is not None:
                dotted = (
                    alias[1]
                    if alias[0] == "import"
                    else (f"{alias[1]}.{alias[2]}" if alias[1] else alias[2])
                )
                tpath = modmap.get(dotted)
                if tpath is not None:
                    tidx = indexes[tpath]
                    qual = tidx.top_level.get(expr.attr)
                    return (tpath, qual) if qual is not None else None
            return None
        dn = dotted_name(expr)
        if dn is not None and "." in dn:
            mod, _, fname = dn.rpartition(".")
            tpath = modmap.get(mod)
            if tpath is not None:
                qual = indexes[tpath].top_level.get(fname)
                return (tpath, qual) if qual is not None else None
    return None


def _callable_seeds(
    expr: ast.AST,
    caller: FuncInfo | None,
    idx: _ModuleIndex,
    indexes: dict[str, _ModuleIndex],
    modmap: dict[str, str],
) -> list[FuncKey]:
    """Resolve a spawn/callback target; a lambda target seeds every function
    its body calls (the body RUNS in the spawned context)."""
    if isinstance(expr, ast.Lambda):
        out: list[FuncKey] = []
        for sub in ast.walk(expr.body):
            if isinstance(sub, ast.Call):
                key = _resolve(sub.func, caller, idx, indexes, modmap)
                if key is not None:
                    out.append(key)
        return out
    key = _resolve(expr, caller, idx, indexes, modmap)
    return [key] if key is not None else []


def _spawn_targets(
    call: ast.Call,
) -> tuple[str, list[ast.AST]] | None:
    """``(color, target exprs)`` when ``call`` hands a callable to another
    execution context, else None."""
    name = dotted_name(call.func)
    tail = terminal_name(call.func)
    if tail == "Thread" or (name is not None and name.endswith("threading.Thread")):
        targets = [kw.value for kw in call.keywords if kw.arg == "target"]
        return (THREAD, targets) if targets else None
    if tail == "to_thread" and call.args:
        return (THREAD, [call.args[0]])
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _THREAD_POOL_METHODS
        and call.args
    ):
        return (THREAD, [call.args[0]])
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "run_in_executor"
        and len(call.args) >= 2
    ):
        return (THREAD, [call.args[1]])
    if isinstance(call.func, ast.Attribute) and call.func.attr in _LOOP_CALLBACK_METHODS:
        pos = 1 if call.func.attr in ("call_later", "call_at") else 0
        if len(call.args) > pos:
            return (LOOP, [call.args[pos]])
    return None


def _direct_calls(func: ast.AST) -> Iterator[ast.Call]:
    stack: list[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def build_context_map(trees: dict[str, ast.AST]) -> ContextMap:
    indexes: dict[str, _ModuleIndex] = {}
    for path, tree in trees.items():
        idx = _ModuleIndex(path)
        idx.visit(tree)
        indexes[path] = idx
    modmap = {_module_dotted(path): path for path in trees}

    funcs: dict[FuncKey, FuncInfo] = {}
    for idx in indexes.values():
        for info in idx.funcs.values():
            funcs[info.key] = info

    edges: dict[FuncKey, set[FuncKey]] = {k: set() for k in funcs}
    loop_seeds: dict[FuncKey, str] = {}
    thread_seeds: dict[FuncKey, str] = {}

    for path, tree in trees.items():
        idx = indexes[path]
        scopes: list[tuple[FuncInfo | None, ast.AST]] = [(None, tree)]
        scopes.extend((info, info.node) for info in idx.funcs.values())
        for caller, scope in scopes:
            # module-level scope must not descend into defs (they have their
            # own rows); _direct_calls already guarantees that for both.
            for call in _direct_calls(scope):
                spawn = _spawn_targets(call)
                if spawn is not None:
                    color, exprs = spawn
                    seeds = thread_seeds if color == THREAD else loop_seeds
                    for expr in exprs:
                        for key in _callable_seeds(
                            expr, caller, idx, indexes, modmap
                        ):
                            seeds.setdefault(
                                key,
                                f"{'thread target' if color == THREAD else 'loop callback'}"
                                f" at {path}:{call.lineno}",
                            )
                    continue
                if caller is None:
                    continue  # plain module-level call: import-time, no color
                key = _resolve(call.func, caller, idx, indexes, modmap)
                if key is not None:
                    edges[caller.key].add(key)

    for info in funcs.values():
        if info.is_async:
            loop_seeds.setdefault(info.key, "async def")

    def _closure(seeds: dict[FuncKey, str], color: str) -> set[FuncKey]:
        seen: set[FuncKey] = set()
        frontier = [k for k in seeds if k in funcs]
        seen.update(frontier)
        while frontier:
            cur = frontier.pop()
            if funcs[cur].is_async and color == THREAD:
                # an async def reached from thread context is not RUN there
                # (calling it only builds a coroutine object) — don't spread
                continue
            for nxt in edges.get(cur, ()):
                if nxt in seen:
                    continue
                if funcs[nxt].is_async:
                    # sync->async edge builds a coroutine; the async body
                    # itself is already a loop seed
                    continue
                seen.add(nxt)
                frontier.append(nxt)
        return seen

    loop = _closure(loop_seeds, LOOP)
    thread = _closure(thread_seeds, THREAD)
    # async defs spawned AS thread targets were skipped above; drop them from
    # the thread set entirely so contexts_of never reports the impossible
    thread = {k for k in thread if not funcs[k].is_async}

    seeds = dict(loop_seeds)
    seeds.update(thread_seeds)
    return ContextMap(
        indexes=indexes,
        funcs=funcs,
        edges=edges,
        loop=loop,
        thread=thread,
        seeds={k: v for k, v in seeds.items() if k in funcs},
    )
