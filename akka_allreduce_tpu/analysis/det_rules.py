"""DET001/002/003 — byte-identical-replay discipline as enforced rules.

The repo's strongest correctness evidence is deterministic replay: the chaos
drills pin byte-identical event JSONL across runs, the 256-node control-plane
sims assert exact round trajectories, and PR 15 re-litigated (by review) that
``GossipState`` stays clock-free and seeded. These rules turn that convention
into a gate for the modules declared deterministic via the ``[tool.arlint]``
``det-modules`` config key (path suffixes; ``gossip.py``, ``stripes.py``,
``chaos.py``, ``simfabric.py``, ``adapt.py`` in this repo):

- **DET001** — wall-clock reads: ``time.time()``/``time.monotonic()``/
  ``datetime.now()`` and friends called inside a det-module. Deterministic
  code takes an injected clock (the ``clock: Callable[[], float] =
  time.monotonic`` *default-argument reference* is the sanctioned idiom and
  is not a call, so it never fires). ``time.perf_counter`` is exempt: the
  sim fabric measures its own wall-cost with it, which never feeds state.
- **DET002** — unseeded RNG: module-level ``random.*`` calls and
  ``np.random.*`` legacy-global calls. Seeded construction —
  ``random.Random(seed)``, ``np.random.default_rng(seed)`` /
  ``PCG64``/``Philox``/``SeedSequence`` with arguments — is the sanctioned
  idiom and is exempt.
- **DET003** — iteration over a ``set``/``frozenset`` in a context where
  order can escape (a ``for`` loop, a list/generator/dict comprehension, or
  a generator fed to an order-sensitive consumer): set order varies with
  PYTHONHASHSEED and insertion history, so anything it feeds — emitted
  events, probe order, rumor order — diverges across replays. ``sorted(...)``
  is the fix; ``list(...)`` is NOT (it freezes the nondeterministic order).
  Set comprehensions and order-insensitive consumers (``sorted``, ``set``,
  ``min``/``max``, ``any``/``all``, ``len``, ``sum``, ``frozenset``) are
  exempt.
"""

from __future__ import annotations

import ast

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding
from akka_allreduce_tpu.analysis.astutil import dotted_name, terminal_name

_WALL_CLOCK = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "date.today",
    "datetime.date.today",
}

_SEEDED_NP = {"default_rng", "PCG64", "Philox", "SeedSequence", "Generator"}

_ORDER_INSENSITIVE = {
    "sorted",
    "set",
    "frozenset",
    "min",
    "max",
    "any",
    "all",
    "len",
    "sum",
}


def _is_det_module(path: str, config: ArlintConfig) -> bool:
    return any(path.endswith(suffix) for suffix in config.det_modules)


def rule_det001(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    if not _is_det_module(path, config):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in _WALL_CLOCK:
            findings.append(
                Finding(
                    path,
                    node.lineno,
                    "DET001",
                    f"wall-clock read {name}() inside a deterministic module "
                    f"— replay diverges the moment real time leaks into "
                    f"state; take an injected clock callable instead "
                    f"(default-arg 'clock=time.monotonic' reference is the "
                    f"sanctioned idiom)",
                    end_line=node.end_lineno or node.lineno,
                )
            )
    return findings


def rule_det002(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    if not _is_det_module(path, config):
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        hit: str | None = None
        if name.startswith("random."):
            tail = name.split(".", 1)[1]
            if not (tail == "Random" and (node.args or node.keywords)):
                hit = name
        elif name.startswith(("np.random.", "numpy.random.")):
            tail = name.rsplit(".", 1)[1]
            if not (
                tail in _SEEDED_NP and (node.args or node.keywords)
            ):
                hit = name
        if hit is not None:
            findings.append(
                Finding(
                    path,
                    node.lineno,
                    "DET002",
                    f"unseeded RNG call {hit}() inside a deterministic "
                    f"module — the process-global generator breaks seeded "
                    f"replay; construct random.Random(seed) / "
                    f"np.random.default_rng(seed) from a derived seed and "
                    f"thread it through",
                    end_line=node.end_lineno or node.lineno,
                )
            )
    return findings


def _set_names(tree: ast.AST) -> set[str]:
    """Names (locals, module globals, and ``self`` attrs by terminal name)
    that are bound to a set/frozenset anywhere in the file — by literal,
    comprehension, constructor call, set-algebra BinOp over a known set, or
    a ``set[...]`` annotation."""
    names: set[str] = set()

    def is_set_expr(expr: ast.AST | None) -> bool:
        if expr is None:
            return False
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call):
            fname = terminal_name(expr.func)
            if fname in ("set", "frozenset"):
                return True
            if (
                isinstance(expr.func, ast.Attribute)
                and expr.func.attr
                in (
                    "difference",
                    "union",
                    "intersection",
                    "symmetric_difference",
                    "copy",
                )
                and terminal_name(expr.func.value) in names
            ):
                return True
            return False
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)
        ):
            return (
                terminal_name(expr.left) in names
                or terminal_name(expr.right) in names
            )
        if isinstance(expr, ast.Name) or isinstance(expr, ast.Attribute):
            return terminal_name(expr) in names
        return False

    def ann_is_set(ann: ast.AST | None) -> bool:
        if ann is None:
            return False
        base = ann.value if isinstance(ann, ast.Subscript) else ann
        return terminal_name(base) in ("set", "Set", "frozenset", "FrozenSet")

    # two passes so `b = a` after `a = set()` still registers
    for _ in range(2):
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and is_set_expr(node.value):
                for t in node.targets:
                    name = terminal_name(t)
                    if name is not None:
                        names.add(name)
            elif isinstance(node, ast.AnnAssign):
                if ann_is_set(node.annotation) or is_set_expr(node.value):
                    name = terminal_name(node.target)
                    if name is not None:
                        names.add(name)
            elif isinstance(node, ast.arg) and ann_is_set(node.annotation):
                names.add(node.arg)
    return names


def rule_det003(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    if not _is_det_module(path, config):
        return []
    names = _set_names(tree)
    if not names:
        return []

    # parent links so a GeneratorExp can see its consuming call
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def set_base(expr: ast.AST) -> str | None:
        """The set name ``expr`` iterates, resolving through list()/tuple()
        (which do NOT fix set order) but treating sorted() as sanctioned."""
        while isinstance(expr, ast.Call):
            fname = (
                expr.func.id if isinstance(expr.func, ast.Name) else None
            )
            if fname == "sorted":
                return None
            if fname in ("list", "tuple") and expr.args:
                expr = expr.args[0]
                continue
            return None
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)
        ):
            left = terminal_name(expr.left)
            right = terminal_name(expr.right)
            if left in names:
                return left
            if right in names:
                return right
            return None
        name = terminal_name(expr)
        return name if name in names else None

    findings = []

    def flag(name: str, node: ast.AST) -> None:
        findings.append(
            Finding(
                path,
                node.lineno,
                "DET003",
                f"iteration over set '{name}' in a deterministic module — "
                f"set order varies with hashing/insertion history, so "
                f"anything this loop emits diverges across replays; iterate "
                f"sorted({name}) (list() only freezes the nondeterministic "
                f"order)",
                end_line=node.end_lineno or node.lineno,
            )
        )

    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            name = set_base(node.iter)
            if name is not None:
                flag(name, node)
        elif isinstance(node, (ast.ListComp, ast.DictComp)):
            for gen in node.generators:
                name = set_base(gen.iter)
                if name is not None:
                    flag(name, node)
        elif isinstance(node, ast.GeneratorExp):
            parent = parents.get(node)
            if (
                isinstance(parent, ast.Call)
                and isinstance(parent.func, ast.Name)
                and parent.func.id in _ORDER_INSENSITIVE
            ):
                continue
            for gen in node.generators:
                name = set_base(gen.iter)
                if name is not None:
                    flag(name, node)
        # SetComp is exempt: a set built from a set has no observable order
    return findings
