"""Analyzer core: findings, suppression comments, baseline, and the runner.

Enforcement contract (tests/test_arlint.py, ``make lint``): a finding is
*unsuppressed* unless an inline ``# arlint: disable=RULE`` comment covers its
line or the baseline file carries its fingerprint. Fingerprints are
``(relative path, rule, stripped source line)`` — content-addressed, so a
baseline survives unrelated edits shifting line numbers, but any change to
the offending line itself resurfaces the finding for a fresh look.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from collections import Counter
from pathlib import Path

from akka_allreduce_tpu.analysis.config import ArlintConfig


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str
    #: stripped source text of ``line`` (fingerprint component); filled by
    #: the runner, empty for findings built directly in unit fixtures
    line_content: str = ""
    #: last line of the offending statement (0 = same as ``line``): a
    #: trailing suppression comment on a black-wrapped multi-line call sits
    #: on the CLOSING line, so suppression matching covers the whole span
    end_line: int = 0

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def fingerprint(self) -> tuple[str, str, str, str]:
        # the message participates so that two DIFFERENT findings anchored to
        # the same line (WIRE001 reports everything at the _TAGS literal)
        # never collapse into one baseline entry
        return (self.path, self.rule, self.line_content, self.message)


# -- inline suppressions ------------------------------------------------------

# the rules group accepts lowercase too: `disable=buf001` must parse as a
# NAMED suppression (normalized to uppercase below), never degrade to a
# blanket disable because the group failed to match
_SUPPRESS = re.compile(
    r"#\s*arlint:\s*disable(?P<next>-next)?"
    r"(?P<eq>\s*=\s*(?P<rules>[A-Za-z0-9_, ]*))?"
)


def _comment_tokens(source: str) -> list[tuple[int, str]]:
    """(line, text) of every real COMMENT token — tokenizing (rather than
    regex-scanning raw lines) keeps a directive spelled inside a string
    literal or docstring from registering a phantom suppression."""
    try:
        return [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # untokenizable source never got past ast.parse either; nothing to
        # suppress on a file that only carries a PARSE finding
        return []


def suppressed_lines(source: str) -> dict[int, frozenset[str] | None]:
    """``{line_number: rules}`` where rules is None for a blanket disable.

    ``# arlint: disable=RULE`` suppresses its own (1-based) line;
    ``# arlint: disable-next=RULE`` suppresses the following line.
    """
    out: dict[int, frozenset[str] | None] = {}
    for i, text in _comment_tokens(source):
        m = _SUPPRESS.search(text)
        if m is None:
            continue
        target = i + 1 if m.group("next") else i
        if m.group("eq") is None:
            ruleset = None  # no '=': a deliberate blanket disable
        else:
            # '=' present: ONLY the named rules are suppressed (uppercased —
            # `disable=buf001` means BUF001); an empty/garbled list
            # suppresses nothing rather than everything
            ruleset = frozenset(
                r.strip().upper()
                for r in (m.group("rules") or "").split(",")
                if r.strip()
            )
        if target in out:
            prev = out[target]
            out[target] = (
                None if prev is None or ruleset is None else prev | ruleset
            )
        else:
            out[target] = ruleset
    return out


def is_suppressed(
    finding: Finding, suppressions: dict[int, frozenset[str] | None]
) -> bool:
    last = max(finding.line, finding.end_line)
    for line in range(finding.line, last + 1):
        rules = suppressions.get(line, ...)
        if rules is ...:
            continue
        if rules is None or finding.rule in rules:
            return True
    return False


# -- baseline -----------------------------------------------------------------


def load_baseline(path: Path) -> Counter:
    """Fingerprint multiset from a baseline JSON file (missing file = empty:
    a fresh checkout with no baseline simply enforces everything)."""
    if not path.is_file():
        return Counter()
    data = json.loads(path.read_text(encoding="utf-8"))
    return Counter(
        (e["path"], e["rule"], e["line_content"], e.get("message", ""))
        for e in data.get("findings", [])
    )


def write_baseline(path: Path, findings: list[Finding]) -> None:
    entries = [
        {
            "path": f.path,
            "rule": f.rule,
            "line_content": f.line_content,
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
        encoding="utf-8",
    )


def apply_baseline(
    findings: list[Finding], baseline: Counter
) -> tuple[list[Finding], list[Finding]]:
    """Split into (unsuppressed, baselined); each baseline entry absorbs at
    most its multiplicity, so a SECOND identical violation still fails."""
    remaining = Counter(baseline)
    fresh: list[Finding] = []
    known: list[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if remaining[fp] > 0:
            remaining[fp] -= 1
            known.append(f)
        else:
            fresh.append(f)
    return fresh, known


# -- runner -------------------------------------------------------------------


def _attach_line_content(findings: list[Finding], source: str) -> list[Finding]:
    lines = source.splitlines()
    out = []
    for f in findings:
        content = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        out.append(dataclasses.replace(f, line_content=content))
    return out


def analyze_source(
    source: str,
    path: str = "<string>",
    config: ArlintConfig | None = None,
    *,
    apply_suppressions: bool = True,
    tree: ast.AST | None = None,
) -> list[Finding]:
    """Run the per-file rules over one source string (the fixture/test API).

    Returns findings sorted by line; syntax errors surface as a synthetic
    ``PARSE`` finding rather than an exception, so one broken file cannot
    take the whole lint run down silently. ``tree`` lets a caller that
    already parsed the source (analyze_paths) skip the second parse.
    """
    from akka_allreduce_tpu.analysis.rules import FILE_RULES

    config = config or ArlintConfig()
    if tree is None:
        try:
            tree = ast.parse(source)
        except SyntaxError as exc:
            return [
                Finding(
                    path, exc.lineno or 1, "PARSE", f"syntax error: {exc.msg}"
                )
            ]
    findings: list[Finding] = []
    for rule_id, rule in FILE_RULES.items():
        if config.rules is not None and rule_id not in config.rules:
            continue
        findings.extend(rule(tree, path, config))
    findings = _attach_line_content(findings, source)
    if apply_suppressions:
        sup = suppressed_lines(source)
        findings = [f for f in findings if not is_suppressed(f, sup)]
    return sorted(findings, key=lambda f: (f.line, f.rule, f.message))


def iter_python_files(paths: list[Path], config: ArlintConfig) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    out = []
    for f in files:
        posix = f.as_posix()
        if any(pat in posix for pat in config.exclude):
            continue
        out.append(f)
    # overlapping inputs (`arlint pkg/ pkg/mod.py`) must not analyze a file
    # twice — duplicate findings would defeat baseline multiplicity
    return list(dict.fromkeys(out))


def _project_checks():
    """Registry of cross-file checks: ``(rule ids, fn)`` where ``fn`` has the
    uniform signature ``(trees, config, *, root) -> list[Finding]``. A check
    runs when any of its rule ids is selected; its output is then filtered to
    the selected ids (one walker can serve several rules). Lazy imports keep
    the core free of rule-module cycles."""
    from akka_allreduce_tpu.analysis.obs_rule import check_obs_doc_drift
    from akka_allreduce_tpu.analysis.thread_rules import check_thread_safety
    from akka_allreduce_tpu.analysis.wire_rule import (
        check_wire_exhaustiveness,
        check_wire_skew,
    )

    return (
        (("THRD001", "THRD002"), check_thread_safety),
        (("WIRE001",), check_wire_exhaustiveness),
        (("WIRE002",), check_wire_skew),
        (("OBS001",), check_obs_doc_drift),
    )


def analyze_paths(
    paths: list[Path],
    config: ArlintConfig | None = None,
    *,
    root: Path | None = None,
) -> list[Finding]:
    """Analyze files/trees: per-file rules + the project-wide checks
    (WIRE001/WIRE002 codec contracts, THRD001/002 over the call-graph
    context classifier, OBS001 doc drift).

    ``root`` anchors the relative paths used in output and baseline
    fingerprints (default: the config's pyproject directory, else cwd).
    Inline suppressions are already applied; baseline filtering is the
    caller's second step (the CLI and the enforcement test both do it).
    """
    config = config or ArlintConfig()
    if root is None:
        root = (
            config.source.parent if config.source is not None else Path.cwd()
        )
    files = iter_python_files([p.resolve() for p in paths], config)
    findings: list[Finding] = []
    parsed: dict[str, tuple[ast.AST, str]] = {}
    suppressions: dict[str, dict] = {}
    for f in files:
        try:
            rel = f.relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        source = f.read_text(encoding="utf-8")
        try:
            tree = ast.parse(source)
        except SyntaxError:
            findings.extend(analyze_source(source, rel, config))  # -> PARSE
            continue
        findings.extend(analyze_source(source, rel, config, tree=tree))
        parsed[rel] = (tree, source)
        suppressions[rel] = suppressed_lines(source)
    trees = {rel: tree for rel, (tree, _) in parsed.items()}
    for rule_ids, check in _project_checks():
        if config.rules is not None and not set(rule_ids) & set(config.rules):
            continue
        project_findings = [
            f
            for f in check(trees, config, root=root)
            if config.rules is None or f.rule in config.rules
        ]
        project_findings = [
            dataclasses.replace(
                f,
                line_content=(
                    parsed[f.path][1].splitlines()[f.line - 1].strip()
                    if 0 < f.line <= len(parsed[f.path][1].splitlines())
                    else ""
                ),
            )
            if not f.line_content and f.path in parsed
            else f
            for f in project_findings
        ]
        findings.extend(
            f
            for f in project_findings
            if not is_suppressed(f, suppressions.get(f.path, {}))
        )
    # message participates in the sort key so two same-line findings order
    # deterministically — the analyzer's own output is replay-pinned too
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))
