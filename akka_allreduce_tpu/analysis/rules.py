"""Per-file AST rules: ASYNC001-ASYNC004 and BUF001.

Each rule is a function ``(tree, path, config) -> list[Finding]`` registered
in ``FILE_RULES``. The rules are deliberately shallow — no cross-function
dataflow — because every one of them targets a *syntactically local* defect
shape this codebase has actually shipped (see ANALYSIS.md). Shallow means
predictable: a finding always points at one line a human can judge in
isolation, and a suppression comment on that line is the whole escape hatch.
"""

from __future__ import annotations

import ast

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding

# -- shared helpers (astutil is the canonical home; re-exported here because
#    rule modules and tests historically import them from this module) -------
from akka_allreduce_tpu.analysis.astutil import (
    direct_body_walk as _direct_body_walk,
    dotted_name,
    functions as _functions,
    terminal_name,
)


# -- ASYNC001: blocking call inside a coroutine -------------------------------

# Callables that block the calling thread. The event loop thread carries
# heartbeats, failure detection, and every in-flight round: one of these in a
# coroutine stalls ALL of them for its full duration.
_BLOCKING = {
    "time.sleep": "asyncio.sleep",
    "subprocess.run": "asyncio.create_subprocess_exec",
    "subprocess.call": "asyncio.create_subprocess_exec",
    "subprocess.check_call": "asyncio.create_subprocess_exec",
    "subprocess.check_output": "asyncio.create_subprocess_exec",
    "subprocess.Popen": "asyncio.create_subprocess_exec",
    "os.system": "asyncio.create_subprocess_shell",
    "os.waitpid": "asyncio.create_subprocess_exec",
    "select.select": "loop.add_reader/add_writer",
    "socket.create_connection": "loop.sock_connect on a non-blocking socket",
    "urllib.request.urlopen": "a thread via asyncio.to_thread",
}


def rule_async001(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    blocking = dict(_BLOCKING)
    for extra in config.async001_blocking:
        blocking.setdefault(extra, "an async equivalent or asyncio.to_thread")
    findings = []
    for func in _functions(tree):
        if not isinstance(func, ast.AsyncFunctionDef):
            continue
        for node in _direct_body_walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in blocking:
                findings.append(
                    Finding(
                        path,
                        node.lineno,
                        "ASYNC001",
                        f"blocking call {name}() inside 'async def "
                        f"{func.name}' stalls the event loop (and every "
                        f"heartbeat/round it carries); use "
                        f"{blocking[name]} or asyncio.to_thread",
                        end_line=node.end_lineno or node.lineno,
                    )
                )
    return findings


# -- ASYNC002: coroutine called but never awaited -----------------------------

# asyncio module-level coroutine functions whose bare call is always a bug
_ASYNCIO_COROS = {
    "asyncio.sleep",
    "asyncio.wait",
    "asyncio.wait_for",
    "asyncio.gather",
    "asyncio.to_thread",
    "asyncio.open_connection",
    "asyncio.start_server",
}


def _async_contexts(
    tree: ast.AST,
) -> list[tuple[ast.AsyncFunctionDef, frozenset[str]]]:
    """Every ``async def`` paired with the async-method names of its
    enclosing class (empty for module-level/nested functions): ``self.X``
    must resolve against the SAME class, or a sync ``B.ping`` would be
    flagged because an unrelated ``A.ping`` is async."""
    out: list[tuple[ast.AsyncFunctionDef, frozenset[str]]] = []
    class_methods: dict[ast.AST, frozenset[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            class_methods[node] = frozenset(
                f.name
                for f in node.body
                if isinstance(f, ast.AsyncFunctionDef)
            )
            for f in node.body:
                if isinstance(f, ast.AsyncFunctionDef):
                    out.append((f, class_methods[node]))
    in_class = {id(f) for f, _ in out}
    for f in _functions(tree):
        if isinstance(f, ast.AsyncFunctionDef) and id(f) not in in_class:
            out.append((f, frozenset()))
    return out


def rule_async002(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    # bare-Name calls resolve against module-level async defs only
    top_coros = {
        f.name
        for f in getattr(tree, "body", [])
        if isinstance(f, ast.AsyncFunctionDef)
    }
    findings = []
    # only coroutine bodies are scanned (like ASYNC001/ASYNC004): a sync
    # function calling a coroutine may be handing it to a scheduler —
    # inside an async def a bare coroutine-call statement is a lost body
    for func, self_coros in _async_contexts(tree):
        for node in _direct_body_walk(func):
            if not (
                isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
            ):
                continue
            call = node.value
            name = dotted_name(call.func)
            hit: str | None = None
            if name in _ASYNCIO_COROS:
                hit = name
            elif isinstance(call.func, ast.Name) and call.func.id in top_coros:
                hit = call.func.id
            elif (
                isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id == "self"
                and call.func.attr in self_coros
            ):
                hit = f"self.{call.func.attr}"
            if hit is not None:
                findings.append(
                    Finding(
                        path,
                        node.lineno,
                        "ASYNC002",
                        f"coroutine {hit}() is called but never awaited — "
                        f"the body never runs; await it or wrap it in a "
                        f"retained task",
                        end_line=node.end_lineno or node.lineno,
                    )
                )
    return findings


# -- ASYNC003: dropped task handle --------------------------------------------


def _is_task_spawn(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    # observed_task included: it keeps the task alive and logs crashes, but a
    # dropped handle still loses the caller's ability to cancel/await it
    if tail in ("create_task", "ensure_future", "observed_task"):
        return name
    return None


def rule_async003(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        name = _is_task_spawn(node.value)
        if name is not None:
            findings.append(
                Finding(
                    path,
                    node.lineno,
                    "ASYNC003",
                    f"{name}() handle dropped: the event loop keeps only a "
                    f"weak reference, so the task can be garbage-collected "
                    f"mid-flight and its exception is silently lost — retain "
                    f"the handle (task set / attribute) or add a "
                    f"done-callback that logs failures",
                    end_line=node.end_lineno or node.lineno,
                )
            )
    return findings


# -- ASYNC004: cancellation-swallowing except inside a coroutine --------------

_SWALLOWING = ("Exception", "BaseException", "CancelledError")


def _handler_catches(handler: ast.ExceptHandler, names: tuple[str, ...]) -> str | None:
    """Which of ``names`` this handler's type expression covers (bare
    ``except`` counts as BaseException)."""
    if handler.type is None:
        return "bare except"
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        tname = terminal_name(t)
        if tname in names:
            return tname
    return None


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Handler body re-raises the active exception at any depth outside
    nested defs: bare ``raise``, or ``raise e`` of the bound name
    (``except ... as e``)."""
    for node in _direct_body_walk(handler):
        if not isinstance(node, ast.Raise):
            continue
        if node.exc is None:
            return True
        if (
            handler.name is not None
            and isinstance(node.exc, ast.Name)
            and node.exc.id == handler.name
        ):
            return True
    return False


def rule_async004(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    findings = []
    for func in _functions(tree):
        if not isinstance(func, ast.AsyncFunctionDef):
            continue
        for node in _direct_body_walk(func):
            if not isinstance(node, ast.Try):
                continue
            # A dedicated CancelledError arm (no broad type alongside it) is
            # a deliberate decision about cancellation — the idiomatic
            # `task.cancel(); await task; except CancelledError: pass`
            # included. It protects an `except Exception` arm ANYWHERE in
            # the same try (Exception cannot catch CancelledError on
            # py3.8+, so arm order is irrelevant), but protects bare
            # `except`/`except BaseException` only when it comes FIRST —
            # those catch CancelledError themselves, making a later
            # dedicated arm dead code.
            dedicated = [
                bool(
                    _handler_catches(h, ("CancelledError",))
                    and _handler_catches(h, ("Exception", "BaseException"))
                    is None
                )
                for h in node.handlers
            ]
            for i, handler in enumerate(node.handlers):
                if dedicated[i]:
                    continue
                caught = _handler_catches(handler, _SWALLOWING)
                if caught is None or _reraises(handler):
                    continue
                protected = (
                    any(dedicated)
                    if caught == "Exception"
                    else any(dedicated[:i])
                )
                if protected:
                    continue
                findings.append(
                    Finding(
                        path,
                        handler.lineno,
                        "ASYNC004",
                        # span stays on the `except` line only: the handler
                        # BODY must not become a suppression surface
                        f"'{caught}' handler inside 'async def {func.name}' "
                        f"can swallow asyncio.CancelledError (wait_for "
                        f"timeouts/teardown deadlock class, Python < 3.12 "
                        f"especially) — add an 'except "
                        f"asyncio.CancelledError: raise' arm before it or "
                        f"re-raise inside",
                    )
                )
    return findings


# -- BUF001: escaping view of a recycled buffer -------------------------------

_VIEW_CALLS = ("np.frombuffer", "numpy.frombuffer", "memoryview")

# a view escaping THROUGH one of these owns its memory: methods called on the
# view, and constructors/functions the view is passed into
_COPYING_METHODS = ("copy", "tobytes", "astype")
_COPYING_CALLS = ("bytes", "bytearray", "list", "tuple", "np.array", "numpy.array")


def _recycled_view_call(
    node: ast.AST, markers: tuple[str, ...]
) -> tuple[ast.Call, str] | None:
    """A ``np.frombuffer``/``memoryview`` Call over a source whose terminal
    name matches a recycled-buffer marker, found anywhere inside ``node`` —
    except under a copying wrapper (``view.copy()``, ``bytes(view)``, …),
    whose result owns its memory: 'copy before the escape' must silence the
    rule even when done in the same expression."""
    if isinstance(node, ast.Call):
        func_name = dotted_name(node.func)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _COPYING_METHODS
        ):
            return None  # <view expr>.copy() — nothing below escapes
        if func_name in _COPYING_CALLS:
            return None  # bytes(<view expr>) — ditto
        if func_name in _VIEW_CALLS and node.args:
            src = terminal_name(node.args[0])
            if src is not None:
                # markers match whole underscore-separated segments of the
                # name — a bare substring test would make the default
                # 'ring' fire on '_instring'/'wiring'
                segments = [s for s in src.lower().split("_") if s]
                if any(marker in segments for marker in markers):
                    return node, src
    for child in ast.iter_child_nodes(node):
        hit = _recycled_view_call(child, markers)
        if hit is not None:
            return hit
    return None


def rule_buf001(
    tree: ast.AST, path: str, config: ArlintConfig
) -> list[Finding]:
    markers = tuple(m.lower() for m in config.buf001_markers)
    findings = []
    for func in _functions(tree):
        for node in _direct_body_walk(func):
            escape: str | None = None
            value: ast.AST | None = None
            if isinstance(node, ast.Return) and node.value is not None:
                escape, value = "returned", node.value
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
                escape, value = "yielded", node.value
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                stores_self = any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in targets
                )
                if stores_self and node.value is not None:
                    escape, value = "stored on self", node.value
            if escape is None or value is None:
                continue
            hit = _recycled_view_call(value, markers)
            if hit is None:
                continue
            call, src = hit
            findings.append(
                Finding(
                    path,
                    node.lineno,
                    "BUF001",
                    f"zero-copy view of recycled buffer '{src}' is {escape}: "
                    f"once the buffer is reused the view aliases live memory "
                    f"(recv-ring corruption class) — copy before the escape, "
                    f"or guard the recycle and suppress with a justification",
                    end_line=node.end_lineno or node.lineno,
                )
            )
    return findings


# imported at the bottom on purpose: det_rules/life_rule use the shared
# helpers above, so importing them any earlier would be circular
from akka_allreduce_tpu.analysis.det_rules import (  # noqa: E402
    rule_det001,
    rule_det002,
    rule_det003,
)
from akka_allreduce_tpu.analysis.life_rule import rule_life001  # noqa: E402

FILE_RULES = {
    "ASYNC001": rule_async001,
    "ASYNC002": rule_async002,
    "ASYNC003": rule_async003,
    "ASYNC004": rule_async004,
    "BUF001": rule_buf001,
    "DET001": rule_det001,
    "DET002": rule_det002,
    "DET003": rule_det003,
    "LIFE001": rule_life001,
}
