"""WIRE001/WIRE002 — wire-tag exhaustiveness and the version-skew contract.

The control plane's binary codec (``control/wire.py``) is a hand-rolled
tag-dispatch pair: ``_TAGS`` maps message type -> tag byte, ``_encode_parts``
and ``decode`` each carry one ``tag == N`` arm per entry, and every decoded
message must reach an ``isinstance`` dispatch arm in some handler
(``control/worker.py``, ``control/bootstrap.py``, the line/grid masters).
Three places to update per new message type, and nothing ties them together
at runtime: a missed decode arm is a silent ``ValueError: unknown wire tag``
under load, a missed dispatch arm a ``TypeError`` mid-round. This rule makes
the tie mechanical:

- every ``_TAGS`` tag has an encode arm and a decode arm, and every arm's
  tag exists in ``_TAGS`` (set equality, both directions);
- every ``_TAGS`` message type name appears in at least one
  ``isinstance(..., Type)`` / ``match``-class dispatch somewhere in the
  analyzed files.

The rule activates on any analyzed module that assigns a dict literal named
``_TAGS`` with int values and defines ``decode`` — i.e. the wire module
itself; trees without one simply skip the rule.

**WIRE002** is the *version-skew* half of the contract, pinned today only
dynamically (``test_wire_roundtrip``'s trailing-bytes cases, ``test_chaos``'s
tag-range pin). A rolling upgrade has old and new nodes on the wire at once,
so the codec's compatibility rules become static checks:

- no decode-family function may compare ``len(<buffer>)`` for exact equality
  (``==``/``!=``): trailing bytes from a newer peer — the trace trailer is
  the shipped example — must be *tolerated*, so bounds are ``<=``, never
  ``==`` (emptiness checks against ``0`` are exempt);
- wire dataclasses (types in ``_TAGS``, plus dataclasses the wire module
  references, e.g. ``RoundPolicy``) must keep new fields trailing-with-
  default: a defaultless field after a defaulted one — including the
  ``field(kw_only=True)`` escape hatch Python requires for that shape —
  breaks old decoders that construct with fewer fields;
- ``_TAGS`` values stay unique and contiguous from 1 (the ``test_chaos``
  pin, statically), and tag ranges declared module-owned via
  ``[tool.arlint] wire-owned`` (``"control/gossip.py:24-26"``) must match
  exactly the tags of the types that module defines, both directions.
"""

from __future__ import annotations

import ast

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding
from akka_allreduce_tpu.analysis.astutil import terminal_name

_ENCODE_FUNCS = ("_encode_parts", "encode")
_DECODE_FUNCS = ("decode",)


def _find_tags(
    tree: ast.AST,
) -> tuple[ast.Dict, dict[str, int] | None] | None:
    """The module's ``_TAGS`` dict assignment.

    Returns ``None`` when the module has no ``_TAGS`` dict at all (the rule
    does not apply), or ``(dict node, mapping)`` when it does —  with
    ``mapping=None`` when the dict is not the statically-readable
    ``{TypeName: int literal}`` shape. The unreadable case must surface as a
    FINDING, never a silent rule shutdown: one computed tag value would
    otherwise turn the whole exhaustiveness check off while lint stays
    green."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        named = any(
            isinstance(t, ast.Name) and t.id == "_TAGS" for t in targets
        )
        if not named or not isinstance(node.value, ast.Dict):
            continue
        mapping: dict[str, int] = {}
        for key, value in zip(node.value.keys, node.value.values):
            if key is None or not (
                isinstance(value, ast.Constant) and isinstance(value.value, int)
            ):
                return node.value, None  # not statically readable
            name = terminal_name(key)
            if name is None:
                return node.value, None
            mapping[name] = value.value
        if mapping:
            return node.value, mapping
    return None


def _tag_arms(tree: ast.AST, func_names: tuple[str, ...]) -> set[int] | None:
    """Int constants compared against ``tag`` (``tag == N`` / ``N == tag`` /
    ``match tag: case N``) inside the highest-priority function of
    ``func_names`` (earlier names win: ``_encode_parts`` is the arm-carrying
    body, ``encode`` just joins its segments)."""
    funcs = {
        node.name: node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name in func_names
    }
    for fname in func_names:
        node = funcs.get(fname)
        if node is not None:
            arms: set[int] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Compare) and len(sub.ops) == 1 and isinstance(sub.ops[0], ast.Eq):
                    sides = [sub.left, sub.comparators[0]]
                    names = [terminal_name(s) for s in sides]
                    consts = [
                        s.value
                        for s in sides
                        if isinstance(s, ast.Constant)
                        and isinstance(s.value, int)
                    ]
                    if "tag" in names and consts:
                        arms.add(consts[0])
                elif isinstance(sub, ast.Match) and terminal_name(sub.subject) == "tag":
                    for case in sub.cases:
                        pat = case.pattern
                        if isinstance(pat, ast.MatchValue) and isinstance(
                            pat.value, ast.Constant
                        ):
                            arms.add(pat.value.value)
            return arms
    return None


def _dispatched_type_names(trees: dict[str, ast.AST]) -> set[str]:
    """Every type name used as an ``isinstance`` classinfo (or a
    ``match``-case class pattern) anywhere in the analyzed files."""
    names: set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2
            ):
                classinfo = node.args[1]
                elts = (
                    classinfo.elts
                    if isinstance(classinfo, ast.Tuple)
                    else [classinfo]
                )
                for e in elts:
                    name = terminal_name(e)
                    if name is not None:
                        names.add(name)
            elif isinstance(node, ast.MatchClass):
                name = terminal_name(node.cls)
                if name is not None:
                    names.add(name)
    return names


def check_wire_exhaustiveness(
    trees: dict[str, ast.AST], config: ArlintConfig, *, root=None
) -> list[Finding]:
    wire_path: str | None = None
    tags_node: ast.Dict | None = None
    tags: dict[str, int] | None = None
    for path, tree in trees.items():
        found = _find_tags(tree)
        if found is not None:
            wire_path, (tags_node, tags) = path, found
            break
    if wire_path is None or tags_node is None:
        return []  # no wire module in this tree: rule does not apply
    if tags is None:
        return [
            Finding(
                wire_path,
                tags_node.lineno,
                "WIRE001",
                "_TAGS is not a statically-readable {TypeName: int literal} "
                "dict — exhaustiveness cannot be checked; keep tag values "
                "literal (or suppress here with a justification)",
            )
        ]
    tree = trees[wire_path]
    findings: list[Finding] = []
    by_tag = {v: k for k, v in tags.items()}
    for kind, funcs in (("encode", _ENCODE_FUNCS), ("decode", _DECODE_FUNCS)):
        arms = _tag_arms(tree, funcs)
        if arms is None:
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"no {kind} dispatch function ({'/'.join(funcs)}) found "
                    f"alongside _TAGS",
                )
            )
            continue
        for name, tag in sorted(tags.items(), key=lambda kv: kv[1]):
            if tag not in arms:
                findings.append(
                    Finding(
                        wire_path,
                        tags_node.lineno,
                        "WIRE001",
                        f"wire tag {tag} ({name}) has no 'tag == {tag}' arm "
                        f"in {kind} dispatch — messages of this type "
                        f"{'cannot be sent' if kind == 'encode' else 'raise unknown-tag on receive'}",
                    )
                )
        for tag in sorted(arms - set(tags.values())):
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"{kind} dispatch has an arm for tag {tag} which is not "
                    f"in _TAGS — dead arm or missing _TAGS entry",
                )
            )
    if len(trees) == 1:
        # only the wire module itself was analyzed (e.g. `arlint
        # control/wire.py` after editing it): the handler modules are not in
        # the tree, so absence of dispatch arms proves nothing — the
        # encode/decode arm checks above still ran, and the dispatch check
        # runs on every whole-package scan (make lint, tier-1)
        return findings
    dispatched = _dispatched_type_names(trees)
    for name, tag in sorted(tags.items(), key=lambda kv: kv[1]):
        if name not in dispatched:
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"message type {name} (wire tag {tag}) is decodable but "
                    f"no isinstance/match dispatch arm in the analyzed tree "
                    f"handles it — receivers will raise TypeError",
                )
            )
    return findings


# -- WIRE002: version-skew contract -------------------------------------------


def _buffer_param(func: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
    for a in (*func.args.posonlyargs, *func.args.args):
        if a.arg not in ("self", "cls"):
            return a.arg
    return None


def _exact_length_findings(tree: ast.AST, path: str) -> list[Finding]:
    findings: list[Finding] = []
    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "decode" not in func.name:
            continue
        buf = _buffer_param(func)
        if buf is None:
            continue
        for node in ast.walk(func):
            if not (
                isinstance(node, ast.Compare)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Eq, ast.NotEq))
            ):
                continue
            sides = [node.left, node.comparators[0]]
            is_len_of_buf = [
                isinstance(s, ast.Call)
                and isinstance(s.func, ast.Name)
                and s.func.id == "len"
                and len(s.args) == 1
                and isinstance(s.args[0], ast.Name)
                and s.args[0].id == buf
                for s in sides
            ]
            if not any(is_len_of_buf):
                continue
            other = sides[0 if is_len_of_buf[1] else 1]
            if isinstance(other, ast.Constant) and other.value == 0:
                continue  # emptiness check, not a consumed-length assertion
            findings.append(
                Finding(
                    path,
                    node.lineno,
                    "WIRE002",
                    f"exact-length comparison against len({buf}) in decode "
                    f"function '{func.name}' — a newer peer's trailing bytes "
                    f"(trace-trailer class) must be tolerated: bound with "
                    f"'<=', never '=='",
                    end_line=node.end_lineno or node.lineno,
                )
            )
    return findings


def _dataclass_decorator(cls: ast.ClassDef) -> tuple[bool, bool]:
    """(is_dataclass, decorator_kw_only)."""
    for dec in cls.decorator_list:
        call = dec if not isinstance(dec, ast.Call) else dec.func
        if terminal_name(call) == "dataclass":
            kw_only = isinstance(dec, ast.Call) and any(
                kw.arg == "kw_only"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in dec.keywords
            )
            return True, kw_only
    return False, False


def _field_shapes(cls: ast.ClassDef) -> list[tuple[str, int, bool, bool]]:
    """(name, line, has_default, kw_only_escape) per dataclass field, in
    declaration order. ClassVar annotations are not fields."""
    out: list[tuple[str, int, bool, bool]] = []
    for stmt in cls.body:
        if not isinstance(stmt, ast.AnnAssign) or not isinstance(
            stmt.target, ast.Name
        ):
            continue
        ann = stmt.annotation
        ann_base = ann.value if isinstance(ann, ast.Subscript) else ann
        if terminal_name(ann_base) == "ClassVar":
            continue
        has_default = stmt.value is not None
        kw_escape = False
        if (
            isinstance(stmt.value, ast.Call)
            and terminal_name(stmt.value.func) == "field"
        ):
            kwargs = {kw.arg for kw in stmt.value.keywords}
            has_default = bool(kwargs & {"default", "default_factory"})
            kw_escape = any(
                kw.arg == "kw_only"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in stmt.value.keywords
            )
        out.append((stmt.target.id, stmt.lineno, has_default, kw_escape))
    return out


def _trailing_default_findings(
    trees: dict[str, ast.AST], wire_tree: ast.AST, tags: dict[str, int]
) -> list[Finding]:
    """Trailing-with-default contract over wire dataclasses: the ``_TAGS``
    types plus any dataclass the wire module references by name
    (``RoundPolicy`` rides inside frames without its own tag)."""
    referenced = {
        node.id for node in ast.walk(wire_tree) if isinstance(node, ast.Name)
    }
    wanted = set(tags) | referenced
    findings: list[Finding] = []
    for path in sorted(trees):
        for cls in ast.walk(trees[path]):
            if not isinstance(cls, ast.ClassDef) or cls.name not in wanted:
                continue
            is_dc, dec_kw_only = _dataclass_decorator(cls)
            if not is_dc:
                continue
            fields = _field_shapes(cls)
            seen_default = False
            for name, line, has_default, kw_escape in fields:
                if has_default:
                    seen_default = True
                    continue
                # defaultless-after-defaulted, or the field(kw_only=True)
                # escape hatch anywhere (it exists only to permit that shape)
                if seen_default or kw_escape:
                    findings.append(
                        Finding(
                            path,
                            line,
                            "WIRE002",
                            f"wire dataclass {cls.name}: field '{name}' has "
                            f"no default but follows defaulted fields"
                            + (" (via the kw_only escape)" if kw_escape else "")
                            + " — an old decoder constructing with fewer "
                            "fields breaks; new fields must be trailing-"
                            "with-default (RoundPolicy skew contract)",
                        )
                    )
            if dec_kw_only and any(not d for _, _, d, _ in fields):
                findings.append(
                    Finding(
                        path,
                        cls.lineno,
                        "WIRE002",
                        f"wire dataclass {cls.name} uses @dataclass("
                        f"kw_only=True) with defaultless fields — this "
                        f"defeats the trailing-with-default skew contract; "
                        f"give every post-v1 field a default",
                    )
                )
    return findings


def _owned_range_findings(
    trees: dict[str, ast.AST],
    tags: dict[str, int],
    tags_node: ast.Dict,
    wire_path: str,
    config: ArlintConfig,
) -> list[Finding]:
    findings: list[Finding] = []
    for suffix, lo, hi in config.wire_owned:
        owner_paths = [p for p in sorted(trees) if p.endswith(suffix)]
        if not owner_paths:
            continue  # owner module not in this scan (single-file run)
        owned_types = set()
        for p in owner_paths:
            for cls in ast.walk(trees[p]):
                if isinstance(cls, ast.ClassDef) and cls.name in tags:
                    owned_types.add(cls.name)
        actual = sorted(tags[t] for t in owned_types)
        expected = list(range(lo, hi + 1))
        if actual == expected:
            continue
        stray = [t for t in actual if t not in expected]
        missing = [t for t in expected if t not in actual]
        detail = []
        if stray:
            detail.append(
                f"types defined in {suffix} hold out-of-range tag(s) "
                f"{stray}"
            )
        if missing:
            holders = sorted(
                name for name, tag in tags.items() if tag in missing
            )
            detail.append(
                f"tag(s) {missing} in the owned range belong to types "
                f"defined elsewhere ({', '.join(holders) or 'none'})"
            )
        findings.append(
            Finding(
                wire_path,
                tags_node.lineno,
                "WIRE002",
                f"wire-owned range {suffix}:{lo}-{hi} violated — "
                f"{'; '.join(detail)} (module-owned tag ranges are the "
                f"rolling-upgrade coordination contract)",
            )
        )
    return findings


def check_wire_skew(
    trees: dict[str, ast.AST], config: ArlintConfig, *, root=None
) -> list[Finding]:
    wire_path: str | None = None
    tags_node: ast.Dict | None = None
    tags: dict[str, int] | None = None
    for path, tree in trees.items():
        found = _find_tags(tree)
        if found is not None:
            wire_path, (tags_node, tags) = path, found
            break
    if wire_path is None or tags_node is None:
        return []  # no wire module in this tree: rule does not apply
    wire_tree = trees[wire_path]
    findings = _exact_length_findings(wire_tree, wire_path)
    if tags is None:
        # WIRE001 already reports the unreadable-_TAGS case; the skew checks
        # that need the mapping simply cannot run
        return findings
    values = sorted(tags.values())
    if values != list(range(1, len(values) + 1)):
        dupes = sorted({v for v in values if values.count(v) > 1})
        findings.append(
            Finding(
                wire_path,
                tags_node.lineno,
                "WIRE002",
                f"_TAGS values must be unique and contiguous from 1 (the "
                f"test_chaos pin, statically): got {values}"
                + (f" with duplicate(s) {dupes}" if dupes else "")
                + " — retiring a tag means reserving it, not renumbering",
            )
        )
    findings.extend(_trailing_default_findings(trees, wire_tree, tags))
    findings.extend(
        _owned_range_findings(trees, tags, tags_node, wire_path, config)
    )
    return findings
