"""WIRE001 — wire-tag exhaustiveness, checked across the analyzed tree.

The control plane's binary codec (``control/wire.py``) is a hand-rolled
tag-dispatch pair: ``_TAGS`` maps message type -> tag byte, ``_encode_parts``
and ``decode`` each carry one ``tag == N`` arm per entry, and every decoded
message must reach an ``isinstance`` dispatch arm in some handler
(``control/worker.py``, ``control/bootstrap.py``, the line/grid masters).
Three places to update per new message type, and nothing ties them together
at runtime: a missed decode arm is a silent ``ValueError: unknown wire tag``
under load, a missed dispatch arm a ``TypeError`` mid-round. This rule makes
the tie mechanical:

- every ``_TAGS`` tag has an encode arm and a decode arm, and every arm's
  tag exists in ``_TAGS`` (set equality, both directions);
- every ``_TAGS`` message type name appears in at least one
  ``isinstance(..., Type)`` / ``match``-class dispatch somewhere in the
  analyzed files.

The rule activates on any analyzed module that assigns a dict literal named
``_TAGS`` with int values and defines ``decode`` — i.e. the wire module
itself; trees without one simply skip the rule.
"""

from __future__ import annotations

import ast

from akka_allreduce_tpu.analysis.config import ArlintConfig
from akka_allreduce_tpu.analysis.core import Finding
from akka_allreduce_tpu.analysis.rules import terminal_name

_ENCODE_FUNCS = ("_encode_parts", "encode")
_DECODE_FUNCS = ("decode",)


def _find_tags(
    tree: ast.AST,
) -> tuple[ast.Dict, dict[str, int] | None] | None:
    """The module's ``_TAGS`` dict assignment.

    Returns ``None`` when the module has no ``_TAGS`` dict at all (the rule
    does not apply), or ``(dict node, mapping)`` when it does —  with
    ``mapping=None`` when the dict is not the statically-readable
    ``{TypeName: int literal}`` shape. The unreadable case must surface as a
    FINDING, never a silent rule shutdown: one computed tag value would
    otherwise turn the whole exhaustiveness check off while lint stays
    green."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        named = any(
            isinstance(t, ast.Name) and t.id == "_TAGS" for t in targets
        )
        if not named or not isinstance(node.value, ast.Dict):
            continue
        mapping: dict[str, int] = {}
        for key, value in zip(node.value.keys, node.value.values):
            if key is None or not (
                isinstance(value, ast.Constant) and isinstance(value.value, int)
            ):
                return node.value, None  # not statically readable
            name = terminal_name(key)
            if name is None:
                return node.value, None
            mapping[name] = value.value
        if mapping:
            return node.value, mapping
    return None


def _tag_arms(tree: ast.AST, func_names: tuple[str, ...]) -> set[int] | None:
    """Int constants compared against ``tag`` (``tag == N`` / ``N == tag`` /
    ``match tag: case N``) inside the highest-priority function of
    ``func_names`` (earlier names win: ``_encode_parts`` is the arm-carrying
    body, ``encode`` just joins its segments)."""
    funcs = {
        node.name: node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name in func_names
    }
    for fname in func_names:
        node = funcs.get(fname)
        if node is not None:
            arms: set[int] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Compare) and len(sub.ops) == 1 and isinstance(sub.ops[0], ast.Eq):
                    sides = [sub.left, sub.comparators[0]]
                    names = [terminal_name(s) for s in sides]
                    consts = [
                        s.value
                        for s in sides
                        if isinstance(s, ast.Constant)
                        and isinstance(s.value, int)
                    ]
                    if "tag" in names and consts:
                        arms.add(consts[0])
                elif isinstance(sub, ast.Match) and terminal_name(sub.subject) == "tag":
                    for case in sub.cases:
                        pat = case.pattern
                        if isinstance(pat, ast.MatchValue) and isinstance(
                            pat.value, ast.Constant
                        ):
                            arms.add(pat.value.value)
            return arms
    return None


def _dispatched_type_names(trees: dict[str, ast.AST]) -> set[str]:
    """Every type name used as an ``isinstance`` classinfo (or a
    ``match``-case class pattern) anywhere in the analyzed files."""
    names: set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2
            ):
                classinfo = node.args[1]
                elts = (
                    classinfo.elts
                    if isinstance(classinfo, ast.Tuple)
                    else [classinfo]
                )
                for e in elts:
                    name = terminal_name(e)
                    if name is not None:
                        names.add(name)
            elif isinstance(node, ast.MatchClass):
                name = terminal_name(node.cls)
                if name is not None:
                    names.add(name)
    return names


def check_wire_exhaustiveness(
    trees: dict[str, ast.AST], config: ArlintConfig
) -> list[Finding]:
    wire_path: str | None = None
    tags_node: ast.Dict | None = None
    tags: dict[str, int] | None = None
    for path, tree in trees.items():
        found = _find_tags(tree)
        if found is not None:
            wire_path, (tags_node, tags) = path, found
            break
    if wire_path is None or tags_node is None:
        return []  # no wire module in this tree: rule does not apply
    if tags is None:
        return [
            Finding(
                wire_path,
                tags_node.lineno,
                "WIRE001",
                "_TAGS is not a statically-readable {TypeName: int literal} "
                "dict — exhaustiveness cannot be checked; keep tag values "
                "literal (or suppress here with a justification)",
            )
        ]
    tree = trees[wire_path]
    findings: list[Finding] = []
    by_tag = {v: k for k, v in tags.items()}
    for kind, funcs in (("encode", _ENCODE_FUNCS), ("decode", _DECODE_FUNCS)):
        arms = _tag_arms(tree, funcs)
        if arms is None:
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"no {kind} dispatch function ({'/'.join(funcs)}) found "
                    f"alongside _TAGS",
                )
            )
            continue
        for name, tag in sorted(tags.items(), key=lambda kv: kv[1]):
            if tag not in arms:
                findings.append(
                    Finding(
                        wire_path,
                        tags_node.lineno,
                        "WIRE001",
                        f"wire tag {tag} ({name}) has no 'tag == {tag}' arm "
                        f"in {kind} dispatch — messages of this type "
                        f"{'cannot be sent' if kind == 'encode' else 'raise unknown-tag on receive'}",
                    )
                )
        for tag in sorted(arms - set(tags.values())):
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"{kind} dispatch has an arm for tag {tag} which is not "
                    f"in _TAGS — dead arm or missing _TAGS entry",
                )
            )
    if len(trees) == 1:
        # only the wire module itself was analyzed (e.g. `arlint
        # control/wire.py` after editing it): the handler modules are not in
        # the tree, so absence of dispatch arms proves nothing — the
        # encode/decode arm checks above still ran, and the dispatch check
        # runs on every whole-package scan (make lint, tier-1)
        return findings
    dispatched = _dispatched_type_names(trees)
    for name, tag in sorted(tags.items(), key=lambda kv: kv[1]):
        if name not in dispatched:
            findings.append(
                Finding(
                    wire_path,
                    tags_node.lineno,
                    "WIRE001",
                    f"message type {name} (wire tag {tag}) is decodable but "
                    f"no isinstance/match dispatch arm in the analyzed tree "
                    f"handles it — receivers will raise TypeError",
                )
            )
    return findings
