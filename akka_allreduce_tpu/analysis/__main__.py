"""CLI: ``python -m akka_allreduce_tpu.analysis [paths...]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings, 2 = usage or
configuration error — identical across every output mode, so CI gates on the
code and picks presentation freely. ``--format=text`` (default) prints
``file:line: RULE message`` per finding; ``--format=json`` (alias ``--json``)
emits a machine-readable report; ``--format=github`` emits workflow-command
annotations (``::error file=...``) that annotate diffs in GitHub CI.
``--sarif OUT.json`` additionally writes a SARIF 2.1.0 log alongside any
format, for code-scanning upload in any CI.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from akka_allreduce_tpu.analysis.config import (
    ArlintConfig,
    ConfigError,
    load_config,
)
from akka_allreduce_tpu.analysis.core import (
    analyze_paths,
    apply_baseline,
    load_baseline,
    write_baseline,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m akka_allreduce_tpu.analysis",
        description="arlint: async-safety / buffer-aliasing / "
        "wire-exhaustiveness static analyzer (ANALYSIS.md documents the "
        "rules and the bugs that motivated them)",
    )
    p.add_argument(
        "paths", nargs="+", type=Path, help="files or directories to analyze"
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="JSON report on stdout (alias for --format=json)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "github"),
        default=None,
        help="output mode: text (default), json, or github "
        "workflow-command annotations",
    )
    p.add_argument(
        "--sarif",
        type=Path,
        default=None,
        metavar="OUT.json",
        help="also write a SARIF 2.1.0 log to this path (any --format)",
    )
    p.add_argument(
        "--rules",
        help="comma-separated rule subset (default: all, or [tool.arlint] "
        "rules)",
    )
    p.add_argument(
        "--config",
        type=Path,
        default=None,
        help="pyproject.toml carrying [tool.arlint] (default: nearest one "
        "above the first path)",
    )
    p.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline JSON overriding the configured one",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline: report everything",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline file from the current findings and exit 0",
    )
    args = p.parse_args(argv)
    if args.format is None:
        args.format = "json" if args.json else "text"
    elif args.json and args.format != "json":
        print(
            "arlint: --json conflicts with --format="
            f"{args.format}", file=sys.stderr
        )
        return 2

    for path in args.paths:
        if not path.exists():
            print(f"arlint: no such path: {path}", file=sys.stderr)
            return 2
    try:
        config = load_config(args.paths, pyproject=args.config)
    except ConfigError as exc:
        print(f"arlint: {exc}", file=sys.stderr)
        return 2
    if args.rules:
        config.rules = tuple(
            r.strip() for r in args.rules.split(",") if r.strip()
        )
    if config.rules is not None:
        # an unvalidated typo ('ASYNC01') would silently select NOTHING and
        # turn the whole gate green — unknown rule ids are a usage error
        from akka_allreduce_tpu.analysis import ALL_RULES

        unknown = sorted(set(config.rules) - set(ALL_RULES))
        if unknown:
            print(
                f"arlint: unknown rule(s) {', '.join(unknown)}; known: "
                f"{', '.join(ALL_RULES)}",
                file=sys.stderr,
            )
            return 2

    findings = analyze_paths(args.paths, config)

    baseline_path = (
        args.baseline if args.baseline is not None else config.baseline_path()
    )
    if args.no_baseline:
        baseline_path = None
    if args.write_baseline:
        if baseline_path is None:
            print(
                "arlint: --write-baseline needs --baseline or a "
                "[tool.arlint] baseline entry",
                file=sys.stderr,
            )
            return 2
        write_baseline(baseline_path, findings)
        print(
            f"arlint: wrote {len(findings)} finding(s) to {baseline_path}"
        )
        return 0

    baselined: list = []
    if baseline_path is not None:
        findings, baselined = apply_baseline(
            findings, load_baseline(baseline_path)
        )

    if args.sarif is not None:
        args.sarif.write_text(
            json.dumps(_sarif_log(findings), indent=2) + "\n",
            encoding="utf-8",
        )

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "baselined": [f.as_dict() for f in baselined],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    elif args.format == "github":
        for f in findings:
            print(
                f"::error file={f.path},line={f.line},"
                f"endLine={max(f.line, f.end_line)},title={f.rule}::"
                f"{_gh_escape(f.message)}"
            )
        note = f", {len(baselined)} baselined" if baselined else ""
        print(
            f"arlint: {len(findings)} unsuppressed finding(s){note}",
            file=sys.stderr,
        )
    else:
        for f in findings:
            print(f.render())
        note = f", {len(baselined)} baselined" if baselined else ""
        print(
            f"arlint: {len(findings)} unsuppressed finding(s){note}",
            file=sys.stderr,
        )
    return 1 if findings else 0


def _gh_escape(text: str) -> str:
    """Workflow-command data escaping (the %0A/%0D/%25 triple GitHub's
    runner unescapes; a raw newline would terminate the command)."""
    return text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def _sarif_log(findings: list) -> dict:
    """Minimal SARIF 2.1.0 log — one run, one result per finding, rule ids
    registered in the driver so code-scanning UIs group by rule."""
    from akka_allreduce_tpu.analysis import ALL_RULES

    seen_rules = sorted(
        {f.rule for f in findings} | set(ALL_RULES)
    )
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "arlint",
                        "informationUri": "ANALYSIS.md",
                        "rules": [{"id": r} for r in seen_rules],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": f.line,
                                        "endLine": max(f.line, f.end_line),
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


if __name__ == "__main__":
    sys.exit(main())
