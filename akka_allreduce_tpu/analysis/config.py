"""``[tool.arlint]`` configuration.

The container targets Python 3.10 (no ``tomllib``) and the analyzer must not
grow third-party deps, so this module reads the ONE table it needs with a
deliberately small parser: ``[tool.arlint]`` holding scalar strings, booleans,
integers, and flat string lists. That subset is the documented contract
(ANALYSIS.md); anything fancier in the block is a config error, not a silent
skip. On 3.11+ the real ``tomllib`` is used instead.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

try:  # Python >= 3.11
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - exercised on 3.10 only
    tomllib = None


@dataclasses.dataclass
class ArlintConfig:
    """Resolved analyzer configuration (defaults = no pyproject needed)."""

    #: rules to run (None = all registered rules)
    rules: tuple[str, ...] | None = None
    #: baseline file path, relative to the pyproject that named it
    baseline: str | None = None
    #: path substrings excluded from analysis (fixtures, generated code)
    exclude: tuple[str, ...] = ()
    #: extra dotted callables ASYNC001 treats as blocking
    async001_blocking: tuple[str, ...] = ()
    #: markers BUF001 treats as recycled-buffer sources, matched against
    #: whole underscore-separated segments of the name ("ring" hits
    #: ``_ring``/``ring_buf`` but never ``_instring``)
    buf001_markers: tuple[str, ...] = ("ring", "pool", "recycled")
    #: path suffixes of modules declared deterministic — DET001/002/003 run
    #: only inside these (empty = the DET rules are silent)
    det_modules: tuple[str, ...] = ()
    #: metric-table document OBS001 checks Registry names against, relative
    #: to the pyproject that named it (None = rule is silent)
    obs_doc: str | None = None
    #: module-owned wire-tag ranges for WIRE002, parsed from
    #: ``"path/suffix.py:lo-hi"`` entries
    wire_owned: tuple[tuple[str, int, int], ...] = ()
    #: where the config came from (for error messages / baseline resolution)
    source: Path | None = None

    def baseline_path(self) -> Path | None:
        if self.baseline is None:
            return None
        p = Path(self.baseline)
        if not p.is_absolute() and self.source is not None:
            p = self.source.parent / p
        return p


class ConfigError(ValueError):
    """Malformed ``[tool.arlint]`` block."""


_KV = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*=\s*(.+?)\s*$")


def _parse_value(raw: str, *, key: str):
    raw = raw.strip()
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        items = []
        for part in _split_list(inner):
            items.append(_parse_value(part, key=key))
        return items
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    if re.fullmatch(r"-?\d+", raw):
        return int(raw)
    raise ConfigError(f"[tool.arlint] {key}: unsupported TOML value {raw!r}")


def _split_list(inner: str) -> list[str]:
    """Split a flat TOML list body on commas outside quotes."""
    parts: list[str] = []
    buf = ""
    quote: str | None = None
    for ch in inner:
        if quote is not None:
            buf += ch
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            buf += ch
        elif ch == ",":
            if buf.strip():
                parts.append(buf.strip())
            buf = ""
        else:
            buf += ch
    if buf.strip():
        parts.append(buf.strip())
    return parts


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment that sits outside any quoted string —
    tomllib accepts them everywhere, so the 3.10 fallback must too."""
    quote: str | None = None
    for i, ch in enumerate(line):
        if quote is not None:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i].rstrip()
    return line


def _read_arlint_table_minitoml(text: str) -> dict:
    """Extract ``[tool.arlint]`` key/values from raw TOML text (3.10 path)."""
    table: dict = {}
    in_table = False
    pending = ""  # accumulates a multi-line list value
    for line in text.splitlines():
        stripped = _strip_comment(line.strip()).strip()
        if pending:
            if not stripped:
                continue
            pending += " " + stripped
            if stripped.endswith("]"):
                m = _KV.match(pending)
                assert m is not None
                table[m.group(1)] = _parse_value(m.group(2), key=m.group(1))
                pending = ""
            continue
        if not stripped or stripped.startswith("#"):
            continue
        if stripped.startswith("["):
            # a table header may carry a trailing comment; strip it before
            # matching so `[tool.arlint]  # config` is still recognized
            header = stripped.split("#", 1)[0].strip()
            in_table = header == "[tool.arlint]"
            continue
        if not in_table:
            continue
        m = _KV.match(stripped)
        if m is None:
            raise ConfigError(f"[tool.arlint]: cannot parse line {stripped!r}")
        if m.group(2).startswith("[") and not m.group(2).endswith("]"):
            pending = stripped
            continue
        table[m.group(1)] = _parse_value(m.group(2), key=m.group(1))
    if pending:
        # an unterminated multi-line list must be a loud error, never a
        # silently dropped key
        raise ConfigError(
            f"[tool.arlint]: unterminated list starting at {pending!r}"
        )
    return table


def _read_arlint_table(path: Path) -> dict:
    text = path.read_text(encoding="utf-8")
    if tomllib is not None:
        data = tomllib.loads(text)
        return data.get("tool", {}).get("arlint", {})
    return _read_arlint_table_minitoml(text)


def _str_tuple(value, *, key: str) -> tuple[str, ...]:
    if not isinstance(value, list) or not all(
        isinstance(v, str) for v in value
    ):
        raise ConfigError(f"[tool.arlint] {key}: expected a list of strings")
    return tuple(value)


def config_from_table(table: dict, *, source: Path | None = None) -> ArlintConfig:
    cfg = ArlintConfig(source=source)
    for key, value in table.items():
        norm = key.replace("-", "_")
        if norm == "rules":
            cfg.rules = _str_tuple(value, key=key)
        elif norm == "baseline":
            if not isinstance(value, str):
                raise ConfigError("[tool.arlint] baseline: expected a string")
            cfg.baseline = value
        elif norm == "exclude":
            cfg.exclude = _str_tuple(value, key=key)
        elif norm == "async001_blocking":
            cfg.async001_blocking = _str_tuple(value, key=key)
        elif norm == "buf001_markers":
            cfg.buf001_markers = _str_tuple(value, key=key)
        elif norm == "det_modules":
            cfg.det_modules = _str_tuple(value, key=key)
        elif norm == "obs_doc":
            if not isinstance(value, str):
                raise ConfigError("[tool.arlint] obs-doc: expected a string")
            cfg.obs_doc = value
        elif norm == "wire_owned":
            cfg.wire_owned = tuple(
                _parse_wire_owned(v) for v in _str_tuple(value, key=key)
            )
        else:
            raise ConfigError(f"[tool.arlint]: unknown key {key!r}")
    return cfg


def _parse_wire_owned(entry: str) -> tuple[str, int, int]:
    m = re.fullmatch(r"(?P<suffix>[^:]+):(?P<lo>\d+)-(?P<hi>\d+)", entry)
    if m is None:
        raise ConfigError(
            f"[tool.arlint] wire-owned: expected 'path/suffix.py:lo-hi', "
            f"got {entry!r}"
        )
    lo, hi = int(m.group("lo")), int(m.group("hi"))
    if lo > hi:
        raise ConfigError(
            f"[tool.arlint] wire-owned: empty range in {entry!r}"
        )
    return (m.group("suffix"), lo, hi)


def find_pyproject(start: Path) -> Path | None:
    """Nearest pyproject.toml at or above ``start``."""
    cur = start if start.is_dir() else start.parent
    for candidate in (cur, *cur.parents):
        p = candidate / "pyproject.toml"
        if p.is_file():
            return p
    return None


def load_config(
    paths: list[Path] | None = None, *, pyproject: Path | None = None
) -> ArlintConfig:
    """Resolve config: explicit ``pyproject`` wins, else the nearest
    pyproject.toml above the first analyzed path; no file -> defaults."""
    if pyproject is None and paths:
        pyproject = find_pyproject(paths[0].resolve())
    if pyproject is None:
        return ArlintConfig()
    return config_from_table(_read_arlint_table(pyproject), source=pyproject)
