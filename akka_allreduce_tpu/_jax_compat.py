"""OPT-IN compatibility shims for older jax installs (import side effect).

This codebase targets the modern public API surface (``jax.shard_map`` with
``check_vma``, ``lax.pcast``); some containers pin an older jax where those
names live elsewhere or do not exist. Importing this module installs gated
aliases ONCE — a no-op on modern jax — so much of the same source runs on
both (verified: the DP trainer trains and ``measure_allreduce`` measures on
jax 0.4.37 with the shims live).

Deliberately NOT auto-imported: on old jax the shims turn some fast,
visible API failures into long-running semi-compatible executions (e.g.
the pre-VMA pipeline-elastic path can hang), which is worse than failing
loudly under a test budget. Operators on an old-jax container opt in with
``import akka_allreduce_tpu._jax_compat`` before building meshes.

Shim semantics on old jax:

- ``jax.shard_map``: aliases ``jax.experimental.shard_map.shard_map``.
  ``check_vma`` does not translate to the old ``check_rep`` checker (the
  pre-VMA replication inference predates several primitives used here and
  rejects valid programs), so the static checker is disabled — the runtime
  replica asserts in ``utils/verify.py`` are exactly the compensation this
  codebase already carries for unchecked regions.
- ``lax.pcast``: the varying-manual-axes *type* cast; with no VMA type
  system (and the static checker off) it is the identity on data.
- ``lax.axis_size``: ``psum(1, axis)`` — the long-standing idiom; on a
  constant it folds at trace time to the static size, which is what the
  ZeRO-1 pad-shape arithmetic needs.
"""

from __future__ import annotations

import jax
from jax import lax


def _install() -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(
            f=None, /, *, mesh=None, in_specs=None, out_specs=None,
            check_vma=None, **kw,
        ):
            kw.setdefault("check_rep", False)
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
            )

        jax.shard_map = shard_map

    if not hasattr(lax, "pcast"):

        def pcast(x, axis_name=None, *, to=None):
            return x

        lax.pcast = pcast

    if not hasattr(lax, "axis_size"):

        def axis_size(axis_name):
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size


_install()
