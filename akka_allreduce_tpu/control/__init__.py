"""Control plane: the reference's actor roles as transport-agnostic state machines.

The reference's ``Master`` / ``LineMaster`` / ``AllreduceWorker`` actors
(SURVEY.md §2 L2-L3) become pure-Python message handlers: each exposes
``handle(msg) -> list[Envelope]`` and owns no thread — single-threaded message
processing gives the same no-races-by-construction property as the actor model
(SURVEY.md §6 "Race detection"). A router (in-process ``LocalRouter`` for the
local dev mode, gRPC/TCP for multi-host) delivers envelopes.

On TPU the worker's data plane is the XLA collective (``comm``); the host engine
data path in ``worker.py`` carries real payloads only for tests, CPU fallback,
and DCN-side chunk movement — exactly the control/data split of the north star
(BASELINE.json:5).
"""

from akka_allreduce_tpu.control.envelope import Envelope, MASTER, master_addr, peer_addr  # noqa: F401
from akka_allreduce_tpu.control.worker import AllreduceWorker  # noqa: F401
from akka_allreduce_tpu.control.line_master import LineMaster  # noqa: F401
from akka_allreduce_tpu.control.grid_master import GridMaster  # noqa: F401
from akka_allreduce_tpu.control.local import LocalAllreduceSystem, LocalRouter  # noqa: F401
