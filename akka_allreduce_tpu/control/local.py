"""In-process router + local N-worker system — the reference's single-JVM dev
mode (BASELINE.json:7 "4 local JVM workers"; SURVEY.md §5 "Integration").

``LocalRouter`` plays the transport: FIFO delivery between registered handlers,
with a pluggable drop filter for fault injection (the reference's tests inject
faults exactly this way — by omitting messages, SURVEY.md §5).

Run as a module for the config-1 throughput demo:

    python -m akka_allreduce_tpu.control.local --nodes 4 --size 1000000 --rounds 20
"""

from __future__ import annotations

import argparse
import logging
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from akka_allreduce_tpu.config import AllreduceConfig
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.grid_master import GridMaster, dim_worker_id
from akka_allreduce_tpu.control.node import AllreduceNode
from akka_allreduce_tpu.protocol import (
    AllReduceInput,
    AllReduceInputRequest,
    AllReduceOutput,
)

log = logging.getLogger(__name__)

DropFilter = Callable[[Envelope], bool]


class LocalRouter:
    """FIFO in-process message delivery with fault injection.

    Fault injection comes in two strengths: the original ``drop_filter``
    (omit matching messages — the reference's own technique) and the
    shared ``chaos`` hook point (control/chaos.py — the SAME injector the
    TCP transport takes): drop, duplicate, reorder (push-to-back; also how
    a planned delay manifests in a synchronous router), and payload
    corruption via a wire-codec round trip, so even the in-process mode
    exercises the real tag-2/3 checksum rejection path.
    """

    def __init__(self, drop_filter: DropFilter | None = None) -> None:
        self._handlers: dict[str, Callable[[Any], list[Envelope]]] = {}
        self._prefix_handlers: dict[
            str, Callable[[int, Any], list[Envelope]]
        ] = {}
        self._queue: deque[Envelope] = deque()
        self.drop_filter = drop_filter
        self.chaos = None  # control.chaos.ChaosInjector | None
        self.delivered = 0
        self.dropped = 0

    def register(self, addr: str, handler: Callable[[Any], list[Envelope]]) -> None:
        self._handlers[addr] = handler

    def register_prefix(
        self, prefix: str, handler: Callable[[int, Any], list[Envelope]]
    ) -> None:
        """Handle every ``prefix:<int>`` address (e.g. all ``worker:N``)."""
        self._prefix_handlers[prefix] = handler

    def send_all(self, envelopes: list[Envelope]) -> None:
        held: list[Envelope] = []
        for env in envelopes:
            if self.drop_filter is not None and self.drop_filter(env):
                self.dropped += 1
                continue
            if self.chaos is not None:
                act = self.chaos.plan_send(env)
                if act is not None:
                    self._apply_chaos(env, act, held)
                    continue
            self._queue.append(env)
        # a synchronous router has no clock to hold a message against:
        # delay/reorder become hold-until-end-of-batch, so every message
        # sent LATER in the same batch overtakes the held one — the same
        # FIFO violation the TCP transport's delay fault produces
        self._queue.extend(held)

    def _apply_chaos(
        self, env: Envelope, act, held: list[Envelope]
    ) -> None:
        if act.drop or act.fail:
            self.dropped += 1  # no failure callbacks in-process: both drop
            return
        if act.corrupt:
            corrupted = self._corrupt_roundtrip(env, act)
            if corrupted is None:
                self.dropped += 1  # checksum rejected the flip, as it must
                return
            env = corrupted
        sink = held if act.delay_s > 0 else self._queue
        sink.append(env)
        if act.duplicate:
            sink.append(env)

    def _corrupt_roundtrip(self, env: Envelope, act) -> Envelope | None:
        """Apply the payload bit-flip through the REAL wire codec: encode,
        flip, decode. Returns None when decode rejects the frame (the
        checksum doing its job — the overwhelmingly common case)."""
        from akka_allreduce_tpu.control import wire

        try:
            # honor the envelope's per-frame wire mode (RoundPolicy): an
            # in-process int8/f16 round should corrupt the SAME bytes the
            # TCP path would put on the wire
            parts = wire.encode_frame_parts(env.dest, env.msg, wire=env.wire)
            parts = self.chaos.corrupt_frame_parts(parts, act)
            body = b"".join(bytes(p) for p in parts)[4:]
            dest, msg = wire.decode_frame_body(body)
            return Envelope(dest, msg, via=env.via)
        except Exception:
            return None

    def run(self, max_messages: int = 1_000_000) -> int:
        """Deliver until quiescent; returns messages delivered."""
        n = 0
        while self._queue and n < max_messages:
            env = self._queue.popleft()
            handler = self._handlers.get(env.dest)
            if handler is None:
                prefix, _, suffix = env.dest.rpartition(":")
                ph = self._prefix_handlers.get(prefix)
                if ph is not None:
                    handler = lambda m, _ph=ph, _id=int(suffix): _ph(_id, m)
            if handler is None:
                log.warning("no handler for %s; dropping", env.dest)
                self.dropped += 1
                continue
            self.send_all(handler(env.msg))
            n += 1
        self.delivered += n
        return n


class LocalAllreduceSystem:
    """N nodes + grid master + router, fully in-process (dev/test mode)."""

    def __init__(
        self,
        n_nodes: int,
        data_sources,
        data_sinks,
        config: AllreduceConfig,
        drop_filter: DropFilter | None = None,
    ) -> None:
        assert len(data_sources) == n_nodes and len(data_sinks) == n_nodes
        self.config = config
        dims = config.master.dimensions
        self.master = GridMaster(
            config.threshold,
            config.master,
            config.line_master,
        )
        self.router = LocalRouter(drop_filter)
        if config.chaos.enabled:
            # dev-mode chaos: ONE injector plays the whole single-process
            # cluster (role: master — it owns the router), same spec
            # grammar and seed determinism as the TCP deployment
            from akka_allreduce_tpu.control.chaos import (
                MASTER_ROLE,
                ChaosInjector,
            )

            self.router.chaos = ChaosInjector(
                config.chaos.seed,
                config.chaos.spec,
                role=MASTER_ROLE,
                dims=dims,
            )
        self.nodes: dict[int, AllreduceNode] = {}
        for i in range(n_nodes):
            self.add_node(i, data_sources[i], data_sinks[i], join=False)
        self.router.register_prefix("worker", self._route_to_node)
        self.router.register_prefix("line_master", self.master.handle_for_line)

    def _route_to_node(self, worker_id: int, msg: Any) -> list[Envelope]:
        dims = self.config.master.dimensions
        node_id = worker_id // dims
        node = self.nodes.get(node_id)
        if node is None:
            return []  # node left the cluster; transport drops the message
        return node.handle(worker_id, msg)

    def add_node(self, node_id: int, source, sink, *, join: bool = True) -> None:
        self.nodes[node_id] = AllreduceNode(
            node_id,
            self.config.master.dimensions,
            source,
            sink,
            self.config.metadata,
            self.config.threshold,
            self.config.worker,
        )
        if join:
            self.router.send_all(self.master.member_up(node_id))

    def remove_node(self, node_id: int) -> None:
        self.nodes.pop(node_id, None)
        self.router.send_all(self.master.member_unreachable(node_id))

    def start(self) -> None:
        for node_id in sorted(self.nodes):
            self.router.send_all(self.master.member_up(node_id))

    def run_until_quiescent(self) -> int:
        return self.router.run()


def _main() -> None:
    parser = argparse.ArgumentParser(description="local N-worker allreduce demo")
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--size", type=int, default=1_000_000)
    parser.add_argument("--rounds", type=int, default=20)
    parser.add_argument("--chunk", type=int, default=262_144)
    parser.add_argument("--dims", type=int, default=1)
    parser.add_argument("--th", type=float, default=1.0, help="all three thresholds")
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument(
        "--chaos-spec", default="",
        help="dev-mode chaos on the in-process router (drop/duplicate/"
        "reorder/corrupt — RESILIENCE.md); empty = off",
    )
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="%(message)s")

    from akka_allreduce_tpu.config import (
        ChaosConfig,
        LineMasterConfig,
        MasterConfig,
        MetaDataConfig,
        ThresholdConfig,
        WorkerConfig,
    )

    cfg = AllreduceConfig(
        threshold=ThresholdConfig(args.th, args.th, args.th),
        metadata=MetaDataConfig(data_size=args.size, max_chunk_size=args.chunk),
        line_master=LineMasterConfig(round_window=2, max_rounds=args.rounds),
        master=MasterConfig(node_num=args.nodes, dimensions=args.dims),
        # demo sources return fixed arrays -> snapshot contract holds
        worker=WorkerConfig(zero_copy_scatter=True),
        chaos=ChaosConfig(seed=args.chaos_seed, spec=args.chaos_spec),
    )

    rng = np.random.default_rng(0)
    inputs = [
        rng.standard_normal(args.size).astype(np.float32)
        for _ in range(args.nodes)
    ]
    flushes: list[list[int]] = [[] for _ in range(args.nodes)]

    def source_for(i):
        return lambda req: AllReduceInput(inputs[i])

    def sink_for(i):
        return lambda out: flushes[i].append(out.iteration)

    t0 = time.perf_counter()
    system = LocalAllreduceSystem(
        args.nodes,
        [source_for(i) for i in range(args.nodes)],
        [sink_for(i) for i in range(args.nodes)],
        cfg,
    )
    system.start()
    system.run_until_quiescent()
    dt = time.perf_counter() - t0
    # a "round" is one collective across ALL nodes; count rounds every node
    # flushed, not per-node flush events
    completed = min(len(f) for f in flushes)
    total_bytes = args.size * 4 * completed
    # provenance next to the number (same flag the TCP cluster prints):
    # throughput without the engine path recorded is not comparable.
    # loaded() (non-blocking, no build) — available() could compile for
    # minutes and then describe a library the finished run never used
    from akka_allreduce_tpu import native

    print(
        f"nodes={args.nodes} size={args.size} rounds_completed={completed} "
        f"(per-node flushes: {[len(f) for f in flushes]}) "
        f"elapsed={dt:.3f}s allreduce_throughput={total_bytes / dt / 1e6:.1f} MB/s "
        f"engine={'native' if native.loaded() else 'numpy'} "
        f"(host engine; the TPU data plane runs this as one XLA collective)"
    )


if __name__ == "__main__":
    _main()
