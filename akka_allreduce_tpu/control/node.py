"""Per-node supervisor: one worker per grid dimension (reference
``AllreduceNode`` / ``AllreduceDimensionNode``, SURVEY.md §3).

Butterfly composition (SURVEY.md §4.3): the dim-0 worker allreduces along this
node's row line; its per-round output feeds the dim-1 worker's data source,
which allreduces along the column line. To keep contributor counts EXACT under
thresholds, the dim-0 -> dim-1 chain payload is ``concat(sum, counts)``: dim 1
sums both halves, so the final count of an element is the total number of
original contributors that reached it through both stages.

Because line masters run independently, dim-1's ``StartAllreduce(r)`` can
arrive before dim-0 has produced round r's output; the node stashes the start
and replays it when the chain data is ready (the reference gets this ordering
from its dim-0-sink-feeds-dim-1-source actor wiring).
"""

from __future__ import annotations

import logging
from typing import Any

import numpy as np

from akka_allreduce_tpu.config import (
    MetaDataConfig,
    ThresholdConfig,
    WorkerConfig,
)
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.worker import AllreduceWorker, DataSink, DataSource
from akka_allreduce_tpu.protocol import (
    AllReduceInput,
    AllReduceInputRequest,
    AllReduceOutput,
    StartAllreduce,
)

log = logging.getLogger(__name__)


class AllreduceNode:
    """Hosts ``dims`` chained workers; routes their messages by worker id."""

    def __init__(
        self,
        node_id: int,
        dims: int,
        data_source: DataSource,
        data_sink: DataSink,
        metadata: MetaDataConfig,
        threshold: ThresholdConfig,
        worker_config: WorkerConfig = WorkerConfig(),
        stash_window: int = 8,
        flush_floors: dict[int, int] | None = None,
    ) -> None:
        if dims not in (1, 2):
            raise ValueError(f"dims must be 1 or 2, got {dims}")
        self.node_id = node_id
        self.dims = dims
        self.metadata = metadata
        self.stash_window = stash_window
        self._chain: dict[int, np.ndarray] = {}  # round -> concat(sum, counts)
        self._pending_starts: dict[int, StartAllreduce] = {}
        self.workers: dict[int, AllreduceWorker] = {}

        if dims == 1:
            w0 = AllreduceWorker(data_source, data_sink, worker_config)
            w0.configure(metadata, threshold)
            self.workers[0] = w0
        else:
            w0 = AllreduceWorker(data_source, self._chain_sink, worker_config)
            w0.configure(metadata, threshold)
            chain_meta = MetaDataConfig(
                data_size=2 * metadata.data_size,
                max_chunk_size=metadata.max_chunk_size,
            )
            w1 = AllreduceWorker(
                self._chain_source,
                self._final_sink_wrapper(data_sink),
                worker_config,
            )
            w1.configure(chain_meta, threshold)
            self.workers[0] = w0
            self.workers[1] = w1
        # the cross-epoch dedup floor survives node rebuilds: a rejoin (or
        # master failover) constructs a fresh AllreduceNode, but the rounds
        # the OLD instance's workers already flushed must stay flushed —
        # pass flush_floors() of the instance being replaced
        for dim, floor in (flush_floors or {}).items():
            if dim in self.workers:
                self.workers[dim].flushed_up_to = floor

    def flush_floors(self) -> dict[int, int]:
        """Per-dimension highest flushed round — hand to the replacement
        AllreduceNode so re-issued round ids dedup across rebuilds."""
        return {dim: w.flushed_up_to for dim, w in self.workers.items()}

    # -- chain plumbing (dims == 2) -----------------------------------------

    def _chain_sink(self, out: AllReduceOutput) -> None:
        payload = np.concatenate(
            [out.data, out.count.astype(np.float32)]
        )
        self._chain[out.iteration] = payload
        for stale in [r for r in self._chain if r < out.iteration - self.stash_window]:
            del self._chain[stale]
        for stale in [
            r for r in self._pending_starts if r < out.iteration - self.stash_window
        ]:
            del self._pending_starts[stale]  # dim-0 abandoned these rounds

    def _chain_source(self, req: AllReduceInputRequest) -> AllReduceInput:
        payload = self._chain.get(req.iteration)
        if payload is None:
            raise RuntimeError(
                f"node {self.node_id}: dim-1 round {req.iteration} started "
                "before dim-0 produced it (stash ordering bug)"
            )
        return AllReduceInput(payload)

    @staticmethod
    def _final_sink_wrapper(user_sink: DataSink):
        def sink(out: AllReduceOutput) -> None:
            n = out.data.shape[0] // 2
            # An element is valid only if BOTH its halves survived dim-1's
            # th_complete (sum at i, count at n+i land in different chunks, so
            # one can be dropped without the other); masking with dim-1's own
            # fill counts keeps sums and counts exactly paired.
            ok = (out.count[:n] > 0) & (out.count[n:] > 0)
            total = np.where(ok, out.data[:n], 0.0).astype(np.float32)
            counts = np.where(
                ok, np.rint(out.data[n:]).astype(np.int32), 0
            ).astype(np.int32)
            user_sink(AllReduceOutput(total, counts, out.iteration))

        return sink

    # -- message routing -----------------------------------------------------

    def dim_of(self, worker_id: int) -> int:
        return worker_id % self.dims

    def handle(self, worker_id: int, msg: Any) -> list[Envelope]:
        dim = self.dim_of(worker_id)
        worker = self.workers[dim]
        if (
            self.dims == 2
            and dim == 1
            and isinstance(msg, StartAllreduce)
            and msg.round_num not in self._chain
        ):
            self._pending_starts[msg.round_num] = msg
            return []
        out = worker.handle(msg)
        if self.dims == 2 and dim == 0:
            out.extend(self._replay_ready_starts())
        return out

    def _replay_ready_starts(self) -> list[Envelope]:
        out: list[Envelope] = []
        for r in sorted(self._pending_starts):
            if r in self._chain:
                msg = self._pending_starts.pop(r)
                out.extend(self.workers[1].handle(msg))
        return out
