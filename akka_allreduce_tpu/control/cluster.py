"""Cluster membership protocol — the reference's Akka Cluster seam.

The reference gets membership from Akka Cluster: nodes join via seed-node
addresses, gossip carries MemberUp/Unreachable, and the grid master reacts to
those events (SURVEY.md §3 "Membership", §4.1 bootstrap, §4.5 recovery). This
module is the same seam as explicit messages: a node dials the master (the
single seed), is welcomed with its node id + the cluster config, then
heartbeats; the master's phi-accrual detector (control/failure.py) turns
heartbeat silence into ``member_unreachable`` and the grid re-organizes.

These are control-plane-only messages (no float payloads) carried by the same
wire codec and TCP transport as the round protocol.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """A reachable transport address (host, port) — the actor-system address."""

    host: str
    port: int

    def __str__(self) -> str:  # "host:port", the CLI's --seed format
        return f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, text: str) -> "Endpoint":
        host, _, port = text.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"expected host:port, got {text!r}")
        return cls(host, int(port))


@dataclasses.dataclass(frozen=True)
class JoinCluster:
    """Node -> master (seed): request membership.

    ``host``/``port`` is the joiner's own server endpoint — peers will dial it
    to deliver ScatterBlock/ReduceBlock. ``preferred_node_id`` lets a restarted
    node ask for its old identity back (-1 = master assigns).

    ``incarnation`` identifies one NodeProcess lifetime. Joins are retried
    until Welcomed (delivery is at-most-once), so the master uses it to tell
    a retry (same incarnation: just re-send Welcome) from a process restart
    on the same endpoint (new incarnation: the workers are fresh — force the
    Prepare handshake).
    """

    host: str
    port: int
    preferred_node_id: int = -1
    incarnation: int = 0


@dataclasses.dataclass(frozen=True)
class Welcome:
    """Master -> node: membership granted.

    Carries the assigned node id and the full cluster config as JSON
    (``AllreduceConfig.to_json``) so every node runs identical geometry and
    thresholds — the reference distributes the same knobs via
    ``application.conf`` on each JVM.

    ``epoch`` is the welcoming master's leadership epoch: the node records
    it as its fencing watermark (messages from older epochs are dropped —
    RESILIENCE.md "Tier 4"). ``standbys`` is the warm-standby endpoint list
    the node walks when the leader stops answering.
    """

    node_id: int
    config_json: str
    epoch: int = 0
    standbys: tuple[tuple[str, int], ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "standbys", tuple((h, int(p)) for h, p in self.standbys)
        )


@dataclasses.dataclass(frozen=True)
class Heartbeat:
    """Node -> master: liveness signal feeding the phi-accrual detector.

    Carries the sender's incarnation so a zombie (a partitioned process
    whose node id was legitimately reclaimed by a newer joiner) cannot
    alias the current holder's liveness with its stale heartbeats, and the
    sender's own server endpoint (``host``/``port``) so a master that does
    NOT know the sender — a replacement master that restarted on the seed
    endpoint with an empty address book — can reply ``Rejoin`` instead of
    dropping the heartbeat and leaving the node wedged forever.
    """

    node_id: int
    incarnation: int = 0
    host: str = ""
    port: int = 0


@dataclasses.dataclass(frozen=True)
class Rejoin:
    """Master -> node: your membership is not recognized here — run the join
    handshake again (new incarnation). Sent by a replacement master that
    receives heartbeats from nodes of its predecessor."""

    reason: str = "unknown-node"
    epoch: int = -1  # sender's leadership epoch (-1 = unfenced)


@dataclasses.dataclass(frozen=True)
class LeaveCluster:
    """Node -> master: graceful departure (Akka Cluster leave)."""

    node_id: int


@dataclasses.dataclass(frozen=True)
class AddressBook:
    """Master -> all nodes: node id -> endpoint map after every membership
    change, so workers can dial their current peers.

    Carries the sender's leadership ``epoch`` (fencing: a zombie master's
    stale book must not overwrite the new leader's) and the current
    ``standbys`` list, so nodes that joined before a standby registered
    still learn where to walk on leader loss.
    """

    entries: tuple[tuple[int, str, int], ...]  # (node_id, host, port)
    epoch: int = -1
    standbys: tuple[tuple[str, int], ...] = ()

    def node_ids(self) -> tuple[int, ...]:
        """The live membership this book describes, sorted — what the
        node-side elastic cycle re-meshes to (RESILIENCE.md "Tier 7")."""
        return tuple(sorted(nid for nid, _h, _p in self.entries))

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "entries", tuple(tuple(e) for e in self.entries)
        )
        object.__setattr__(
            self, "standbys", tuple((h, int(p)) for h, p in self.standbys)
        )

    def endpoint_of(self, node_id: int) -> Endpoint | None:
        for nid, host, port in self.entries:
            if nid == node_id:
                return Endpoint(host, port)
        return None


@dataclasses.dataclass(frozen=True)
class Shutdown:
    """Master -> all nodes: the run is over (max_rounds reached); exit.

    Also master -> master: a promoted standby answers a fenced zombie
    leader's digests with ``Shutdown("superseded-epoch")`` so the zombie
    stands down instead of scheduling into the void forever.
    """

    reason: str = "done"
    epoch: int = -1  # sender's leadership epoch (-1 = unfenced)


@dataclasses.dataclass(frozen=True)
class StandbyRegister:
    """Standby master -> leader: replicate your control-plane state to me.

    ``host``/``port`` is the standby's own server endpoint — the leader
    records it, distributes it to nodes (``Welcome``/``AddressBook``
    ``standbys``), and starts piggybacking :class:`StateDigest` after every
    state-changing event. Registration is idempotent and periodically
    re-sent, so a restarted leader re-learns its standbys.
    """

    host: str
    port: int


@dataclasses.dataclass(frozen=True)
class StateDigest:
    """Leader -> standby: the compact replicated control-plane state.

    Everything a warm standby needs to take over as master: membership
    (address book + incarnations + unreachable set), the round counters
    (next round / completed budget / config id), the peer-checkpoint
    holder registry, and the full cluster config (so chaos + retry knobs
    survive failover). Doubles as the leader's lease heartbeat: the
    standby's phi detector expires on digest silence and the standby takes
    over by bumping ``epoch``. ``host``/``port`` is the leader's endpoint,
    so a promoted standby can fence a still-digesting zombie leader with
    ``Shutdown("superseded-epoch")``.
    """

    epoch: int
    seq: int
    host: str
    port: int
    state_json: str
