"""Cluster membership protocol — the reference's Akka Cluster seam.

The reference gets membership from Akka Cluster: nodes join via seed-node
addresses, gossip carries MemberUp/Unreachable, and the grid master reacts to
those events (SURVEY.md §3 "Membership", §4.1 bootstrap, §4.5 recovery). This
module is the same seam as explicit messages: a node dials the master (the
single seed), is welcomed with its node id + the cluster config, then
heartbeats; the master's phi-accrual detector (control/failure.py) turns
heartbeat silence into ``member_unreachable`` and the grid re-organizes.

These are control-plane-only messages (no float payloads) carried by the same
wire codec and TCP transport as the round protocol.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """A reachable transport address (host, port) — the actor-system address."""

    host: str
    port: int

    def __str__(self) -> str:  # "host:port", the CLI's --seed format
        return f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, text: str) -> "Endpoint":
        host, _, port = text.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"expected host:port, got {text!r}")
        return cls(host, int(port))


@dataclasses.dataclass(frozen=True)
class JoinCluster:
    """Node -> master (seed): request membership.

    ``host``/``port`` is the joiner's own server endpoint — peers will dial it
    to deliver ScatterBlock/ReduceBlock. ``preferred_node_id`` lets a restarted
    node ask for its old identity back (-1 = master assigns).

    ``incarnation`` identifies one NodeProcess lifetime. Joins are retried
    until Welcomed (delivery is at-most-once), so the master uses it to tell
    a retry (same incarnation: just re-send Welcome) from a process restart
    on the same endpoint (new incarnation: the workers are fresh — force the
    Prepare handshake).
    """

    host: str
    port: int
    preferred_node_id: int = -1
    incarnation: int = 0


@dataclasses.dataclass(frozen=True)
class Welcome:
    """Master -> node: membership granted.

    Carries the assigned node id and the full cluster config as JSON
    (``AllreduceConfig.to_json``) so every node runs identical geometry and
    thresholds — the reference distributes the same knobs via
    ``application.conf`` on each JVM.
    """

    node_id: int
    config_json: str


@dataclasses.dataclass(frozen=True)
class Heartbeat:
    """Node -> master: liveness signal feeding the phi-accrual detector.

    Carries the sender's incarnation so a zombie (a partitioned process
    whose node id was legitimately reclaimed by a newer joiner) cannot
    alias the current holder's liveness with its stale heartbeats, and the
    sender's own server endpoint (``host``/``port``) so a master that does
    NOT know the sender — a replacement master that restarted on the seed
    endpoint with an empty address book — can reply ``Rejoin`` instead of
    dropping the heartbeat and leaving the node wedged forever.
    """

    node_id: int
    incarnation: int = 0
    host: str = ""
    port: int = 0


@dataclasses.dataclass(frozen=True)
class Rejoin:
    """Master -> node: your membership is not recognized here — run the join
    handshake again (new incarnation). Sent by a replacement master that
    receives heartbeats from nodes of its predecessor."""

    reason: str = "unknown-node"


@dataclasses.dataclass(frozen=True)
class LeaveCluster:
    """Node -> master: graceful departure (Akka Cluster leave)."""

    node_id: int


@dataclasses.dataclass(frozen=True)
class AddressBook:
    """Master -> all nodes: node id -> endpoint map after every membership
    change, so workers can dial their current peers."""

    entries: tuple[tuple[int, str, int], ...]  # (node_id, host, port)

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "entries", tuple(tuple(e) for e in self.entries)
        )

    def endpoint_of(self, node_id: int) -> Endpoint | None:
        for nid, host, port in self.entries:
            if nid == node_id:
                return Endpoint(host, port)
        return None


@dataclasses.dataclass(frozen=True)
class Shutdown:
    """Master -> all nodes: the run is over (max_rounds reached); exit."""

    reason: str = "done"
