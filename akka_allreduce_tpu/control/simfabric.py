"""Deterministic pod-scale membership simulator (RESILIENCE.md "Scale").

``Fabric`` drives N :class:`~akka_allreduce_tpu.control.gossip.GossipState`
machines over a synchronous in-process message fabric on a PURELY LOGICAL
clock, with an optional per-role :class:`ChaosInjector` compiled from the
real spec grammar — each role gets its own injector, exactly like each OS
process does over TCP. Same seed + same schedule = byte-identical chaos
logs and identical membership judgements, at 256–1024 nodes in seconds:
this is how the ladder's guarantees (zero false expulsions under a
partition, bounded confirmed-dead detection, leader failover + re-mesh)
are ASSERTED at production node counts that no CI box can spawn as real
processes (tests/test_gossip_scale.py; the ``chaos-scale`` drill records
the sim rate next to its real-process phases).

Grew out of tests/test_gossip.py's 64-node harness; promoted here so the
drill CLI and the scale suite share one definition. The only mechanics a
fabric applies are loss (drop/fail) — delay/reorder belong to the async
transports; a synchronous fabric models the CONTROL decisions, which are
clock-free by construction (every GossipState method takes ``now``).
"""

from __future__ import annotations

import time

from akka_allreduce_tpu.config import GossipConfig
from akka_allreduce_tpu.control.chaos import ChaosInjector
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.gossip import (
    DEAD,
    MASTER_ID,
    GossipState,
)

__all__ = ["Fabric", "sim_rate"]


class Fabric:
    """Synchronous message fabric over N member state machines."""

    def __init__(
        self,
        n_nodes: int,
        *,
        config: GossipConfig | None = None,
        chaos_spec: str = "",
        chaos_seed: int = 99,
    ) -> None:
        self.now = 0.0
        self.n_nodes = n_nodes
        cfg = config or GossipConfig(
            enabled=True,
            probe_interval_s=0.5,
            probe_timeout_s=0.15,
            indirect=3,
            suspicion_periods=4,
            seed=7,
        )
        self.config = cfg
        self.states: dict[int, GossipState] = {
            MASTER_ID: GossipState(MASTER_ID, 1, cfg)
        }
        for i in range(n_nodes):
            # distinct incarnations, like distinct processes
            self.states[i] = GossipState(i, 1000 + i, cfg)
        roster = set(self.states)
        for st in self.states.values():
            st.set_members(roster)  # set_members drops the self id
        self.dead: set[int] = set()  # roles whose process is gone
        self.ticks = 0  # node-ticks executed (the sim-rate numerator)
        self.injectors: dict[int, ChaosInjector] = {}
        if chaos_spec:
            for role in self.states:
                self.injectors[role] = ChaosInjector(
                    chaos_seed, chaos_spec, role=role,
                    clock=lambda: self.now, t0=0.0,
                )

    def deliver(self, sender: int, envelopes: list[Envelope]) -> None:
        for env in envelopes:
            inj = self.injectors.get(sender)
            if inj is not None:
                act = inj.plan_send(env)
                if act is not None and (act.drop or act.fail):
                    continue  # the fabric's only mechanics: loss
            target = int(env.dest.rpartition(":")[2])
            st = self.states.get(target)
            if st is None or target in self.dead:
                continue
            self.deliver(target, st.handle(env.msg, self.now))

    def step(self, dt: float = 0.1) -> None:
        self.now += dt
        for role in sorted(self.states):
            if role in self.dead:
                continue
            self.ticks += 1
            self.deliver(role, self.states[role].tick(self.now))

    def run(self, seconds: float, dt: float = 0.1) -> None:
        for _ in range(int(seconds / dt)):
            self.step(dt)

    # -- failure scripting -----------------------------------------------------

    def kill(self, role: int) -> None:
        """The role's process is gone: it stops ticking and every frame
        addressed to it vanishes (the fabric's SIGKILL)."""
        self.dead.add(role)

    def promote_master(self, incarnation: int) -> GossipState:
        """A standby takes over the MASTER_ID ring identity under a
        bumped incarnation (PR-7's takeover joins the ring exactly like
        this: fresh epoch = fresh incarnation, same member id). The dead
        leader's state object is replaced wholesale and the role
        resumes ticking."""
        st = GossipState(MASTER_ID, incarnation, self.config)
        st.set_members(set(self.states))
        self.states[MASTER_ID] = st
        self.dead.discard(MASTER_ID)
        return st

    def run_until(self, pred, timeout_s: float, dt: float = 0.1) -> float | None:
        """Step until ``pred(self)`` holds; logical seconds elapsed, or
        None when the timeout ran out first."""
        t0 = self.now
        while self.now - t0 < timeout_s:
            self.step(dt)
            if pred(self):
                return self.now - t0
        return None

    # -- views -----------------------------------------------------------------

    @property
    def master(self) -> GossipState:
        return self.states[MASTER_ID]

    def dead_count_at_master(self) -> int:
        return sum(
            1
            for nid in range(self.n_nodes)
            if self.master.status_of(nid) == DEAD
        )

    def judgement(self) -> tuple:
        """A compact, hashable view of every state machine's verdicts +
        counters — what the same-seed determinism pins compare."""
        return tuple(
            (
                role,
                st.incarnation,
                st.probes_sent,
                st.indirect_sent,
                st.suspicions,
                st.confirms,
                st.refutations,
                st.digest_truncations,
                tuple(
                    (n, r.incarnation, r.status)
                    for n, r in sorted(st.members.items())
                ),
            )
            for role, st in sorted(self.states.items())
        )


def sim_rate(n_nodes: int = 256, seconds: float = 10.0) -> dict:
    """Measure the fabric's throughput on THIS box: node-ticks/second
    over a quiet ``n_nodes`` sim — the number the chaos-scale drill
    records in its summary (a regression here is the O(N²) class the
    1024-node arms exist to keep out)."""
    fab = Fabric(n_nodes)
    t0 = time.perf_counter()
    fab.run(seconds)
    wall = time.perf_counter() - t0
    return {
        "nodes": n_nodes,
        "sim_seconds": seconds,
        "wall_seconds": round(wall, 3),
        "node_ticks": fab.ticks,
        "node_ticks_per_s": round(fab.ticks / max(wall, 1e-9)),
    }
