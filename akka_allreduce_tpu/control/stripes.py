"""Congestion-aware stripe scheduling for the multi-stream data plane.

PR 9 stripes payload frames across an endpoint's payload streams by chunk
id — a fixed assignment, so one persistently slow stream (a congested
path, a flaky NIC queue, a chaos ``delay``) gates every round that owns a
chunk on it. This module closes that loop the way the adapt ladder closed
the threshold loop: the per-stream byte gauges the sender threads already
maintain feed a :class:`StripeScheduler` whose DEFICIT-WEIGHTED assignment
(stride scheduling: pick the stream with the least weighted virtual time)
shifts work away from a stream that demonstrably is not draining, with
hysteresis on both edges so a noisy window cannot flap the weights.

Decision rule, evaluated once per ``window_s`` of the caller's clock
(every entry point takes ``now`` — the scheduler owns no clock, so tests
and the bench replay it deterministically under a fake one, exactly the
``GossipState`` discipline):

- a stream's window **drain ratio** is ``sent / (backlog_at_window_start +
  assigned_this_window)`` — self-normalizing, so a stream that was
  assigned little is judged on what it WAS given, not against busier
  peers;
- a ratio below ``SLOW_RATIO`` with at least ``MIN_EVIDENCE_BYTES`` of
  work outstanding counts one *slow* window; ``HYSTERESIS`` consecutive
  slow windows halve the stream's weight (``stripe.sheds``), floored at
  ``MIN_WEIGHT`` so evidence keeps flowing to a shed stream;
- a ratio at/above ``RESTORE_RATIO`` counts one *fast* window;
  ``HYSTERESIS`` consecutive fast windows double a shed stream's weight
  back toward parity (``stripe.restores``) — distinct bars, like the
  adapt ladder's degrade/restore thresholds.

The scheduler is shared between the event loop (``pick`` at enqueue) and
the per-stream sender threads (``note_sent`` after each batch), so every
entry point locks; the hot path is a handful of float ops per payload
frame.
"""

from __future__ import annotations

import threading

from akka_allreduce_tpu.obs import metrics as _metrics

__all__ = ["StripeScheduler"]

# weight-shift accounting (OBSERVABILITY.md): how often congestion evidence
# actually moved assignment weight, process-wide
_SHEDS = _metrics.counter("stripe.sheds")
_RESTORES = _metrics.counter("stripe.restores")


class StripeScheduler:
    """Deficit-weighted stripe assignment over ``n`` payload streams."""

    #: evaluation window of the caller's clock
    WINDOW_S = 0.25
    #: drain ratio below this (with evidence) = one slow window
    SLOW_RATIO = 0.5
    #: drain ratio at/above this = one fast window (the restore bar —
    #: deliberately far from SLOW_RATIO: the hysteresis gap)
    RESTORE_RATIO = 0.9
    #: consecutive slow/fast windows before a weight shift
    HYSTERESIS = 2
    #: weight multiplier per shed (and divisor per restore)
    SHED_FACTOR = 0.5
    #: floor: a shed stream keeps receiving SOME work, so recovery
    #: evidence can accumulate (a zero-weight stream could never heal)
    MIN_WEIGHT = 0.125
    #: ignore windows where a stream had less than this much work pending
    #: (an idle stream is not a slow stream)
    MIN_EVIDENCE_BYTES = 64 << 10

    def __init__(self, n: int, *, window_s: float | None = None) -> None:
        if n < 1:
            raise ValueError(f"need at least one stripe, got {n}")
        self.n = n
        self.window_s = float(window_s) if window_s else self.WINDOW_S
        self.weights = [1.0] * n
        self.sheds = 0
        self.restores = 0
        self._lock = threading.Lock()
        self._vtime = [0.0] * n  # weighted bytes assigned this window
        self._assigned = [0] * n  # bytes assigned this window
        self._sent = [0] * n  # bytes the sender threads moved this window
        self._outstanding = [0] * n  # assigned-but-unsent, across windows
        self._backlog0 = [0] * n  # outstanding at window start
        self._slow = [0] * n  # consecutive slow windows
        self._fast = [0] * n  # consecutive fast windows
        self._window_start: float | None = None

    # -- assignment ----------------------------------------------------------

    def pick(self, nbytes: int, now: float) -> int:
        """The stripe (0-based) to carry ``nbytes`` — least weighted
        virtual time wins (ties to the lowest index: deterministic)."""
        with self._lock:
            self._roll(now)
            best = min(range(self.n), key=lambda i: (self._vtime[i], i))
            self._vtime[best] += nbytes / self.weights[best]
            self._assigned[best] += nbytes
            self._outstanding[best] += nbytes
            return best

    def note_sent(self, idx: int, nbytes: int, now: float) -> None:
        """Sender-thread feedback: ``nbytes`` of stripe ``idx``'s queue
        reached the socket."""
        with self._lock:
            self._sent[idx] += nbytes
            self._outstanding[idx] = max(0, self._outstanding[idx] - nbytes)
            self._roll(now)

    def note_dropped(self, idx: int, nbytes: int, now: float) -> None:
        """``nbytes`` assigned to stripe ``idx`` were DROPPED unsent
        (dead-letter, backpressure withdrawal). The phantom backlog must
        leave the books: it will never produce a ``note_sent``, and
        uncleared it would read as permanent congestion — a stream that
        dead-lettered one burst could otherwise never restore its
        weight."""
        with self._lock:
            self._outstanding[idx] = max(0, self._outstanding[idx] - nbytes)
            self._roll(now)

    def share(self, idx: int) -> float:
        """Stripe ``idx``'s current fraction of the assignment weight."""
        with self._lock:
            return self.weights[idx] / sum(self.weights)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "weights": list(self.weights),
                "sheds": self.sheds,
                "restores": self.restores,
                "outstanding": list(self._outstanding),
            }

    # -- the window decision -------------------------------------------------

    def _roll(self, now: float) -> None:
        if self._window_start is None:
            self._window_start = now
            return
        if now - self._window_start < self.window_s:
            return
        for i in range(self.n):
            pending = self._backlog0[i] + self._assigned[i]
            if pending < self.MIN_EVIDENCE_BYTES:
                continue  # thin evidence: neither advances nor resets
            ratio = self._sent[i] / pending
            if ratio < self.SLOW_RATIO:
                self._fast[i] = 0
                self._slow[i] += 1
                if self._slow[i] >= self.HYSTERESIS:
                    self._slow[i] = 0
                    shed = max(
                        self.MIN_WEIGHT, self.weights[i] * self.SHED_FACTOR
                    )
                    if shed < self.weights[i]:
                        self.weights[i] = shed
                        self.sheds += 1
                        _SHEDS.inc()
            elif ratio >= self.RESTORE_RATIO:
                self._slow[i] = 0
                if self.weights[i] < 1.0:
                    self._fast[i] += 1
                    if self._fast[i] >= self.HYSTERESIS:
                        self._fast[i] = 0
                        self.weights[i] = min(
                            1.0, self.weights[i] / self.SHED_FACTOR
                        )
                        self.restores += 1
                        _RESTORES.inc()
            else:
                self._slow[i] = 0
                self._fast[i] = 0
        self._window_start = now
        self._assigned = [0] * self.n
        self._sent = [0] * self.n
        self._vtime = [0.0] * self.n
        self._backlog0 = list(self._outstanding)
