"""SWIM-style decentralized membership (RESILIENCE.md "Tier 6").

The hub design every PR up to 9 lived with — all N nodes heartbeating into
ONE master's phi detector (control/failure.py) — makes the leader both a
throughput cap and a single *vantage point*: one congested master-side
link reads as N dead nodes, and detection work scales O(N) on one process.
This module replaces it with the SWIM protocol family the reference's Akka
Cluster gossip belongs to (SURVEY.md §3 "Membership"):

- **probe**: every process pings ONE member per probe period, chosen by a
  shuffled round-robin cycle (every member is probed within one cycle —
  SWIM's time-bounded-detection property, not coupon-collector luck);
- **indirect probe**: a missed direct ack escalates to K ``PingReq``
  relays through other members before anything is suspected — aliveness
  is judged from K+1 vantage points, so one bad link cannot expel a
  healthy node;
- **suspicion**: a member that failed the direct AND indirect round is
  SUSPECTED (gossiped, not acted on); unrefuted suspicion times out into
  CONFIRMED DEAD — the only state the master's membership machinery acts
  on (expulsion, re-mesh: exactly the old ``member_unreachable`` path);
- **refutation**: a member that hears itself suspected bumps its own
  incarnation (the same ordering token the PR-5/6 rejoin path mints per
  process lifetime) and gossips itself ALIVE — higher incarnation wins,
  so a slandered-but-alive node un-suspects itself cluster-wide;
- **dissemination**: membership updates piggyback on probe/ack traffic as
  bounded digests (``digest_max`` entries, freshest-first by remaining
  spread budget) — no broadcast storms, no hub.

``GossipState`` is a PURE state machine: every method takes ``now``
explicitly, every random decision draws from a stream seeded by
``(seed, node_id)``, and nothing reads a wall clock — the 64..256-node
LocalRouter simulations in tests/test_gossip.py replay byte-identically.
``GossipAgent`` binds one state to a live transport (the async side:
probe loop task, handler registration).

Wire: ``Ping``/``PingReq``/``Ack`` are ordinary control messages (tags
24-26, control/wire.py) on the existing codec — trailing-bytes tolerant,
round-tripped in tests/test_wire_roundtrip.py, WIRE001-exhaustive.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
from typing import Any, Callable

from akka_allreduce_tpu.config import GossipConfig
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics

__all__ = [
    "ALIVE",
    "SUSPECT",
    "DEAD",
    "MASTER_ID",
    "Ping",
    "PingReq",
    "Ack",
    "GossipEvent",
    "GossipState",
    "GossipAgent",
    "gossip_addr",
]

# member status bytes — the wire form of a digest entry's third field
ALIVE = 0
SUSPECT = 1
DEAD = 2

#: the master's member id in the gossip ring (== chaos.MASTER_ROLE, so
#: partitions cut gossip traffic by the same role ids as round traffic)
MASTER_ID = -1

_STATUS_NAMES = {ALIVE: "alive", SUSPECT: "suspect", DEAD: "dead"}

# gossip.* observability (OBSERVABILITY.md): probe traffic volume and the
# suspicion state machine's edges — what a membership post-mortem reads
# next to the chaos event log
_PROBES = _metrics.counter("gossip.probes")
_INDIRECT = _metrics.counter("gossip.indirect_probes")
_ACKS_RELAYED = _metrics.counter("gossip.acks_relayed")
_SUSPICIONS = _metrics.counter("gossip.suspicions")
_CONFIRMS = _metrics.counter("gossip.confirmed_dead")
_REFUTATIONS = _metrics.counter("gossip.refutations")
_DIGEST_ENTRIES = _metrics.counter("gossip.digest_entries")
# digest-budget pressure (RESILIENCE.md "Scale"): how often a digest had
# MORE spreadable news than digest_max slots — the ~3·log2(n) spread
# bound is an assumption until this stays ~0; at n=1024 under churn it
# is the first thing to watch
_DIGEST_TRUNCATIONS = _metrics.counter("gossip.digest_truncations")


def gossip_addr(node_id: int) -> str:
    """Transport address of a process's gossip endpoint (the master is
    ``gossip:-1`` — chaos MASTER_ROLE, same id space as partitions)."""
    return f"gossip:{node_id}"


# one digest entry: (node_id, incarnation, status byte)
DigestEntry = tuple[int, int, int]


@dataclasses.dataclass(frozen=True)
class Ping:
    """Direct probe (also the relay leg of an indirect probe).

    ``host``/``port`` is the sender's server endpoint, carried for the
    same reason ``Heartbeat`` carries it: a replacement master that does
    not know the pinger must be able to reply ``Rejoin`` instead of
    dropping the frame and leaving the node wedged.
    """

    sender: int
    incarnation: int
    seq: int
    host: str = ""
    port: int = 0
    digest: tuple[DigestEntry, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "digest", tuple(tuple(e) for e in self.digest)
        )


@dataclasses.dataclass(frozen=True)
class PingReq:
    """Indirect-probe request: ``sender`` could not get a direct ack from
    ``target`` — please ping it and relay the ack back (``seq`` is the
    ORIGIN's probe sequence; the relayed Ack carries it back)."""

    sender: int
    target: int
    seq: int
    digest: tuple[DigestEntry, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "digest", tuple(tuple(e) for e in self.digest)
        )


@dataclasses.dataclass(frozen=True)
class Ack:
    """Probe acknowledgement. ``sender`` is the node whose aliveness this
    ack vouches for — for a direct ack that is the responder itself; for
    a relayed ack the relay re-sends the target's identity under the
    origin's ``seq``, so the origin matches it to its pending probe."""

    sender: int
    incarnation: int
    seq: int
    digest: tuple[DigestEntry, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "digest", tuple(tuple(e) for e in self.digest)
        )


@dataclasses.dataclass(frozen=True)
class GossipEvent:
    """Edge-triggered membership change for subscribers (the master's
    expulsion path, a node's master-loss trigger)."""

    node_id: int
    status: int  # ALIVE / SUSPECT / DEAD
    incarnation: int
    at: float  # caller's clock (logical in sims)


@dataclasses.dataclass
class _Member:
    incarnation: int = 0
    status: int = ALIVE
    spread: int = 0  # piggyback transmissions already spent on this state
    suspect_at: float | None = None  # local clock when suspicion started


@dataclasses.dataclass
class _Probe:
    target: int
    sent_at: float
    direct_deadline: float
    deadline: float
    indirect_sent: bool = False


def _derive_seed(seed: int, node_id: int) -> int:
    digest = hashlib.blake2b(
        f"gossip:{seed}:{node_id}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "little")


class GossipState:
    """One process's SWIM membership state machine (clock-free, seeded).

    The member set is AUTHORITATIVELY the master's address book (joins and
    expulsions stay master-decided, exactly as before): callers feed it
    via :meth:`set_members` / :meth:`reset_member` / :meth:`remove_member`.
    Gossip owns only the alive/suspect/dead judgement within that set —
    what used to be the phi hub's job.
    """

    #: piggyback budget per state change, scaled by ln(membership): SWIM's
    #: O(log n) retransmission bound for whole-cluster dissemination
    SPREAD_MULT = 3

    def __init__(
        self,
        node_id: int,
        incarnation: int,
        config: GossipConfig,
        *,
        host: str = "",
        port: int = 0,
        seed: int | None = None,
    ) -> None:
        self.node_id = node_id
        self.incarnation = incarnation
        self.config = config
        self.host = host
        self.port = port
        self._rng = random.Random(
            _derive_seed(config.seed if seed is None else seed, node_id)
        )
        self.members: dict[int, _Member] = {}
        self._cycle: list[int] = []  # shuffled probe order (round-robin)
        # incremental indexes over `members` — what keeps tick()/digest()
        # O(changes) instead of O(membership) so the 256..1024-node sims
        # (and a real pod's per-message hot path) stay allocation-light:
        # ids currently SUSPECT (the only records the confirm scan needs)
        # and ids with remaining piggyback budget (the only digest
        # candidates). Both are maintained at every status/spread edge
        # and lazily validated where staleness is harmless.
        self._suspects: set[int] = set()
        self._fresh: set[int] = set()
        self._seq = 0
        self._pending: dict[int, _Probe] = {}  # my probe seq -> probe
        # relay bookkeeping: my relay-ping seq -> (origin id, origin seq,
        # expiry) — expired in tick() like _pending, or a target that
        # never acks (the PingReq case par excellence) would leak one
        # entry per relayed probe forever
        self._relays: dict[int, tuple[int, int, float]] = {}
        self._next_probe_at = 0.0
        # how many more digests must lead with our own ALIVE entry (a
        # refutation in flight); self is otherwise not in `members`
        self._refute_spread = 0
        self.events: list[GossipEvent] = []
        # per-instance counters (the process-global gossip.* metrics
        # aggregate across instances; sims pin THESE)
        self.probes_sent = 0
        self.indirect_sent = 0
        self.suspicions = 0
        self.confirms = 0
        self.refutations = 0
        self.digest_truncations = 0

    # -- membership roster (master-book-driven) --------------------------------

    def set_members(self, node_ids) -> None:
        """Adopt the roster: new ids get fresh ALIVE records, ids gone
        from the roster are dropped (expelled/left — the master decided).
        Existing records keep their state (a roster refresh must not
        amnesty a suspect)."""
        ids = {int(n) for n in node_ids if int(n) != self.node_id}
        fresh_ids = sorted(ids - set(self.members))
        for nid in fresh_ids:
            self.members[nid] = _Member()
        if fresh_ids:
            # a roster addition is NOT gossip news: the master already
            # broadcast the book to everyone (membership is hub-
            # authoritative), and an ALIVE-at-inc-0 entry outranks
            # nothing anywhere. Starting these settled is also what
            # keeps a 1024-member boot from spending O(N) digest sorts
            # per message on un-news until every budget drains —
            # liveness NEWS (suspicion, refutation, readmission via
            # reset_member's incarnation bump) still spreads from a
            # fresh budget.
            limit = self._spread_limit()
            for nid in fresh_ids:
                self.members[nid].spread = limit
        gone = sorted(set(self.members) - ids)
        for nid in gone:
            self.members.pop(nid, None)
            self._suspects.discard(nid)
            self._fresh.discard(nid)
        if gone:
            self._cycle = [n for n in self._cycle if n not in gone]

    def reset_member(self, node_id: int, incarnation: int = 0) -> None:
        """A (re)admitted member: fresh ALIVE record at the given
        incarnation — its predecessor's DEAD record must not shadow the
        new process (the master vouched for the rejoin)."""
        if node_id == self.node_id:
            return
        self.members[node_id] = _Member(incarnation=incarnation)
        self._suspects.discard(node_id)
        self._fresh.add(node_id)

    def remove_member(self, node_id: int) -> None:
        self.members.pop(node_id, None)
        self._suspects.discard(node_id)
        self._fresh.discard(node_id)
        self._cycle = [n for n in self._cycle if n != node_id]

    # -- views -----------------------------------------------------------------

    def status_of(self, node_id: int) -> int | None:
        rec = self.members.get(node_id)
        return None if rec is None else rec.status

    def alive_or_suspect(self) -> list[int]:
        """Members gossip has NOT confirmed dead — the set the master's
        monitor mirror keeps fresh (a suspect is innocent until the
        suspicion times out; phi must not front-run the confirm)."""
        return sorted(
            n for n, r in self.members.items() if r.status != DEAD
        )

    def poll_events(self) -> list[GossipEvent]:
        """Drain the edge-triggered event queue (confirmed deaths and
        post-suspicion revivals) — the subscriber interface."""
        out, self.events = self.events, []
        return out

    def digest_state(self) -> dict[str, list[int]]:
        """Replication form for the master-HA StateDigest: a promoted
        standby inherits WHO was suspect/dead mid-incident instead of
        re-learning it from scratch."""
        return {
            str(n): [r.incarnation, r.status]
            for n, r in sorted(self.members.items())
        }

    def restore_state(self, state: dict | None) -> None:
        """Adopt a replicated :meth:`digest_state` (standby takeover)."""
        if not state:
            return
        for key, (inc, status) in state.items():
            nid = int(key)
            if nid == self.node_id:
                continue
            rec = self.members.setdefault(nid, _Member())
            rec.incarnation = int(inc)
            rec.status = int(status)
            # inherited suspicions restart their timer at takeover: the
            # digest has no clock, and a fresh window errs alive-ward
            rec.suspect_at = None
            (self._suspects.add if rec.status == SUSPECT
             else self._suspects.discard)(nid)
            # the inherited judgement is NEWS from this identity: the
            # takeover path runs set_members() first, which marks every
            # roster record settled (the boot rule) — without a fresh
            # budget here the promoted master would never gossip WHO was
            # suspect/dead mid-incident, and members that missed the
            # rumor would re-learn it only by their own probe timeouts
            rec.spread = 0
            self._fresh.add(nid)

    # -- the probe loop --------------------------------------------------------

    def tick(self, now: float) -> list[Envelope]:
        """One scheduler pass: expire pending probes (escalate to
        ping-reqs, then suspicion), confirm timed-out suspicions, and
        launch the period's direct probe. Returns the envelopes to send."""
        cfg = self.config
        out: list[Envelope] = []
        for seq in [
            s for s, (_, _, exp) in self._relays.items() if now >= exp
        ]:
            del self._relays[seq]
        for seq in sorted(self._pending):
            probe = self._pending[seq]
            rec = self.members.get(probe.target)
            if rec is None or rec.status == DEAD:
                self._pending.pop(seq, None)
                continue
            if not probe.indirect_sent and now >= probe.direct_deadline:
                probe.indirect_sent = True
                # a LATE escalation (this process stalled past the
                # period — the loaded-host case) still gives the relays
                # their FULL window before suspicion: the rule is
                # "direct AND indirect both came up empty", never "we
                # were too busy to ask"
                probe.deadline = max(
                    probe.deadline,
                    now + (probe.deadline - probe.direct_deadline),
                )
                out.extend(self._ping_reqs(probe, seq))
            if now >= probe.deadline:
                self._pending.pop(seq, None)
                self._suspect(probe.target, now)
        if self._suspects:
            # only the SUSPECT records can confirm — scanning the whole
            # membership here was the sims' O(N) * N-nodes per tick wall
            for nid in sorted(self._suspects):
                rec = self.members.get(nid)
                if (
                    rec is not None
                    and rec.status == SUSPECT
                    and rec.suspect_at is not None
                    and now - rec.suspect_at
                    >= cfg.suspicion_periods * cfg.probe_interval_s
                ):
                    self._confirm_dead(nid, rec, now)
        if now >= self._next_probe_at:
            self._next_probe_at = now + cfg.probe_interval_s
            target = self._next_target()
            if target is not None:
                self._seq += 1
                self._pending[self._seq] = _Probe(
                    target,
                    now,
                    now + cfg.probe_timeout_s,
                    now + cfg.probe_interval_s,
                )
                self.probes_sent += 1
                _PROBES.inc()
                out.append(
                    Envelope(gossip_addr(target), self._ping(self._seq))
                )
        return out

    def _next_target(self) -> int | None:
        """Shuffled round-robin over the probe-able membership (SWIM §4.3:
        randomized cycling bounds worst-case time-to-probe by one cycle,
        where pure random sampling only bounds the expectation).

        Candidacy is checked per POP (an O(1) status read), and the full
        membership is only walked when the cycle runs dry — amortized
        O(1) per probe, where rebuilding the candidate set per call was
        an O(N) allocation that multiplied into the sims' N² wall."""
        for _ in range(2):
            while self._cycle:
                nid = self._cycle.pop()
                rec = self.members.get(nid)
                if rec is not None and rec.status != DEAD:
                    return nid
            candidates = sorted(
                n for n, r in self.members.items() if r.status != DEAD
            )
            if not candidates:
                return None
            self._cycle = candidates
            self._rng.shuffle(self._cycle)
        return None  # unreachable: a rebuilt non-empty cycle always pops

    def _ping_reqs(self, probe: _Probe, seq: int) -> list[Envelope]:
        """K indirect probes through other members — the vantage-point
        fan-out that makes one bad link insufficient for expulsion."""
        relays = sorted(
            n
            for n, r in self.members.items()
            if r.status != DEAD and n != probe.target
        )
        if not relays or self.config.indirect == 0:
            return []
        self._rng.shuffle(relays)
        chosen = relays[: self.config.indirect]
        self.indirect_sent += len(chosen)
        _INDIRECT.inc(len(chosen))
        msg = PingReq(self.node_id, probe.target, seq, self._digest())
        return [Envelope(gossip_addr(n), msg) for n in chosen]

    def _ping(self, seq: int) -> Ping:
        return Ping(
            self.node_id,
            self.incarnation,
            seq,
            self.host,
            self.port,
            self._digest(),
        )

    # -- the message handler ---------------------------------------------------

    def handle(self, msg: Any, now: float) -> list[Envelope]:
        out: list[Envelope] = []
        if isinstance(msg, Ping):
            self._absorb(msg.digest, now)
            self._note_direct(msg.sender, msg.incarnation, now)
            out.append(
                Envelope(
                    gossip_addr(msg.sender),
                    Ack(self.node_id, self.incarnation, msg.seq, self._digest()),
                )
            )
        elif isinstance(msg, PingReq):
            self._absorb(msg.digest, now)
            self._note_direct(msg.sender, None, now)
            # relay leg: ping the target with a fresh seq of our own and
            # remember whose probe this answers — the target's ack comes
            # back to us and is re-issued to the origin under ITS seq
            # (bounded: the entry expires with the origin's probe period)
            self._seq += 1
            self._relays[self._seq] = (
                msg.sender, msg.seq, now + self.config.probe_interval_s
            )
            out.append(Envelope(gossip_addr(msg.target), self._ping(self._seq)))
        elif isinstance(msg, Ack):
            self._absorb(msg.digest, now)
            self._note_direct(msg.sender, msg.incarnation, now)
            if msg.seq in self._pending:
                probe = self._pending[msg.seq]
                if probe.target == msg.sender:
                    del self._pending[msg.seq]
            relay = self._relays.pop(msg.seq, None)
            if relay is not None:
                origin, origin_seq, _exp = relay
                _ACKS_RELAYED.inc()
                out.append(
                    Envelope(
                        gossip_addr(origin),
                        Ack(
                            msg.sender,
                            msg.incarnation,
                            origin_seq,
                            self._digest(),
                        ),
                    )
                )
        else:
            raise TypeError(f"gossip cannot handle {type(msg).__name__}")
        return out

    # -- evidence and state transitions ----------------------------------------

    def _note_direct(
        self, sender: int, incarnation: int | None, now: float
    ) -> None:
        """First-hand evidence: a frame FROM the member itself (or a relay
        vouching for it). Clears local suspicion WITHOUT an incarnation
        bump — we hold proof, but only the member itself may refute the
        cluster-wide rumor (SWIM's ordering rule), so nothing is spread."""
        if sender == self.node_id:
            return
        rec = self.members.get(sender)
        if rec is None:
            return  # not in the roster (the master decides membership)
        if incarnation is not None and incarnation < rec.incarnation:
            # a STALE incarnation's frame (a zombie predecessor of the
            # id's current holder) is not evidence for the holder: the
            # hub's heartbeat path ignored exactly this (zombie guard),
            # and clearing suspicion on it would let a dead rejoiner be
            # vouched alive by its own ghost forever
            return
        # a bump PAST A KNOWN token is news; learning a member's first
        # real incarnation (record still at the 0 placeholder — nothing
        # was ever claimed) is not, or every boot would flood digests
        # with N un-news entries per node
        bumped = (
            incarnation is not None and 0 < rec.incarnation < incarnation
        )
        if incarnation is not None and incarnation > rec.incarnation:
            rec.incarnation = incarnation
        was_dead = rec.status == DEAD
        if rec.status != ALIVE:
            rec.status = ALIVE
            rec.suspect_at = None
            self._suspects.discard(sender)
            if bumped:
                # a STRICTLY higher incarnation heard first-hand is a
                # fresh fact, not a rumor we must leave to its owner:
                # ALIVE@inc outranks every lower-incarnation state by
                # the absorb precedence, so spreading it is safe — and
                # without the spread, a promoted master's (or any
                # rejoiner's) revival reaches each member only by
                # DIRECT contact: O(N) probe periods of re-mesh time,
                # the 256-node sims' measured 145 s wall (bounded at
                # ~3·log2(n) periods with it — pinned at scale)
                rec.spread = 0
                self._fresh.add(sender)
            else:
                # equal incarnation: local-only amnesty — we hold
                # first-hand proof, but only the member itself may
                # refute the cluster-wide rumor (SWIM's ordering rule),
                # so nothing is spread
                self._fresh.discard(sender)
                rec.spread = self._spread_limit()
            if was_dead:
                # first-hand proof trumps a rumor we already acted on:
                # surface the revival so the subscriber can re-admit
                self.events.append(
                    GossipEvent(sender, ALIVE, rec.incarnation, now)
                )
        elif bumped:
            # already alive, but the incarnation moved (a refutation or
            # readmission we witnessed first-hand): the new token is
            # news — spread it so stale lower-inc rumors die everywhere
            rec.spread = 0
            self._fresh.add(sender)

    def _suspect(self, node_id: int, now: float) -> None:
        rec = self.members.get(node_id)
        if rec is None or rec.status != ALIVE:
            return
        rec.status = SUSPECT
        rec.suspect_at = now
        rec.spread = 0  # news: spend a fresh piggyback budget on it
        self._suspects.add(node_id)
        self._fresh.add(node_id)
        self.suspicions += 1
        _SUSPICIONS.inc()
        _flight.note(
            "gossip", event="suspect", node=node_id, by=self.node_id,
            incarnation=rec.incarnation,
        )
        self.events.append(GossipEvent(node_id, SUSPECT, rec.incarnation, now))

    def _confirm_dead(self, node_id: int, rec: _Member, now: float) -> None:
        rec.status = DEAD
        rec.suspect_at = None
        rec.spread = 0
        self._suspects.discard(node_id)
        self._fresh.add(node_id)
        self.confirms += 1
        _CONFIRMS.inc()
        _flight.note(
            "gossip", event="confirm_dead", node=node_id, by=self.node_id,
            incarnation=rec.incarnation,
        )
        self.events.append(GossipEvent(node_id, DEAD, rec.incarnation, now))

    def _absorb(self, digest, now: float) -> None:
        """Merge a received membership digest under SWIM's precedence
        rules: higher incarnation wins; at equal incarnation suspect
        overrides alive and dead overrides both (dead is terminal per
        incarnation — only a HIGHER-incarnation alive revives)."""
        for entry in digest:
            nid, inc, status = int(entry[0]), int(entry[1]), int(entry[2])
            if nid == self.node_id:
                if status in (SUSPECT, DEAD) and inc >= self.incarnation:
                    # the refutation rule: the rumor is about US and is
                    # current — bump our incarnation past it and lead the
                    # next digests with the fresh ALIVE claim, which
                    # outranks the suspicion everywhere it spread
                    self.incarnation = inc + 1
                    self._refute_spread = self._spread_limit()
                    self.refutations += 1
                    _REFUTATIONS.inc()
                    _flight.note(
                        "gossip", event="refute", node=self.node_id,
                        incarnation=self.incarnation,
                    )
                continue
            rec = self.members.get(nid)
            if rec is None:
                continue  # roster is master-decided; rumors don't add members
            if status == ALIVE:
                takes = inc > rec.incarnation
            elif status == SUSPECT:
                takes = (
                    inc > rec.incarnation
                    or (inc == rec.incarnation and rec.status == ALIVE)
                )
            else:  # DEAD
                takes = inc >= rec.incarnation and rec.status != DEAD
            if not takes:
                continue
            prev = rec.status
            rec.incarnation = inc
            rec.status = status
            rec.spread = 0  # fresh news spreads onward from here
            self._fresh.add(nid)
            (self._suspects.add if status == SUSPECT
             else self._suspects.discard)(nid)
            if status == SUSPECT:
                if prev != SUSPECT:
                    # start OUR OWN suspicion clock: every process confirms
                    # independently (no single confirmer to lose)
                    rec.suspect_at = now
            else:
                rec.suspect_at = None
            if status == DEAD and prev != DEAD:
                self.confirms += 1
                _CONFIRMS.inc()
                self.events.append(GossipEvent(nid, DEAD, inc, now))
            elif status == ALIVE and prev == DEAD:
                self.events.append(GossipEvent(nid, ALIVE, inc, now))

    # -- digest assembly -------------------------------------------------------

    def _spread_limit(self) -> int:
        """Per-state-change piggyback budget: ~3·ln(n) transmissions
        reaches every member whp (SWIM §5's dissemination bound)."""
        n = max(2, len(self.members) + 1)
        return max(3, int(self.SPREAD_MULT * n.bit_length()))

    def _digest(self) -> tuple[DigestEntry, ...]:
        """Bounded membership digest: our own refutation first (when one
        is in flight), then the entries with the most remaining spread
        budget — fresh news travels, settled state stays off the wire.

        Only the ``_fresh`` index is walked (lazily pruned of entries
        whose budget was spent through another path): in steady state it
        is EMPTY, so the per-message cost is O(1), not O(membership).
        News that did not fit the ``digest_max`` slots counts a
        truncation — the observable form of digest-budget pressure the
        ~3·log2(n) spread bound silently assumed away (OBSERVABILITY.md
        ``gossip.digest_truncations``)."""
        out: list[DigestEntry] = []
        if self._refute_spread > 0:
            self._refute_spread -= 1
            out.append((self.node_id, self.incarnation, ALIVE))
        if self._fresh:
            limit = self._spread_limit()
            fresh: list[tuple[int, int]] = []
            stale: list[int] = []
            for nid in sorted(self._fresh):
                rec = self.members.get(nid)
                if rec is None or rec.spread >= limit:
                    stale.append(nid)
                else:
                    fresh.append((rec.spread, nid))
            for nid in stale:
                self._fresh.discard(nid)
            fresh.sort()
            budget = max(0, self.config.digest_max - len(out))
            for _, nid in fresh[:budget]:
                rec = self.members[nid]
                rec.spread += 1
                if rec.spread >= limit:
                    self._fresh.discard(nid)
                out.append((nid, rec.incarnation, rec.status))
            if len(fresh) > budget:
                self.digest_truncations += 1
                _DIGEST_TRUNCATIONS.inc()
        if out:
            _DIGEST_ENTRIES.inc(len(out))
        return tuple(out)


class GossipAgent:
    """Async binding of one :class:`GossipState` to a live transport:
    registers the ``gossip:<id>`` handler and runs the probe loop as a
    ``run_periodic`` task (through ``observed_task``, like every other
    background loop — a dead probe loop is an ERROR log, not silence).

    ``gate`` (when given) pauses both the probe loop and the handler —
    a fenced-out master or a mid-rejoin node must go quiet, not keep
    acking probes under a stale identity. ``on_message`` is a pre-handle
    hook that may return EXTRA envelopes (the master's unknown-pinger
    ``Rejoin`` reply); ``on_events`` is the subscriber drain — when set,
    the agent hands it every batch of edge events after each tick/handle
    (when unset, the owner drains :meth:`GossipState.poll_events` itself).
    """

    def __init__(
        self,
        transport,
        state: GossipState,
        *,
        clock: Callable[[], float],
        gate: Callable[[], bool] | None = None,
        on_message: Callable[[Any], Any] | None = None,
        on_events: Callable[[list[GossipEvent]], None] | None = None,
    ) -> None:
        self.transport = transport
        self.state = state
        self.clock = clock
        self.gate = gate
        self.on_message = on_message
        self.on_events = on_events
        self._task = None
        transport.register(gossip_addr(state.node_id), self._handle)

    def _handle(self, msg: Any) -> list[Envelope]:
        if self.gate is not None and not self.gate():
            return []
        extra = self.on_message(msg) if self.on_message is not None else None
        out = self.state.handle(msg, self.clock())
        self._drain_events()
        return list(extra or []) + out

    def _drain_events(self) -> None:
        if self.on_events is None:
            return  # the owner polls the state directly (master-side)
        events = self.state.poll_events()
        if events:
            self.on_events(events)

    def start(self) -> None:
        """Spawn the probe loop (requires a running event loop). Sync by
        design: callable from inside a transport handler (the node's
        Welcome path)."""
        from akka_allreduce_tpu.control.remote import (
            observed_task,
            run_periodic,
        )

        # sub-period cadence so ack timeouts (fractions of the probe
        # interval) are observed promptly; tick() itself rate-limits the
        # actual probes to one per probe_interval_s
        period = self.state.config.probe_timeout_s / 2.0
        self._task = observed_task(
            run_periodic(period, self._tick),
            name=f"gossip-{self.state.node_id}",
        )

    async def _tick(self) -> None:
        if self.gate is not None and not self.gate():
            return
        out = self.state.tick(self.clock())
        self._drain_events()
        if out:
            await self.transport.send_all(out)

    def cancel(self) -> None:
        """Tear down synchronously (a rejoin's re-welcome runs inside a
        transport handler): the probe loop is cancelled and the address
        registration is replaced with a drop handler, so a superseded
        identity can never keep answering probes."""
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.transport.register(
            gossip_addr(self.state.node_id), lambda _msg: []
        )

    async def stop(self) -> None:
        import asyncio

        task = self._task
        self.cancel()
        if task is not None:
            try:
                await task
            except asyncio.CancelledError:
                pass
