"""Grid-coordinate pod bootstrap + pure shard assignment (RESILIENCE.md
"Scale — the pod-scale control plane").

The paper's own structure is a 2D grid/butterfly over 16+ workers
(PAPER.md §1), and real pods boot the way SNIPPETS.md [2]'s
multi-controller ``jax.distributed.initialize()`` pattern does: every
process learns its ``process_index`` and derives its place in the grid
from it — NOT from the order its join request happened to reach the
master. This module owns that derivation:

- :func:`parse_grid` — the ``RxC`` spec (``"2x8"``) every pod-aware CLI
  flag speaks;
- :func:`resolve_process_index` — explicit flag > environment
  (``AKKA_PROCESS_INDEX``, then the common pod launchers' variables) >
  ``jax.distributed``'s own index, so the same binary boots under a
  scheduler, under a pod runtime, or by hand;
- :func:`grid_coords` / :func:`grid_node_id` — process index <-> (row,
  col) <-> node id, row-major: the node id IS the coordinate, which is
  what makes shard membership a function of the pod layout instead of
  join order;
- :func:`shard_assignment` / :func:`coordinate_shard_assignment` — the
  PURE functions the :class:`GridMaster` re-shards with on every
  reorganize. Purity is the point: the same membership view must produce
  the same shards on every rebuild (a standby takeover replaces the grid
  wholesale mid-incident, and a re-mesh that shuffled workers between
  shards would burn round floors for nothing) — pinned in
  tests/test_grid_hierarchy.py.

Everything here is stdlib-only and clock-free; the jax probe is an
optional last resort behind an import guard (this container's jax is the
documented 0.4.37 skew — the control plane must never depend on it).
"""

from __future__ import annotations

import os

__all__ = [
    "parse_grid",
    "resolve_process_index",
    "grid_coords",
    "grid_node_id",
    "shard_assignment",
    "coordinate_shard_assignment",
]

#: environment variables consulted for the process index, in precedence
#: order — the first one set wins. AKKA_PROCESS_INDEX is ours; the rest
#: are what common pod/task launchers export for exactly this purpose.
PROCESS_INDEX_ENV = (
    "AKKA_PROCESS_INDEX",
    "JAX_PROCESS_INDEX",
    "CLOUD_TPU_TASK_ID",
    "TPU_WORKER_ID",
    "SLURM_PROCID",
    "OMPI_COMM_WORLD_RANK",
    "RANK",
)


def parse_grid(spec: str) -> tuple[int, int]:
    """``"RxC"`` -> (rows, cols); both sides positive integers."""
    parts = spec.lower().split("x")
    if len(parts) != 2:
        raise ValueError(f"grid spec must be RxC (e.g. 2x8), got {spec!r}")
    try:
        rows, cols = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"grid spec must be RxC with integer sides, got {spec!r}"
        ) from None
    if rows < 1 or cols < 1:
        raise ValueError(f"grid sides must be >= 1, got {spec!r}")
    return rows, cols


def resolve_process_index(explicit: int | None = None) -> int:
    """This process's pod index: an explicit value wins, then the first
    set entry of :data:`PROCESS_INDEX_ENV`, then ``jax.process_index()``
    when a distributed jax runtime is already up (never initialized from
    here — bootstrap must not own jax's lifecycle). Raises when nothing
    answers: a pod bootstrap with an unknowable coordinate is a config
    error, not node id -1."""
    if explicit is not None and explicit >= 0:
        return explicit
    for var in PROCESS_INDEX_ENV:
        val = os.environ.get(var)
        if val is not None and val.strip() != "":
            try:
                idx = int(val)
            except ValueError:
                raise ValueError(
                    f"{var}={val!r} is not an integer process index"
                ) from None
            if idx < 0:
                raise ValueError(f"{var}={idx} must be >= 0")
            return idx
    try:  # last resort: a live multi-controller jax runtime knows
        import jax

        return int(jax.process_index())
    except Exception:
        raise ValueError(
            "cannot resolve a process index: pass --process-index, set "
            f"one of {PROCESS_INDEX_ENV}, or run under jax.distributed"
        ) from None


def grid_coords(process_index: int, rows: int, cols: int) -> tuple[int, int]:
    """Row-major (row, col) of ``process_index`` in an RxC grid."""
    if not 0 <= process_index < rows * cols:
        raise ValueError(
            f"process index {process_index} outside the {rows}x{cols} grid"
        )
    return process_index // cols, process_index % cols

def grid_node_id(row: int, col: int, cols: int) -> int:
    """The node id OF a coordinate — row-major, so ids enumerate the pod
    the same way process indices do and shard membership follows the
    layout, not join order."""
    return row * cols + col


def shard_assignment(
    nodes, shards: int
) -> list[list[int]]:
    """Contiguous, balanced split of a membership view into up to
    ``shards`` non-empty shards — the dims-1 ``--line-shards`` rule.

    A PURE function of (sorted view, shard count): same view -> identical
    shards, across GridMaster rebuilds and standby takeovers alike. Sizes
    differ by at most one, larger shards first.
    """
    view = sorted(nodes)
    if not view:
        return []
    n_shards = max(1, min(int(shards), len(view)))
    base, extra = divmod(len(view), n_shards)
    out: list[list[int]] = []
    start = 0
    for s in range(n_shards):
        size = base + (1 if s < extra else 0)
        out.append(view[start : start + size])
        start += size
    return out


def coordinate_shard_assignment(
    nodes, rows: int, cols: int, shards: int
) -> list[list[int]]:
    """Shard membership from GRID COORDINATES: the full RxC coordinate
    space is cut into up to ``shards`` fixed, contiguous, row-major
    blocks, and each live node lands in the block its node id (== its
    coordinate) belongs to. Dead members just shrink their block — the
    boundaries never move, so a single expulsion can never shuffle
    workers between shards the way a balanced re-split of the live view
    would. Empty blocks drop out (their members are all gone).

    Pure in (view, grid, shard count), like :func:`shard_assignment`.
    Ids at or past ``rows*cols`` (a non-pod joiner minted past the grid)
    overflow into the LAST block rather than being dropped — membership
    is the master's call, the layout just places it.
    """
    view = sorted(nodes)
    if not view:
        return []
    total = rows * cols
    n_shards = max(1, min(int(shards), total))
    base, extra = divmod(total, n_shards)
    # block s covers coordinate indices [bounds[s], bounds[s+1])
    bounds = [0]
    for s in range(n_shards):
        bounds.append(bounds[-1] + base + (1 if s < extra else 0))
    blocks: list[list[int]] = [[] for _ in range(n_shards)]
    for nid in view:
        s = n_shards - 1
        for i in range(n_shards):
            if nid < bounds[i + 1]:
                s = i
                break
        blocks[s].append(nid)
    return [b for b in blocks if b]
