"""Failure detection for the elastic control plane.

The reference gets membership from Akka Cluster's gossip + phi-accrual failure
detector (SURVEY.md §3 "Membership"; §4.5 call stack). The TPU build keeps the
same two-tier contract: within-round straggling is absorbed by thresholds (no
detector involvement), while *sustained* silence trips the detector and drives
the master's re-mesh (SURVEY.md §8.4).

``PhiAccrualFailureDetector`` is the standard Hayashibara et al. estimator the
reference relies on: per node, keep a window of heartbeat inter-arrival times,
model them as normal, and report suspicion ``phi = -log10(P(heartbeat still
coming after t_silent))``. ``phi >= threshold`` (default 8, Akka's default)
marks the node unreachable. ``HeartbeatMonitor`` turns that into edge-triggered
membership events for the GridMaster.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from enum import Enum
from typing import Callable

from akka_allreduce_tpu.obs import flight as _flight


class MemberState(Enum):
    UP = "up"
    UNREACHABLE = "unreachable"


@dataclasses.dataclass
class MembershipEvent:
    node_id: int
    state: MemberState
    at: float
    phi: float


class PhiAccrualFailureDetector:
    """Suspicion-level failure detector over heartbeat inter-arrival times."""

    def __init__(
        self,
        *,
        threshold: float = 8.0,
        window: int = 100,
        min_std: float = 0.05,
        first_heartbeat_estimate: float = 1.0,
    ) -> None:
        if threshold <= 0:
            raise ValueError(f"threshold must be positive, got {threshold}")
        self.threshold = threshold
        self.window = window
        self.min_std = min_std
        self.first_estimate = first_heartbeat_estimate
        self._intervals: dict[int, deque[float]] = {}
        self._last: dict[int, float] = {}

    def heartbeat(self, node_id: int, now: float) -> None:
        last = self._last.get(node_id)
        if last is None:
            # seed the history with the configured estimate (the Akka
            # detector's bootstrap) so the first few real samples — which may
            # be tiny — cannot collapse the estimated interval to ~0
            self._intervals[node_id] = deque(
                [self.first_estimate], maxlen=self.window
            )
        else:
            self._intervals[node_id].append(max(now - last, 0.0))
        self._last[node_id] = now

    def remove(self, node_id: int) -> None:
        self._intervals.pop(node_id, None)
        self._last.pop(node_id, None)

    def _mean_std(self, node_id: int) -> tuple[float, float]:
        xs = self._intervals.get(node_id)
        if not xs:
            # one (or zero) heartbeats seen: assume the configured estimate
            # with generous spread, as the Akka detector does on first contact
            return self.first_estimate, self.first_estimate / 2.0
        mean = sum(xs) / len(xs)
        var = sum((x - mean) ** 2 for x in xs) / len(xs)
        return mean, max(math.sqrt(var), self.min_std, mean * 0.1)

    def phi(self, node_id: int, now: float) -> float:
        """Suspicion level; 0 for a node never heard from (can't suspect it)."""
        last = self._last.get(node_id)
        if last is None:
            return 0.0
        mean, std = self._mean_std(node_id)
        t = now - last
        y = (t - mean) / std
        # P(X > t) for X ~ N(mean, std), via the logistic approximation to the
        # normal CDF used by the reference detector family
        p_later = 1.0 / (1.0 + math.exp(min(y * 1.5976 + 0.070566 * y**3, 700.0)))
        if p_later <= 0.0:
            return float("inf")
        return -math.log10(p_later)

    def is_available(self, node_id: int, now: float) -> bool:
        return self.phi(node_id, now) < self.threshold


class LeaderLease:
    """Standby-side lease on the leader, fed by ``StateDigest`` arrivals.

    The phi detector with a single pseudo-member (the leader): ``renew``
    on every digest, and ``expired`` once suspicion crosses the threshold
    — the takeover trigger of the master-HA protocol (RESILIENCE.md
    "Tier 4"). A standby that never received a digest can NOT expire the
    lease: it cannot distinguish "leader dead" from "my registration never
    landed", so it keeps re-registering instead of seizing an epoch whose
    state it does not hold.
    """

    _LEADER = -1  # MASTER_ROLE: the only member this detector tracks

    def __init__(
        self,
        *,
        threshold: float = 8.0,
        first_heartbeat_estimate: float = 1.0,
    ) -> None:
        self.detector = PhiAccrualFailureDetector(
            threshold=threshold,
            first_heartbeat_estimate=first_heartbeat_estimate,
        )
        self.renewals = 0

    def renew(self, now: float) -> None:
        self.detector.heartbeat(self._LEADER, now)
        self.renewals += 1

    def phi(self, now: float) -> float:
        return self.detector.phi(self._LEADER, now)

    def expired(self, now: float) -> bool:
        return self.renewals > 0 and not self.detector.is_available(
            self._LEADER, now
        )

    def reset(self) -> None:
        """Forget the lease history (a fresh leader identity: its digest
        cadence must not inherit the dead leader's inter-arrival model)."""
        self.detector.remove(self._LEADER)
        self.renewals = 0


class HeartbeatMonitor:
    """Edge-triggered membership tracking on top of the phi detector.

    Feed it heartbeats; ``poll(now)`` returns the membership *changes* since
    the last poll — the events the GridMaster's ``member_up`` /
    ``member_unreachable`` handlers consume (SURVEY.md §4.5).
    """

    def __init__(
        self,
        detector: PhiAccrualFailureDetector | None = None,
        *,
        on_event: Callable[[MembershipEvent], None] | None = None,
    ) -> None:
        self.detector = detector or PhiAccrualFailureDetector()
        self.states: dict[int, MemberState] = {}
        self._on_event = on_event

    @property
    def members_up(self) -> list[int]:
        return sorted(
            n for n, s in self.states.items() if s is MemberState.UP
        )

    def heartbeat(self, node_id: int, now: float) -> MembershipEvent | None:
        """Record a heartbeat; returns an UP event if this (re)joins the node."""
        if self.states.get(node_id) is not MemberState.UP:
            # (re)joining after death or silence: the dead gap must not enter
            # the inter-arrival model — each such sample inflates mean/std and
            # makes the detector progressively slower until real crashes go
            # undetected (observed across repeated crash/rejoin cycles)
            self.detector.remove(node_id)
            self.detector.heartbeat(node_id, now)
            return self._transition(node_id, MemberState.UP, now)
        self.detector.heartbeat(node_id, now)
        return None

    def leave(self, node_id: int, now: float) -> MembershipEvent | None:
        """Graceful departure (the reference's Cluster leave)."""
        self.detector.remove(node_id)
        if self.states.get(node_id) is MemberState.UP:
            return self._transition(node_id, MemberState.UNREACHABLE, now)
        self.states.pop(node_id, None)
        return None

    def force_unreachable(self, node_id: int, now: float) -> MembershipEvent | None:
        """Subscriber entry point for an EXTERNAL failure verdict — the
        SWIM gossip layer's confirmed-dead (control/gossip.py): with
        decentralized membership this monitor no longer judges liveness
        itself for gossip-speaking members, it only keeps the same
        edge-triggered event contract the GridMaster consumes. Returns
        the UNREACHABLE edge, or None when the node was already down."""
        self.detector.remove(node_id)
        if self.states.get(node_id) is not MemberState.UP:
            return None
        return self._transition(node_id, MemberState.UNREACHABLE, now)

    def poll(self, now: float) -> list[MembershipEvent]:
        """Detect silent nodes; returns newly-unreachable events."""
        events = []
        for node_id, state in list(self.states.items()):
            if state is MemberState.UP and not self.detector.is_available(
                node_id, now
            ):
                events.append(
                    self._transition(node_id, MemberState.UNREACHABLE, now)
                )
        return events

    def _transition(
        self, node_id: int, state: MemberState, now: float
    ) -> MembershipEvent:
        self.states[node_id] = state
        ev = MembershipEvent(
            node_id, state, now, self.detector.phi(node_id, now)
        )
        # membership edges into the flight-recorder ring: a chaos/stall
        # post-mortem reads WHEN the detector acted next to what the
        # transports dropped (RESILIENCE.md)
        _flight.note(
            "membership",
            node=node_id,
            state=state.value,
            phi=round(ev.phi, 2) if math.isfinite(ev.phi) else "inf",
        )
        if self._on_event:
            self._on_event(ev)
        return ev
