"""Peer-to-peer state transfer: checkpoint chunks served between nodes.

ROADMAP item 5 (RESILIENCE.md "Recovery"): after the compile cache cut warm
re-mesh 5-6.6x, re-mesh latency is dominated by *state restore*, and a node
that loses its disk along with its process cannot rejoin at all. This module
makes delta-checkpoint state a **cluster** resource instead of a per-disk
one:

- a :class:`ChunkService` on every node serves the content-addressed blobs a
  ``DeltaCheckpointer`` manifest names (``blobs/<sha>.npy``) over new wire
  tags (``control/wire.py`` tags 14-20), riding the zero-copy scatter-gather
  send path — the chunk payload segment is a ``memoryview`` handed straight
  to ``sendmsg``, with the additive u32 wire checksum of the payload tags
  verified on decode;
- after every delta save the owner **replicates** its newest manifest's
  chunks to ``replicas`` peers (next ids on the address-book ring), bounded
  (one replication in flight; content-addressed dedup per peer means an
  unchanged leaf is never re-sent) and backpressure-aware (sends go through
  the transport's high-water wait), so state outlives any single disk;
- a **rejoining node** asks the master for the newest manifest + the peer
  map of its holders (``ManifestRequest``/``ManifestReply``) and pulls the
  chunks it is missing in parallel from live peers — per-chunk retry with
  the PR-5 :class:`~akka_allreduce_tpu.config.RetryPolicy` backoff, failover
  across holders, resumable after a partition heal (already-fetched chunks
  are never re-pulled) — verifies every chunk's CONTENT hash before
  publishing it, and only then restores.

Verification is end to end: the wire checksum rejects transport corruption
at decode, and :func:`npy_sha` re-derives the manifest's content hash from
the received bytes — a chunk whose bytes do not hash to its name is
rejected and re-fetched, never written. Because blobs are content-addressed,
a peer-restored store is byte-identical to the disk it replaces (pinned by
the ``chaos-recover`` scenario in tests/test_peer_restore.py).

Everything here is numpy + stdlib — no jax — so the control plane can host
chunk services without importing the training stack; ``train/checkpoint.py``
imports :func:`leaf_sha` from here (one definition of the content hash).
"""

from __future__ import annotations

import asyncio
import dataclasses
import io
import json
import logging
import os
import random
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

from akka_allreduce_tpu.config import RetryPolicy
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics
from akka_allreduce_tpu.obs import trace as _trace

log = logging.getLogger(__name__)

__all__ = [
    "CheckpointAdvert",
    "ManifestRequest",
    "ManifestReply",
    "ChunkFetch",
    "ChunkData",
    "ChunkMissing",
    "ReplicaManifest",
    "ChunkStore",
    "ChunkService",
    "leaf_sha",
    "npy_bytes",
    "npy_sha",
    "copy_delta",
]

# -- metrics (OBSERVABILITY.md "restore.* / replicate.*") ----------------------
# module-level objects, like remote.py's drop counters: hot-path increments
# are one attribute add, never a registry lookup
_R_CHUNKS_FETCHED = _metrics.counter("restore.chunks_fetched")
_R_BYTES_FETCHED = _metrics.counter("restore.bytes_fetched")
_R_CHUNKS_SERVED = _metrics.counter("restore.chunks_served")
_R_BYTES_SERVED = _metrics.counter("restore.bytes_served")
_R_RETRIES = _metrics.counter("restore.chunk_retries")
_R_FAILOVERS = _metrics.counter("restore.failovers")
_R_REJECTED = _metrics.counter("restore.chunks_rejected")
_R_FROM_PEER = _metrics.counter("restore.from_peer")
_R_FROM_DISK = _metrics.counter("restore.from_disk")
_R_SECONDS = _metrics.gauge("restore.seconds")
_P_CHUNKS_SENT = _metrics.counter("replicate.chunks_sent")
_P_BYTES_SENT = _metrics.counter("replicate.bytes_sent")
_P_CHUNKS_STORED = _metrics.counter("replicate.chunks_stored")
_P_BYTES_STORED = _metrics.counter("replicate.bytes_stored")
_P_MANIFESTS = _metrics.counter("replicate.manifests_stored")
_P_REJECTED = _metrics.counter("replicate.chunks_rejected")
_P_SKIPPED_BUSY = _metrics.counter("replicate.skipped_busy")
_P_ROUNDS = _metrics.counter("replicate.rounds")


# -- wire messages (tags 14-20 in control/wire.py) -----------------------------


@dataclasses.dataclass(frozen=True)
class CheckpointAdvert:
    """Holder -> master: "I hold ``origin``'s delta checkpoint at ``step``".

    Sent by the owner after every delta save (``origin == node_id``) and by
    each replica once a pushed manifest's chunks are all stored locally.
    The master folds adverts into its holder map — the "peer map" half of
    :class:`ManifestReply`. Carries the manifest itself so the newest state
    survives the loss of BOTH the owner's process and its disk (the master
    can hand the manifest to the rejoiner; replicas hold the bytes)."""

    node_id: int
    origin: int
    step: int
    manifest_json: str


@dataclasses.dataclass(frozen=True)
class ManifestRequest:
    """Rejoining node -> master: what is my newest checkpoint, who holds it?"""

    node_id: int


@dataclasses.dataclass(frozen=True)
class ManifestReply:
    """Master -> node: newest known manifest for the requester + peer map.

    ``step < 0`` means the master knows of no checkpoint for this node
    (fresh cluster, or every holder is gone) — the node starts from
    scratch. ``holders`` excludes the requester and unreachable members."""

    step: int
    manifest_json: str
    holders: tuple[int, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "holders", tuple(self.holders))


@dataclasses.dataclass(frozen=True)
class ChunkFetch:
    """Node -> peer chunk service (``ckpt:<holder>``): pull one blob."""

    sha: str
    requester: int


@dataclasses.dataclass(frozen=True, eq=False)
class ChunkData:
    """One blob's bytes on the wire (fetch reply, or replication push).

    ``payload`` is the raw ``.npy`` file bytes; on the wire it travels as a
    length-prefixed byte segment with the additive u32 checksum the payload
    tags use (decode rejects flips), encoded as a zero-copy memoryview
    segment through ``encode_frame_parts``. ``push`` distinguishes a
    replication push (store it; ``step``/``origin`` say what it belongs to)
    from a fetch reply (resolve the requester's pending pull)."""

    sha: str
    payload: Any  # bytes | memoryview | np.ndarray(u8) view into recv buffer
    origin: int = -1
    step: int = -1
    push: bool = False


@dataclasses.dataclass(frozen=True)
class ChunkMissing:
    """Peer -> node: the requested blob is not here (failover signal —
    the requester tries the next holder immediately, no timeout burned)."""

    sha: str
    holder: int


@dataclasses.dataclass(frozen=True)
class ReplicaManifest:
    """Owner -> replica: every chunk of ``step`` has been pushed; store the
    manifest durably and advertise yourself to the master as a holder."""

    step: int
    manifest_json: str
    origin: int


@dataclasses.dataclass(frozen=True)
class AdvertSolicit:
    """Master -> node: re-send your :class:`CheckpointAdvert`\\ s now.

    A replacement master binds the seed endpoint with an EMPTY holder
    registry; until nodes happen to re-advertise (which normally rides the
    rejoin Welcome) it would answer ``ManifestRequest`` with a dead end.
    The master therefore solicits adverts on first contact with an unknown
    node and whenever a manifest request finds no live holder — so a
    restore issued immediately after a master restart still converges on
    the surviving replicas (RESILIENCE.md "Tier 4")."""

    reason: str = ""


# -- content hashing (ONE definition; train/checkpoint.py imports these) -------


def leaf_sha(arr: np.ndarray) -> str:
    """Content hash of one checkpoint leaf: sha256 over ``(dtype, shape)``
    then the raw buffer. This IS the blob name in a ``DeltaCheckpointer``
    manifest — keep byte-compatible with every manifest ever written."""
    import hashlib

    arr = np.asarray(arr)
    # hash the raw buffer via memoryview (no tobytes copy). NB
    # ascontiguousarray promotes 0-d to 1-d, so only use it as a hashing
    # VIEW and never hand it back
    buf = arr if arr.flags["C_CONTIGUOUS"] else np.ascontiguousarray(arr)
    h = hashlib.sha256(str((arr.dtype, arr.shape)).encode())
    h.update(buf.data)
    return h.hexdigest()


def npy_bytes(arr: np.ndarray) -> bytes:
    """Serialized ``.npy`` file bytes of ``arr`` (what a blob file holds)."""
    bio = io.BytesIO()
    np.save(bio, np.asarray(arr), allow_pickle=False)
    return bio.getvalue()


def npy_sha(data: bytes | bytearray | memoryview) -> str:
    """Content hash of serialized ``.npy`` bytes — the end-to-end chunk
    verification: a fetched blob whose bytes do not hash back to its
    manifest name is corrupt (or wrong) and must not be published.
    Raises ``ValueError`` on bytes that are not a loadable ``.npy``."""
    bio = io.BytesIO(bytes(data))
    arr = np.load(bio, allow_pickle=False)
    return leaf_sha(arr)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - exists, not ours
        return True
    return True


def note_disk_restore(seconds: float) -> None:
    """Record a disk-path restore in the shared ``restore.*`` metrics —
    ONE definition of the metric names, used by bootstrap's restore path."""
    _R_FROM_DISK.inc()
    _R_SECONDS.set(seconds)


def fsync_write(path: Path, data: bytes) -> None:
    """Write ``data`` to ``path`` durably: flush + fsync BEFORE returning,
    so a later atomic rename can never publish a name whose bytes are
    still in the page cache when the machine dies (the torn-manifest /
    truncated-blob crash class the delta store must exclude)."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def publish_file(tmp: Path, final: Path) -> None:
    """Durable atomic publish: rename the fsynced temp file into place and
    fsync the directory so the NAME survives a crash too."""
    os.replace(tmp, final)
    try:
        dirfd = os.open(final.parent, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(dirfd)
    except OSError:  # pragma: no cover - directory fsync unsupported
        pass
    finally:
        os.close(dirfd)


# -- the store -----------------------------------------------------------------


class ChunkStore:
    """Content-addressed blob + manifest store, layout-compatible with
    ``DeltaCheckpointer``: ``blobs/<sha>.npy`` holds each distinct leaf
    once; ``manifest_<step>.json`` maps leaf paths to blob hashes. A store
    can also hold REPLICA manifests for other nodes
    (``manifest_<origin>_<step>.json``) without colliding with its own —
    ``DeltaCheckpointer._manifests`` skips the three-part names, so a
    trainer's delta store and its replica sidecar can even share a root.

    This is the numpy-only half of the delta format: the train layer's
    ``DeltaCheckpointer`` writes the same bytes through jax pytrees; the
    control plane (and the jax-free cluster-node demo role) goes through
    here. Blob and manifest writes are durable (fsync before the atomic
    rename — see :func:`fsync_write`)."""

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = Path(directory).absolute()
        self.blobs = self.directory / "blobs"
        self.blobs.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep

    # -- blobs ----------------------------------------------------------------

    def blob_path(self, sha: str) -> Path:
        if not sha or any(c in sha for c in "/\\."):
            # blob names come off the wire: a hostile sha must never become
            # a path traversal
            raise ValueError(f"malformed blob sha {sha!r}")
        return self.blobs / f"{sha}.npy"

    def has(self, sha: str) -> bool:
        return self.blob_path(sha).exists()

    def read(self, sha: str) -> bytes:
        return self.blob_path(sha).read_bytes()

    def write(self, sha: str, data: bytes | memoryview, *, verify: bool = True) -> bool:
        """Store one blob; returns False when it was already present.
        ``verify`` re-derives the content hash from ``data`` and refuses a
        mismatch (``ValueError``) — the publish gate for bytes that crossed
        a network or another process's disk."""
        blob = self.blob_path(sha)
        if blob.exists():
            return False
        raw = bytes(data)
        if verify and npy_sha(raw) != sha:
            raise ValueError(f"chunk bytes do not hash to {sha[:12]}…")
        tmp = blob.with_suffix(f".tmp{os.getpid()}")
        fsync_write(tmp, raw)
        publish_file(tmp, blob)
        return True

    # -- manifests ------------------------------------------------------------

    @staticmethod
    def _manifest_steps(names, origin: int | None):
        out = {}
        for f in names:
            parts = f.stem.split("_")
            try:
                if origin is None and len(parts) == 2:
                    out[int(parts[1])] = f
                elif (
                    origin is not None
                    and len(parts) == 3
                    and int(parts[1]) == origin
                ):
                    out[int(parts[2])] = f
            except ValueError:
                continue
        return out

    def manifests(self, origin: int | None = None) -> dict[int, Path]:
        return self._manifest_steps(
            self.directory.glob("manifest_*.json"), origin
        )

    def replica_origins(self) -> set[int]:
        """Every origin id this store holds replica manifests for."""
        out: set[int] = set()
        for f in self.directory.glob("manifest_*.json"):
            parts = f.stem.split("_")
            if len(parts) == 3:
                try:
                    out.add(int(parts[1]))
                except ValueError:
                    continue
        return out

    def latest(self, origin: int | None = None) -> tuple[int, str] | None:
        """Newest ``(step, manifest_json)`` or None."""
        steps = self.manifests(origin)
        if not steps:
            return None
        step = max(steps)
        return step, steps[step].read_text()

    def write_manifest(
        self, step: int, manifest_json: str, origin: int | None = None
    ) -> Path:
        name = (
            f"manifest_{step}.json"
            if origin is None
            else f"manifest_{origin}_{step}.json"
        )
        final = self.directory / name
        tmp = self.directory / f".{name}.tmp{os.getpid()}"
        fsync_write(tmp, manifest_json.encode())
        publish_file(tmp, final)
        return final

    def missing(self, manifest_json: str) -> list[str]:
        """Blob hashes the manifest references that are absent here — what
        a (resumed) peer restore still has to pull."""
        leaves = json.loads(manifest_json)["leaves"]
        seen: set[str] = set()
        out: list[str] = []
        for sha in leaves.values():
            if sha not in seen and not self.has(sha):
                seen.add(sha)
                out.append(sha)
        return out

    # -- flat-state convenience (the jax-free demo / soak replica path) --------

    def save_state(self, step: int, state: dict[str, np.ndarray]) -> dict:
        """Delta-save a flat ``{name: array}`` dict as its own manifest
        (owner form, ``manifest_<step>.json``); returns the same stats dict
        shape as ``DeltaCheckpointer.save``. The numpy-only save the
        cluster-node demo role checkpoints through."""
        manifest = {"step": step, "custom": False, "leaves": {}}
        stats = dict(
            written_bytes=0, reused_bytes=0, written_leaves=0, reused_leaves=0
        )
        for key, arr in state.items():
            arr = np.asarray(arr)
            sha = leaf_sha(arr)
            if self.write(sha, npy_bytes(arr), verify=False):
                stats["written_bytes"] += arr.nbytes
                stats["written_leaves"] += 1
            else:
                stats["reused_bytes"] += arr.nbytes
                stats["reused_leaves"] += 1
            manifest["leaves"][key] = sha
        self.write_manifest(step, json.dumps(manifest))
        self.prune()
        return stats

    def load_state(self, step: int | None = None) -> tuple[int, dict[str, np.ndarray]]:
        """Inverse of :meth:`save_state`: ``(step, {name: array})``."""
        steps = self.manifests()
        step = max(steps) if step is None and steps else step
        if step is None or step not in steps:
            raise FileNotFoundError(
                f"no manifest for step {step} under {self.directory}"
            )
        manifest = json.loads(steps[step].read_text())
        return step, {
            key: np.load(self.blob_path(sha), allow_pickle=False)
            for key, sha in manifest["leaves"].items()
        }

    def prune(self) -> None:
        """Keep ``max_to_keep`` manifests per owner/origin, then drop every
        blob no kept manifest references. Tolerates files vanishing
        underneath it (another process sharing the directory — the store
        itself is single-threaded per process by design)."""
        kept: list[Path] = []
        for origin in (None, *sorted(self.replica_origins())):
            steps = self.manifests(origin)
            for step in sorted(steps)[: -self.max_to_keep]:
                steps.pop(step).unlink(missing_ok=True)
            kept.extend(steps.values())
        live: set[str] = set()
        for f in kept:
            try:
                live.update(json.loads(f.read_text())["leaves"].values())
            except FileNotFoundError:
                continue
        for blob in self.blobs.glob("*.npy"):
            if blob.stem not in live:
                blob.unlink(missing_ok=True)
        for stale in self.blobs.glob("*.tmp*"):
            # crash-orphan sweep — but a shared root (trainer delta store +
            # replica sidecar) may have ANOTHER live writer's in-flight
            # temp here: only sweep temps whose embedded pid is dead (our
            # own pattern), never bare ".tmp" files (DeltaCheckpointer's —
            # its own _prune owns those) or a live process's
            suffix = stale.name.rpartition(".tmp")[2]
            if not suffix.isdigit():
                continue
            if int(suffix) != os.getpid() and _pid_alive(int(suffix)):
                continue
            stale.unlink(missing_ok=True)


def copy_delta(
    src: ChunkStore,
    dst: ChunkStore,
    *,
    step: int | None = None,
    origin: int | None = None,
    dst_origin: int | None = None,
    verify: bool = True,
) -> dict:
    """Replicate one manifest's chunks between two LOCAL stores (the
    in-process form of the replication push — the soak loop's replica
    sidecar and its disk-loss restore both go through here, exercising the
    same verify-before-publish gate as the wire path). Returns
    ``{step, chunks_copied, bytes_copied, chunks_skipped}``."""
    latest = src.latest(origin) if step is None else None
    if step is None:
        if latest is None:
            raise FileNotFoundError(f"no manifest under {src.directory}")
        step, manifest_json = latest
    else:
        steps = src.manifests(origin)
        if step not in steps:
            raise FileNotFoundError(f"no manifest for step {step}")
        manifest_json = steps[step].read_text()
    stats = {"step": step, "chunks_copied": 0, "bytes_copied": 0, "chunks_skipped": 0}
    for sha in dict.fromkeys(json.loads(manifest_json)["leaves"].values()):
        data = src.read(sha)
        if dst.write(sha, data, verify=verify):
            stats["chunks_copied"] += 1
            stats["bytes_copied"] += len(data)
        else:
            stats["chunks_skipped"] += 1
    dst.write_manifest(step, manifest_json, dst_origin)
    dst.prune()
    return stats


# -- the service ---------------------------------------------------------------

_TIMEOUT = object()  # sentinel a timed-out pending future resolves to


class ChunkService:
    """One node's chunk endpoint: serves fetches, absorbs pushes, pulls
    restores, replicates saves. Registered on the transport at
    ``ckpt:<node_id>``; peers resolve that address through the ordinary
    address book (``set_prefix_route("ckpt", ...)``), so chunk traffic
    rides the same zero-copy data plane — and the same chaos layer — as
    round payloads.

    All async entry points are driven by the owner through
    ``observed_task`` (arlint ASYNC003); the handler itself is sync and
    returns reply envelopes, like every other handler in the package.
    """

    #: seconds one fetch attempt waits before burning a retry
    chunk_timeout_s = 5.0
    #: chunks pulled concurrently during a peer restore
    fetch_parallel = 8

    def __init__(
        self,
        transport,
        node_id: int,
        store: ChunkStore,
        *,
        replicas: int = 2,
        retry: RetryPolicy | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.transport = transport
        self.node_id = node_id
        self.store = store
        self.replicas = replicas
        self.retry = retry if retry is not None else RetryPolicy(max_retries=3)
        self.clock = clock
        self._pending: dict[str, asyncio.Future] = {}
        self._manifest_fut: asyncio.Future | None = None
        # per-peer shas already pushed this process lifetime: the delta
        # semantics of replication — an unchanged leaf costs zero bytes on
        # the wire after its first push
        self._pushed: dict[int, set[str]] = {}
        # newest manifest step each peer has been handed (lap-skip check)
        self._sent_manifest: dict[int, int] = {}
        self._replicating = False
        #: stats of the most recent completed peer restore (diagnostics)
        self.last_restore: dict | None = None

    # -- addressing ------------------------------------------------------------

    @staticmethod
    def addr(node_id: int) -> str:
        return f"ckpt:{node_id}"

    def replica_peers(self, known: list[int]) -> list[int]:
        """The next ``replicas`` node ids after us on the id ring — a
        stable choice every member computes identically from the address
        book, so holder sets stay predictable across the cluster."""
        ring = sorted(n for n in known if n != self.node_id)
        if not ring:
            return []
        start = 0
        for i, nid in enumerate(ring):
            if nid > self.node_id:
                start = i
                break
        return [ring[(start + k) % len(ring)] for k in range(min(self.replicas, len(ring)))]

    # -- the sync handler (registered at ckpt:<id>) ----------------------------

    def handle(self, msg: Any) -> list[Envelope]:
        if isinstance(msg, ChunkFetch):
            return self._on_fetch(msg)
        if isinstance(msg, ChunkData):
            return self._on_chunk(msg)
        if isinstance(msg, ChunkMissing):
            fut = self._pending.pop(msg.sha, None)
            if fut is not None and not fut.done():
                _R_FAILOVERS.inc()
                fut.set_result(None)  # failover: try the next holder now
            else:
                # unsolicited: replica feedback that a chunk we dedup-
                # skipped is NOT there (its process — maybe its disk —
                # restarted). Drop it from the per-peer pushed set so the
                # next replication round re-pushes it; without this a
                # reborn replica would never be made whole and silently
                # fall out of the replication factor.
                pushed = self._pushed.get(msg.holder)
                if pushed is not None and msg.sha in pushed:
                    pushed.discard(msg.sha)
            return []
        if isinstance(msg, ReplicaManifest):
            return self._on_replica_manifest(msg)
        if isinstance(msg, ManifestReply):
            fut = self._manifest_fut
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return []
        raise TypeError(f"chunk service cannot handle {type(msg).__name__}")

    def _on_fetch(self, msg: ChunkFetch) -> list[Envelope]:
        reply_to = self.addr(msg.requester)
        if not self.store.has(msg.sha):
            _flight.note("chunk_miss", sha=msg.sha[:12], requester=msg.requester)
            return [Envelope(reply_to, ChunkMissing(msg.sha, self.node_id))]
        data = self.store.read(msg.sha)
        _R_CHUNKS_SERVED.inc()
        _R_BYTES_SERVED.inc(len(data))
        return [Envelope(reply_to, ChunkData(msg.sha, data))]

    def _on_chunk(self, msg: ChunkData) -> list[Envelope]:
        if not msg.push:  # fetch reply: hand the bytes to the waiting pull
            fut = self._pending.pop(msg.sha, None)
            if fut is not None and not fut.done():
                # copy out of the recv buffer NOW: the pump recycles it the
                # moment this handler returns, and the future's consumer
                # runs later
                fut.set_result(bytes(msg.payload))
            return []
        # replication push: verify-before-publish, count a rejection
        # instead of storing poison (the origin's next push retries).
        # Materialize the recv-buffer view ONCE — it is the per-push copy.
        raw = bytes(msg.payload)
        try:
            self.store.write(msg.sha, raw, verify=True)
        except ValueError:
            log.warning(
                "rejected pushed chunk %s from node %d (content hash "
                "mismatch)", msg.sha[:12], msg.origin,
            )
            _P_REJECTED.inc()
            return []
        _P_CHUNKS_STORED.inc()
        _P_BYTES_STORED.inc(len(raw))
        return []

    def _on_replica_manifest(self, msg: ReplicaManifest) -> list[Envelope]:
        missing = self.store.missing(msg.manifest_json)
        if missing:
            # pushes are at-most-once: an incomplete replica must NOT
            # advertise itself as a holder. Report what is missing back to
            # the origin so its per-peer push dedup forgets those chunks —
            # a replica reborn without its disk gets re-pushed on the
            # origin's next replication round instead of never (bounded:
            # the next rounds re-report anything beyond the cap)
            log.info(
                "replica of node %d step %d incomplete here (%d chunks "
                "missing); not advertising", msg.origin, msg.step, len(missing),
            )
            return [
                Envelope(self.addr(msg.origin), ChunkMissing(sha, self.node_id))
                for sha in missing[:256]
            ]
        self.store.write_manifest(msg.step, msg.manifest_json, msg.origin)
        self.store.prune()
        _P_MANIFESTS.inc()
        _flight.note(
            "replica_stored", origin=msg.origin, step=msg.step,
        )
        return [
            Envelope(
                "master",
                CheckpointAdvert(
                    self.node_id, msg.origin, msg.step, msg.manifest_json
                ),
            )
        ]

    # -- replication (owner side) ----------------------------------------------

    def replicate_busy(self) -> bool:
        return self._replicating

    #: catch-up laps one replicate_latest call may run when saves keep
    #: landing while a lap is in flight (bounds the loop, not correctness:
    #: the next save kicks another call)
    replicate_max_laps = 4

    async def replicate_latest(self, peers: list[int]) -> dict | None:
        """Push the newest local manifest's chunks to ``peers`` then hand
        them the manifest; skipped (counted) when a previous replication is
        still in flight — replication must bound bandwidth, not queue
        behind itself.

        Saves can outpace a lap (a push of MBs through a busy data plane
        sits behind backpressure), so this loops: each lap re-reads the
        CURRENT latest manifest, and a lap that discovers a needed blob
        was pruned mid-flight ABORTS without sending the manifest — a
        knowingly-incomplete step is never advertised — and the next lap
        chases the newer step whose blobs exist. Returns the last lap's
        stats or None when skipped/empty."""
        if self._replicating:
            _P_SKIPPED_BUSY.inc()
            return None
        if not peers:
            return None
        self._replicating = True
        stats = None
        try:
            for _ in range(self.replicate_max_laps):
                latest = self.store.latest()
                if latest is None:
                    break
                step, manifest_json = latest
                if all(
                    self._sent_manifest.get(p, -1) >= step for p in peers
                ):
                    break  # every peer already has the current latest
                stats = await self._replicate(step, manifest_json, peers)
                if not stats.pop("stale", False):
                    break
        finally:
            self._replicating = False
        return stats

    async def _replicate(
        self, step: int, manifest_json: str, peers: list[int]
    ) -> dict:
        stats = {"step": step, "peers": list(peers), "chunks_sent": 0, "bytes_sent": 0}
        shas = list(dict.fromkeys(json.loads(manifest_json)["leaves"].values()))
        for peer in peers:
            pushed = self._pushed.setdefault(peer, set())
            for sha in shas:
                if sha in pushed:
                    continue
                try:
                    data = self.store.read(sha)
                except FileNotFoundError:
                    # pruned while this lap slept in backpressure: this
                    # step can no longer be made whole anywhere — abort
                    # WITHOUT the manifest send (never advertise a step we
                    # know is incomplete) and let the caller's next lap
                    # push the newer step that superseded it
                    stats["stale"] = True
                    return stats
                # transport.send applies high-water backpressure: a slow
                # replica throttles this loop instead of ballooning memory
                await self.transport.send(
                    Envelope(
                        self.addr(peer),
                        ChunkData(
                            sha, data, origin=self.node_id, step=step, push=True
                        ),
                    )
                )
                pushed.add(sha)
                stats["chunks_sent"] += 1
                stats["bytes_sent"] += len(data)
                _P_CHUNKS_SENT.inc()
                _P_BYTES_SENT.inc(len(data))
            await self.transport.send(
                Envelope(
                    self.addr(peer),
                    ReplicaManifest(step, manifest_json, self.node_id),
                )
            )
            self._sent_manifest[peer] = max(
                self._sent_manifest.get(peer, -1), step
            )
        _P_ROUNDS.inc()
        _flight.note("replicate", step=step, peers=stats["peers"])
        return stats

    def note_send_failure(self, env: Envelope) -> None:
        """Transport ``on_send_error`` hook: a replication push that never
        reached the wire (backpressure drop, dead connection, partition)
        must be un-marked in the per-peer dedup set, or the chunk would be
        skipped on every later round while the replica stays incomplete —
        the send-time optimism of the dedup is only sound because every
        OBSERVABLE loss is repaired here (silent chaos drops are repaired
        by the replica's ChunkMissing feedback instead)."""
        msg = env.msg
        _, _, suffix = env.dest.rpartition(":")
        if not suffix.lstrip("-").isdigit():
            return
        peer = int(suffix)
        if isinstance(msg, ChunkData) and msg.push:
            self._pushed.get(peer, set()).discard(msg.sha)
        elif isinstance(msg, ReplicaManifest):
            if self._sent_manifest.get(peer, -1) <= msg.step:
                self._sent_manifest.pop(peer, None)  # re-send next lap

    # -- manifest discovery (rejoiner side) ------------------------------------

    async def request_manifest(
        self, *, attempts: int = 3, timeout_s: float | None = None
    ) -> ManifestReply | None:
        """Ask the master for our newest manifest + holders; None when the
        master never answered (it may itself be restarting — the caller
        decides whether to retry later or start fresh)."""
        timeout = self.chunk_timeout_s if timeout_s is None else timeout_s
        for attempt in range(max(1, attempts)):
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            self._manifest_fut = fut
            try:
                await self.transport.send(
                    Envelope("master", ManifestRequest(self.node_id))
                )
                reply = await _wait_result(fut, timeout)
            finally:
                self._manifest_fut = None
            if reply is not _TIMEOUT and reply is not None:
                return reply
            if attempt + 1 < attempts:
                await asyncio.sleep(
                    self.retry.backoff_s(attempt, random.random())
                )
        return None

    # -- peer restore (rejoiner side) ------------------------------------------

    async def _fetch_chunk(self, sha: str, holders: list[int]) -> bool:
        """Pull one blob: per-chunk retry budget over the holder list (a
        missing/unreachable holder fails over to the next), content-verify,
        publish. True on success."""
        if not holders:
            return self.store.has(sha)
        budget = self.retry.max_retries + 1
        # stagger the starting holder per chunk (derived from the sha):
        # without this every concurrent pull hammers holders[0] while the
        # other replicas sit idle — spreading costs nothing and halves the
        # busiest peer's serve load at K=2
        start = sum(sha.encode()) % len(holders)
        for attempt in range(budget * len(holders)):
            if self.store.has(sha):
                return True  # a concurrent pull (or a push) beat us to it
            holder = holders[(start + attempt) % len(holders)]
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            self._pending[sha] = fut
            try:
                await self.transport.send(
                    Envelope(self.addr(holder), ChunkFetch(sha, self.node_id))
                )
                data = await _wait_result(fut, self.chunk_timeout_s)
            finally:
                self._pending.pop(sha, None)
            if isinstance(data, (bytes, bytearray)):
                try:
                    self.store.write(sha, data, verify=True)
                except ValueError:
                    _R_REJECTED.inc()
                    log.warning(
                        "chunk %s from node %d failed content verification; "
                        "re-fetching", sha[:12], holder,
                    )
                    continue
                _R_CHUNKS_FETCHED.inc()
                _R_BYTES_FETCHED.inc(len(data))
                return True
            if data is _TIMEOUT:
                _R_RETRIES.inc()
                await asyncio.sleep(
                    self.retry.backoff_s(attempt % budget, random.random())
                )
            # None = ChunkMissing failover — loop to the next holder at once
        return False

    async def restore_from_peers(
        self, step: int, manifest_json: str, holders: list[int]
    ) -> dict:
        """Pull every chunk of ``manifest_json`` this store is missing from
        ``holders`` (parallel, bounded), verify, publish the manifest, and
        advertise ourselves to the master. Resumable by construction:
        already-present chunks (a partial earlier attempt, or replication
        pushes that landed here) are skipped, so a partition mid-restore
        costs only the chunks not yet fetched. Returns stats; ``complete``
        False when some chunks stayed unfetchable (caller retries with a
        fresh holder map)."""
        t0 = time.perf_counter()
        need = self.store.missing(manifest_json)
        sem = asyncio.Semaphore(self.fetch_parallel)
        results: dict[str, bool] = {}

        async def pull(sha: str) -> None:
            async with sem:
                results[sha] = await self._fetch_chunk(sha, list(holders))

        with _trace.span(
            "restore.peer", step=step, chunks=len(need), node=self.node_id
        ):
            if need and holders:
                await asyncio.gather(*(pull(sha) for sha in need))
        fetched = sum(1 for ok in results.values() if ok)
        complete = not need or (holders and all(results.values()))
        stats = {
            "source": "peer",
            "step": step,
            "seconds": round(time.perf_counter() - t0, 3),
            "chunks_needed": len(need),
            "chunks_fetched": fetched,
            "complete": bool(complete),
        }
        if complete:
            self.store.write_manifest(step, manifest_json)
            self.store.prune()
            _R_FROM_PEER.inc()
            _R_SECONDS.set(stats["seconds"])
        self.last_restore = stats
        _flight.note("restore_peer", **{k: stats[k] for k in ("step", "seconds", "chunks_fetched", "complete")})
        return stats


async def _wait_result(fut: asyncio.Future, timeout: float):
    """Await ``fut`` with a deadline, resolving to the ``_TIMEOUT``
    sentinel instead of raising. Deliberately NOT ``asyncio.wait_for``: on
    Python < 3.12 it can swallow an external task cancellation that races
    the future's completion (the PR-2 transport deadlock class) — a plain
    ``await`` with a manual timer propagates cancellation verbatim."""
    loop = asyncio.get_running_loop()
    timer = loop.call_later(
        timeout, lambda: None if fut.done() else fut.set_result(_TIMEOUT)
    )
    try:
        return await fut
    finally:
        timer.cancel()
