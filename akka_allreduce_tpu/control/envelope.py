"""Addressed messages between control-plane handlers."""

from __future__ import annotations

import dataclasses
from typing import Any

MASTER = "master"


def peer_addr(worker_id: int) -> str:
    return f"worker:{worker_id}"


def master_addr(line_id: int = 0) -> str:
    return f"line_master:{line_id}"


@dataclasses.dataclass(frozen=True, eq=False)
class Envelope:
    """One outgoing message: deliver ``msg`` to ``dest`` (an address string).

    ``via``, when set, pins the delivery endpoint explicitly instead of
    resolving ``dest`` through the route table — used for replies to peers
    that are not (yet) in any address book, e.g. the Welcome to a joiner.
    Local routers ignore it.

    ``trace``, when set, pins the trace context this message propagates
    (``obs.trace.TraceContext``); when ``None`` the transport stamps the
    CURRENT context at send time — so replies built inside a handler
    inherit the inbound message's round trace without every handler
    knowing tracing exists.

    ``wire``, when set, pins this frame's wire precision ("f32"/"f16"/
    "int8") instead of the transport's configured default — how a
    :class:`~akka_allreduce_tpu.protocol.RoundPolicy` applies per-round
    compression to payload frames without any transport-global state
    (decode is stateless; the mode travels in the frame's count-word
    flags).
    """

    dest: str
    msg: Any
    via: Any = None  # control.cluster.Endpoint | None
    trace: Any = None  # obs.trace.TraceContext | None
    wire: str | None = None  # per-frame wire precision override
