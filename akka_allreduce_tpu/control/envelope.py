"""Addressed messages between control-plane handlers."""

from __future__ import annotations

import dataclasses
from typing import Any

MASTER = "master"


def peer_addr(worker_id: int) -> str:
    return f"worker:{worker_id}"


def master_addr(line_id: int = 0) -> str:
    return f"line_master:{line_id}"


@dataclasses.dataclass(frozen=True, eq=False)
class Envelope:
    """One outgoing message: deliver ``msg`` to ``dest`` (an address string)."""

    dest: str
    msg: Any
