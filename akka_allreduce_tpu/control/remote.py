"""Remote transport: asyncio TCP delivery of control-plane envelopes.

The reference's L0 is Akka remoting — ``ActorSelection ! msg`` serialized by
Netty onto TCP (SURVEY.md §2 L0). This is the same layer, idiomatic Python:
each process runs one ``RemoteTransport`` = one inbound TCP server + a pool of
outbound connections + a single-consumer delivery loop, so every local handler
processes one message at a time (the actor guarantee the reference's buffers
rely on — SURVEY.md §6 "Race detection": actor model, buffers actor-private).

Routing mirrors ``LocalRouter`` (control/local.py) but resolves non-local
addresses to endpoints: exact routes ("master" -> seed) and prefix resolvers
("worker:<id>" -> the owning node's endpoint via the address book). Delivery
is at-most-once: a dead or unknown destination drops the message — exactly the
reference's remoting semantics, and what the threshold design expects
(SURVEY.md §4.2: rounds complete at threshold, never wait for lost messages).

Data plane (zero-copy, both directions):

- **send**: frames are scatter-gather segment lists from
  ``wire.encode_frame_parts`` — the float payload segment is a ``memoryview``
  of the engine's array — handed to ``socket.sendmsg`` (writev), so the
  kernel gathers header + payload with NO user-space concatenation copy.
  Small control frames coalesce into a per-connection buffer flushed on the
  next event-loop pass (or as the prefix of the next big send), so a burst
  of heartbeats/acks costs one syscall, not one each.
- **receive**: a ``BufferedProtocol`` reads each frame body straight into a
  pooled preallocated buffer via the event loop's ``recv_into`` (no
  per-frame ``bytes`` allocation, no readexactly join copy), and the decoded
  float payloads are ``np.frombuffer`` views INTO that buffer. A buffer
  returns to the pool only after the handler has run and no decoded view
  still aliases it (checked via the bytearray export count), so zero-copy
  can never turn into use-after-recycle.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import errno as _errno
import logging
import random
import select
import socket
import struct
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Awaitable, Callable

from akka_allreduce_tpu import native
from akka_allreduce_tpu.config import RetryPolicy
from akka_allreduce_tpu.control import wire
from akka_allreduce_tpu.control.cluster import Endpoint
from akka_allreduce_tpu.control.stripes import StripeScheduler
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics
from akka_allreduce_tpu.obs import trace as _trace
from akka_allreduce_tpu.protocol import ReduceBlock, ScatterBlock

log = logging.getLogger(__name__)

# Silent-loss accounting (OBSERVABILITY.md): every drop path increments a
# registry counter alongside the per-transport ``dropped`` total, so message
# loss is countable per CAUSE across the process. Module-level: counter
# lookups stay off the hot path.
_DROP_UNDECODABLE = _metrics.counter("transport.dropped.undecodable")
_DROP_NO_ROUTE = _metrics.counter("transport.dropped.no_route")
_DROP_NO_HANDLER = _metrics.counter("transport.dropped.no_handler")
_DROP_OVERSIZE = _metrics.counter("transport.dropped.oversize_frame")
_DROP_EMPTY = _metrics.counter("transport.dropped.empty_frame")
_DROP_FILTERED = _metrics.counter("transport.dropped.drop_filter")
_DROP_BACKPRESSURE = _metrics.counter("transport.dropped.backpressure")
_DROP_SEND_FAILED = _metrics.counter("transport.dropped.send_failed")
_DROP_CHAOS = _metrics.counter("transport.dropped.chaos")
_DELIVERED = _metrics.counter("transport.delivered")
_HANDLER_ERRORS = _metrics.counter("transport.handler_errors")
# every reconnect-retry any sender performed in this process (satellite of
# the chaos PR: a flight dump must show WHY a peer was declared dead — the
# per-endpoint detail rides the pull-time collector below)
_RECONNECTS = _metrics.counter("remote.endpoint_reconnects")

Handler = Callable[[Any], list[Envelope]]
PrefixHandler = Callable[[int, Any], list[Envelope]]
_U32 = wire._U32

# Frames at or below this many bytes coalesce into the sender queue's tail
# entry (one small memcpy) instead of costing an iovec slot and a frame entry
# each; payload frames are far above it and always go vectored.
_COALESCE_MAX = 1024

# Size bound of one coalesce entry — a burst larger than this just starts a
# new entry (still one sendmsg, one extra iovec slot).
_COALESCE_ENTRY_MAX = 64 << 10

# Kernel socket buffer request for both directions: payload frames are
# MB-scale, and the kernel buffer is the send pipeline now that frames go
# straight from engine memory to the socket (no user-space staging copy) —
# the default ~208 KB would cost several writability round-trips per frame.
_SOCK_BUF_BYTES = 4 << 20

# Pump-pool sizing cap (DataPlaneConfig.pump_pool = 0 -> auto: streams x
# live endpoints, capped here) — the pool offloads INBOUND decode+checksum
# of state-transfer-scale bodies (>= _DECODE_OFFLOAD_MIN); the SEND side
# never touches it (each payload stream has a dedicated sender thread).
_PUMP_POOL_CAP = 8

# SO_SNDTIMEO slice for the pump-pool's blocking sockets: each syscall
# blocks at most this long, so a worker thread re-checks the sender's
# closed flag (teardown) and its progress deadline at this cadence. The
# OVERALL stall bound stays connect_timeout_s, exactly like the event-loop
# writers' per-writability-wait timeout.
_SEND_SLICE_S = 1.0

# Messages striped across payload streams by chunk id (everything else —
# Prepare/Start/epoch fencing, membership, state transfer — stays on the
# ordering-preserving stream 0).
_STRIPED_TYPES = (ScatterBlock, ReduceBlock)

# Sequence gaps observed on inbound payload streams: a gap means a peer's
# reconnect dropped frames mid-stream (at-most-once absorbs the loss; the
# counter makes it visible per process).
_STREAM_SEQ_GAPS = _metrics.counter("transport.stream_seq_gaps")

# io_uring submission accounting (OBSERVABILITY.md): ring submissions the
# sender threads made, and runtime fallbacks — a kernel that probed fine but
# rejects the first real submit (5.1/5.2) latches the transport back to the
# sendmmsg/sendmsg path and counts it here, once.
_URING_SUBMITS = _metrics.counter("uring.submits")
_URING_FALLBACKS = _metrics.counter("uring.fallbacks")

# Intra-chunk striping accounting: sub-chunk continuation frames sent, whole
# frames reassembled from stripes, and assemblies evicted half-built (a
# sender died mid-frame; bounded by _FRAG_ASM_MAX so a lossy peer cannot
# grow assembly buffers forever).
_FRAGS_SENT = _metrics.counter("transport.frags_sent")
_FRAGS_REASSEMBLED = _metrics.counter("transport.frags_reassembled")
_DROP_FRAG_STALE = _metrics.counter("transport.dropped.frag_stale")

# In-flight fragment assemblies per transport: each holds one pooled
# frame-sized buffer, so the cap bounds memory against a peer whose stripes
# never complete (dead sender mid-frame, sustained loss).
_FRAG_ASM_MAX = 32

# Inbound payload bodies at least this big decode in a pump-pool thread;
# smaller ones decode inline on the event loop. The crossover is where the
# native checksum+frombuffer pass outweighs an executor hop on a CONTENDED
# box (~100µs of queue/wake/GIL): measured on the pair cluster, offloading
# 1-2MB frames (round-payload scale — a 1M-float vector reduce-scattered
# over 2 nodes is a 2MB chunk) lost ~10-20% throughput, so the bar sits at
# state-transfer blob scale, strictly above round payloads.
_DECODE_OFFLOAD_MIN = 4 << 20


def _byte_views(parts) -> list[memoryview]:
    return [
        p if isinstance(p, memoryview) else memoryview(p) for p in parts
    ]


def observed_task(coro, *, name: str) -> asyncio.Task:
    """``create_task`` with a done-callback that logs a crashed task.

    The event loop holds only a weak reference to tasks, and an un-retained
    handle can be garbage-collected mid-flight; worse, a retained-but-never-
    awaited background task (pump, writer, heartbeat ticker) that dies on an
    unexpected exception dies SILENTLY — the transport just stops moving
    messages. Every background spawn in this package goes through here
    (arlint ASYNC003 enforces the shape), so a crash is at least an ERROR
    log with the task's name before the silence. The task is also strongly
    referenced in a module-level set until done — the helper must CLOSE the
    weak-reference hole, not depend on every caller retaining the return
    value."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _observed_tasks.add(task)

    def _done(t: asyncio.Task) -> None:
        _observed_tasks.discard(t)
        if t.cancelled():
            return  # cancellation is the normal teardown path
        exc = t.exception()
        if exc is not None:
            log.error("background task %r died: %r", name, exc)

    task.add_done_callback(_done)
    return task


_observed_tasks: set[asyncio.Task] = set()


# Every live transport's per-instance accounting, folded into REGISTRY
# snapshots by one pull-time collector: the hot paths keep their plain dict
# float-adds, the registry absorbs them only when somebody asks.
_live_transports: "weakref.WeakSet" = weakref.WeakSet()


def _collect_transport_stats() -> dict:
    stages: dict[str, float] = {}
    delivered = dropped = 0
    endpoints: dict[str, dict] = {}

    def _rec(key: str) -> dict:
        return endpoints.setdefault(
            key,
            {
                "reconnects": 0, "backoff_s": 0.0,
                "tx_bytes": 0, "rx_bytes": 0, "stream_count": 0,
            },
        )

    for t in list(_live_transports):
        # list() snapshots throughout: sender THREADS insert keys into
        # these dicts concurrently, and a collector that dies mid-iteration
        # ("dictionary changed size") would silently drop the whole
        # transport stats section from that dump
        for k, v in list(t.stage_seconds.items()):
            stages[k] = stages.get(k, 0.0) + v
        delivered += t.delivered
        dropped += t.dropped
        for ep, n in list(t.endpoint_reconnects.items()):
            rec = _rec(f"{ep.host}:{ep.port}")
            rec["reconnects"] += n
            rec["backoff_s"] = max(
                rec["backoff_s"], t.endpoint_backoff.get(ep, 0.0)
            )
        # bandwidth telemetry (the ROADMAP "feed bandwidth in as evidence"
        # follow-on): bytes moved per peer endpoint plus how many stream
        # connections are live right now (outbound sender sockets, or
        # preamble-identified inbound streams — whichever direction this
        # process has)
        for key, v in list(t.endpoint_tx.items()):
            _rec(key)["tx_bytes"] += v
        for key, v in list(t.endpoint_rx.items()):
            _rec(key)["rx_bytes"] += v
        live_out: dict[str, int] = {}
        for (ep, _stream), snd in list(t._senders.items()):
            if snd.sock is not None:
                k = f"{ep.host}:{ep.port}"
                live_out[k] = live_out.get(k, 0) + 1
        for key, n in live_out.items():
            rec = _rec(key)
            rec["stream_count"] = max(rec["stream_count"], n)
        for key, n in list(t._rx_streams.items()):
            rec = _rec(key)
            rec["stream_count"] = max(rec["stream_count"], n)
    out = {
        f"transport.stage_seconds.{k}": round(v, 6) for k, v in stages.items()
    }
    out["transport.instances"] = len(list(_live_transports))
    out["transport.delivered_live"] = delivered
    out["transport.dropped_live"] = dropped
    # per-endpoint escalation state: how many reconnect-retries this process
    # burned against each peer and the backoff currently in force — the
    # flight-recorder's "why was this peer declared dead" line — plus the
    # bandwidth gauges above
    for key, rec in sorted(endpoints.items()):
        out[f"transport.endpoint.{key}.reconnects"] = rec["reconnects"]
        out[f"transport.endpoint.{key}.backoff_s"] = round(
            rec["backoff_s"], 4
        )
        out[f"transport.endpoint.{key}.tx_bytes"] = rec["tx_bytes"]
        out[f"transport.endpoint.{key}.rx_bytes"] = rec["rx_bytes"]
        out[f"transport.endpoint.{key}.stream_count"] = rec["stream_count"]
    return out


_metrics.REGISTRY.register_collector(_collect_transport_stats)


class _Frame:
    """One queued outbound frame: segments + the envelope(s) it carries.

    Payload-stream frames defer their encode to the pump pool: ``parts``
    stays ``None`` and ``encode_job`` carries ``(env, tctx, mode,
    chaos_act)`` until the worker thread runs the encode + checksum pass
    just before the batch syscall (``nbytes`` is exact anyway —
    ``wire.payload_frame_nbytes`` — so backpressure accounting is charged
    at enqueue time)."""

    __slots__ = (
        "parts", "envs", "nbytes", "coalesced", "inflight", "encode_job",
        "frag",
    )

    def __init__(self, parts: list | None, envs: list, nbytes: int, coalesced: bool) -> None:
        self.parts = parts
        self.envs = envs
        self.nbytes = nbytes
        self.coalesced = coalesced
        # set once the writer exports this frame's buffers into a sendmsg
        # batch: no further merging (a resize with live exports raises
        # BufferError) and no backpressure drop (stream would desync)
        self.inflight = False
        self.encode_job: tuple | None = None
        # intra-chunk stripe: (shared encode, frag_id, total_len, offset,
        # length) — this frame carries bytes [offset, offset+length) of one
        # split payload frame's body behind a continuation header
        self.frag: tuple | None = None


class _SharedEncode:
    """One deferred encode shared by every stripe of a split payload frame.

    The stripes ride DIFFERENT sender threads; whichever reaches its batch
    first runs the encode + checksum (and any chaos corruption — applied to
    the WHOLE frame once, so a corrupt fault hits the reassembled bytes
    exactly as it would an unsplit frame) under the lock, and the rest
    slice the same segment list. The payload views alias the engine's
    memory — splitting adds framing bytes, never a payload copy.

    NB every stripe's _Frame carries the SAME envelope, so per-send
    accounting is per STRIPE for a split frame: on_send_ok fires up to
    nstripes times, a lost stripe counts one drop, and a partially failed
    split can emit both ok and error callbacks for one logical send.
    Today's consumers are type-filtered (the statetransfer repair path
    keys on ChunkData/ReplicaManifest, which never split; the rejoin
    counter keys on master destinations), so the multiplicity on payload
    frames is inert — a future consumer keying per-envelope semantics off
    payload-frame callbacks must dedupe here first."""

    __slots__ = ("lock", "env", "tctx", "mode", "act", "parts")

    def __init__(self, env: Envelope, tctx, mode: str, act) -> None:
        self.lock = threading.Lock()
        self.env = env
        self.tctx = tctx
        self.mode = mode
        self.act = act
        self.parts: list | None = None

    def ensure(self, transport: "RemoteTransport") -> tuple[list, float]:
        """(encoded parts, encode seconds charged to THIS caller — zero
        for every stripe after the first)."""
        with self.lock:
            if self.parts is not None:
                return self.parts, 0.0
            t0 = time.perf_counter()
            parts = wire.encode_frame_parts(
                self.env.dest, self.env.msg, wire=self.mode, trace=self.tctx
            )
            act = self.act
            if act is not None and act.corrupt and transport.chaos is not None:
                parts = transport.chaos.corrupt_frame_parts(parts, act)
            self.parts = parts
            return parts, time.perf_counter() - t0


class _FragAssembly:
    """One split frame mid-reassembly: a pooled frame-sized buffer the
    stripes land in directly (each fragment recv_intos its own byte range
    — no join copy ever happens), plus the received-byte watermark.

    ``seen`` records each counted stripe's offset: a sender's partial-
    batch reconnect RESENDS already-delivered stripes (identical bytes —
    the shared encode is cached), and counting one twice would complete
    the assembly with another stripe's range still unwritten. ``writers``
    counts connections currently in direct-mode recv INTO this buffer, so
    completion never pools a buffer a late duplicate is still writing."""

    __slots__ = ("buf", "total", "got", "seen", "writers")

    def __init__(self, buf: bytearray, total: int) -> None:
        self.buf = buf
        self.total = total
        self.got = 0
        self.seen: set[int] = set()
        self.writers = 0


class _Sender:
    """Per-endpoint outbound state: a frame queue drained by ONE writer task.

    ``send`` enqueues frame segments (zero-copy views of engine memory) and
    returns; the writer connects lazily and drains the queue with
    multi-frame vectored ``sendmsg`` calls — the queue is the pipeline, so
    the pump keeps decoding/handling while the kernel drains bytes. Small
    control frames coalesce into the queue's tail entry (one tiny memcpy)
    instead of costing an iovec slot and a wakeup each.
    """

    __slots__ = (
        "queue", "queued_bytes", "sock", "writer_task", "attempts",
        "waiters", "closed", "stream_id", "seq", "need_preamble",
        "cond", "thread", "uring",
    )

    def __init__(self, stream_id: int = 0) -> None:
        self.queue: "deque[_Frame]" = deque()
        self.queued_bytes = 0
        self.sock: socket.socket | None = None
        self.writer_task: asyncio.Task | None = None
        # payload-stream senders (stream_id >= 1) are drained by a DEDICATED
        # thread, not a loop task: cond guards queue/queued_bytes/inflight
        # across the loop/thread boundary and wakes the thread on enqueue
        self.cond = threading.Condition()
        self.thread: threading.Thread | None = None
        # consecutive failures in the CURRENT burst (connect or send); a
        # burst may consume up to RetryPolicy.max_retries reconnect-resend
        # cycles (exponential backoff + full jitter) before the queue is
        # declared dead. Reset to zero by any successfully sent batch.
        self.attempts = 0
        self.waiters: list[asyncio.Future] = []
        self.closed = False
        # multi-stream state: which stream of the endpoint this sender is
        # (0 = control, >=1 = payload), the next per-stream sequence number
        # (scoped to one connection on the receive side — a reconnect
        # resets the peer's expectation), and whether the next batch must
        # open with the stream preamble (set at connect when streams > 1)
        self.stream_id = stream_id
        self.seq = 0
        self.need_preamble = False
        # io_uring submission ring (DataPlaneConfig.uring): created and
        # closed by the OWNING sender thread — rings are never shared
        self.uring = None

    def close_sock(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:  # pragma: no cover - close is best effort
                pass
            self.sock = None

    def close(self) -> None:
        self.closed = True
        task = self.writer_task
        if (
            task is not None
            and not task.done()
            and task is not asyncio.current_task()
        ):
            task.cancel()
        self.close_sock()
        self.queue.clear()
        self.queued_bytes = 0
        self.wake_waiters()

    def wake_waiters(self) -> None:
        for fut in self.waiters:
            if not fut.done():
                fut.set_result(None)
        self.waiters.clear()


class _FrameReceiver(asyncio.BufferedProtocol):
    """Inbound framing over a preallocated receive ring.

    The event loop ``recv_into``s a fixed ring buffer (no per-frame
    ``bytes``), and every COMPLETE frame in it is parsed per recv — a
    coalesced burst of control frames costs one syscall, not one each.
    Small bodies decode via a tiny copy out of the ring (control messages
    and sub-16KB payloads — the ring is reused, so views must not alias
    it); payload-scale bodies switch the protocol to direct mode, where
    the remainder of the body is received straight into a pooled
    frame-sized buffer and decode hands the engine zero-copy views INTO
    that buffer (recycled only once no view aliases it)."""

    _RING_BYTES = 64 << 10
    # bodies at/below this are served out of the ring (one small memcpy);
    # anything larger gets a dedicated pooled buffer and zero-copy decode
    _SMALL_BODY_MAX = 16 << 10

    def __init__(self, owner: "RemoteTransport") -> None:
        self._owner = owner
        self._ring = bytearray(self._RING_BYTES)
        self._rlen = 0  # valid bytes at the ring's start
        self._body: bytearray | None = None  # direct-mode target buffer
        self._need = 0
        self._got = 0
        # direct mode lands bytes at [base+got, base+need) of _body: base
        # stays 0 for whole frame bodies; a sub-chunk continuation frame
        # (intra-chunk striping) sets it to the fragment's offset in the
        # shared assembly buffer, with _frag_info = (key, assembly,
        # fragment length) so completion can advance the reassembly
        self._body_base = 0
        self._frag_info: tuple | None = None
        self._transport: asyncio.Transport | None = None
        # multi-stream state: the first 4 bytes of a connection decide its
        # framing (STREAM_MAGIC's 0xFFFFFFFF prefix can never be a legal
        # legacy length) — until then the connection is unsniffed
        self._sniffed = False
        self._stream_id = 0  # >=1: payload stream ([u32 len][u32 seq] frames)
        self._peer_key: str | None = None  # telemetry key (host:port)
        self._rx_registered = False
        # rx bytes counted BEFORE the framing sniff resolves the peer's
        # canonical key (a stream preamble may rename the connection)
        self._pending_rx = 0
        # per-connection ordered decode pipeline (streams > 1 only):
        # frames decode in arrival order, but connection A's checksum pass
        # runs in a pump-pool thread while the loop serves connection B.
        # _decode_busy counts frames handed to the queue and not yet
        # delivered — while nonzero, inline decode would overtake them.
        self._decode_q: "asyncio.Queue | None" = None
        self._decode_task: asyncio.Task | None = None
        self._decode_busy = 0

    def connection_made(self, transport) -> None:
        self._transport = transport
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:  # payload frames are MB-scale: a roomy kernel buffer keeps
                # the sender streaming instead of bouncing on EAGAIN
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVBUF, _SOCK_BUF_BYTES
                )
            except OSError:  # pragma: no cover - kernel may clamp/refuse
                pass
        # rx telemetry stays UNKEYED until a stream preamble names the
        # peer's canonical endpoint: the TCP peername port is ephemeral,
        # so keying by it would grow endpoint_rx by one dead entry per
        # inbound connection forever (reconnect churn = unbounded memory
        # and metric cardinality). Legacy connections (streams=1, or
        # pre-Welcome joins) never send a preamble and contribute no
        # per-endpoint rx rows — exactly the pre-round-8 behavior.
        self._owner._server_conns.add(transport)

    def connection_lost(self, exc) -> None:
        self._owner._server_conns.discard(self._transport)
        if self._frag_info is not None:
            # a fragment died mid-direct-recv: release the write claim so
            # the assembly's eventual completion can pool its buffer
            self._frag_info[1].writers -= 1
            self._frag_info = None
            self._body = None
            self._body_base = 0
        if self._rx_registered and self._peer_key is not None:
            n = self._owner._rx_streams.get(self._peer_key, 1) - 1
            if n <= 0:
                self._owner._rx_streams.pop(self._peer_key, None)
            else:
                self._owner._rx_streams[self._peer_key] = n
            self._rx_registered = False
        if self._decode_q is not None:
            self._decode_q.put_nowait(None)  # drain, then end the pump

    def eof_received(self) -> bool:
        return False  # close the transport; at-most-once, nothing to recover

    def get_buffer(self, sizehint: int) -> memoryview:
        if self._body is not None:
            base = self._body_base
            return memoryview(self._body)[base + self._got : base + self._need]
        # the BufferedProtocol contract REQUIRES handing out this view: the
        # event loop recv_intos it and reports back via buffer_updated before
        # the ring is ever parsed or compacted, so the view cannot outlive a
        # recycle — and decoded messages never alias the ring (small bodies
        # are copied out, large ones land in pooled per-frame buffers)
        return memoryview(self._ring)[self._rlen :]  # arlint: disable=BUF001

    def buffer_updated(self, nbytes: int) -> None:
        owner = self._owner
        if self._sniffed:
            owner._note_rx(self._peer_key, nbytes)
        else:
            # held until the framing sniff lands on this connection's
            # canonical telemetry key (the preamble may rename it)
            self._pending_rx += nbytes
        if self._body is not None:  # direct mode: body lands in its buffer
            self._got += nbytes
            if self._got < self._need:
                return
            body, need = self._body, self._need
            frag = self._frag_info
            self._body = None
            self._body_base = 0
            self._frag_info = None
            if frag is not None:
                # one stripe of a split frame finished: advance the shared
                # assembly; the whole frame delivers when the last stripe
                # (whichever stream it rode) completes the byte count
                key, rec, offset, frag_len = frag
                rec.writers -= 1
                owner._frag_advance(self, key, rec, offset, frag_len)
                return
            self._deliver(body, need, pooled=body)
            return
        self._rlen += nbytes
        ring = self._ring
        pos = 0
        if not self._sniffed:
            if self._rlen < 4:
                return
            if ring[:4] != b"\xff\xff\xff\xff":
                self._sniffed = True  # legacy framing, no preamble
                owner._note_rx(self._peer_key, self._pending_rx)
                self._pending_rx = 0
            else:
                try:
                    res = wire.parse_stream_preamble(
                        memoryview(ring)[: self._rlen]
                    )
                except ValueError:
                    log.warning("bad stream preamble; closing connection")
                    owner.dropped += 1
                    _DROP_UNDECODABLE.inc()
                    assert self._transport is not None
                    self._transport.close()
                    return
                if res is None:
                    return  # preamble incomplete: wait for more bytes
                stream_id, _total, host, port, consumed = res
                self._sniffed = True
                self._stream_id = stream_id
                self._peer_key = f"{host}:{port}"
                owner._note_rx(self._peer_key, self._pending_rx)
                self._pending_rx = 0
                owner._rx_streams[self._peer_key] = (
                    owner._rx_streams.get(self._peer_key, 0) + 1
                )
                self._rx_registered = True
                pos = consumed
        hdr = 8 if self._stream_id >= 1 else 4
        while True:
            avail = self._rlen - pos
            if avail < hdr:
                break
            (length,) = _U32.unpack_from(ring, pos)
            if length > owner.max_frame_bytes:
                # a corrupt/hostile length prefix must not make us buffer
                # gigabytes; drop the connection (the peer's framing is
                # gone — nothing after this parses)
                log.warning(
                    "frame length %d exceeds limit %d; closing connection",
                    length,
                    owner.max_frame_bytes,
                )
                owner.dropped += 1
                _DROP_OVERSIZE.inc()
                assert self._transport is not None
                self._transport.close()
                return
            # NB the seq check must run exactly ONCE per frame — only on
            # the paths that CONSUME the header. An incomplete small body
            # breaks out with pos unmoved, so its header is re-parsed on
            # the next recv: checking here would advance the expectation
            # twice and count a bogus gap for a frame that merely straddled
            # a TCP read boundary.
            if length == 0:
                if hdr == 8:
                    self._check_seq(_U32.unpack_from(ring, pos + 4)[0])
                owner.dropped += 1  # vacuous frame: nothing to decode
                _DROP_EMPTY.inc()
                pos += hdr
                continue
            if self._stream_id >= 1 and length >= 2 and avail < hdr + 2:
                # a payload-stream body's first two bytes decide its shape
                # (0xFFFF = sub-chunk continuation, anything else a whole
                # frame's dest-length prefix) — never enter direct mode
                # before the peek, or a fragment's bytes would land in a
                # whole-frame buffer and decode as garbage
                break
            if (
                self._stream_id >= 1
                # a real continuation frame is always longer than its
                # header — the bound also keeps the 2-byte peek inside
                # the guard above (a length-1 body would otherwise read
                # one byte past what this frame owns)
                and length > wire.FRAG_HDR_LEN
                and ring[pos + hdr] == 0xFF
                and ring[pos + hdr + 1] == 0xFF
            ):
                nxt = self._begin_fragment(ring, pos, avail, hdr, length)
                if nxt == -2:
                    return  # protocol error: connection closed
                if nxt == -1:
                    break  # continuation header straddles the recv: wait
                pos = nxt
                if self._body is not None:
                    break  # fragment tail arrives in direct mode
                continue
            if length > self._SMALL_BODY_MAX:
                if hdr == 8:
                    self._check_seq(_U32.unpack_from(ring, pos + 4)[0])
                body = owner._acquire_recv_buf(length)
                got = min(avail - hdr, length)
                body[:got] = memoryview(ring)[pos + hdr : pos + hdr + got]
                pos += hdr + got
                if got == length:  # whole body was already buffered
                    self._deliver(body, length, pooled=body)
                    continue
                # switch to direct mode: the rest of the body is received
                # straight into the frame buffer — by construction nothing
                # can follow an incomplete body in the ring
                self._body, self._need, self._got = body, length, got
                break
            if avail - hdr < length:
                break  # incomplete small body: wait for more bytes
            if hdr == 8:
                self._check_seq(_U32.unpack_from(ring, pos + 4)[0])
            # small frame fully buffered: decode via a tiny copy so its
            # decoded views can never alias the (reused) ring
            frame = bytes(memoryview(ring)[pos + hdr : pos + hdr + length])
            pos += hdr + length
            self._deliver(frame, length, pooled=None)
        if pos:  # compact the unconsumed tail to the ring's start
            rest = self._rlen - pos
            if rest:
                ring[:rest] = ring[pos : self._rlen]
            self._rlen = rest

    def _check_seq(self, seq: int) -> None:
        """Per-stream sequence discipline. The expectation lives on the
        OWNER keyed by (peer endpoint, stream id) so it SURVIVES
        reconnects — within one TCP connection a gap is impossible
        (ordered byte stream), so per-connection state would be
        structurally blind to the only loss that can happen: a sender
        whose retry budget died mid-queue is rebuilt with seq=0, and a
        partial-batch reconnect re-stamps its resent frames. Either way
        the cross-connection discontinuity is counted (at-most-once
        absorbs the loss/duplication; the counter makes the disruption
        visible), then the expectation resynchronizes."""
        key = (self._peer_key, self._stream_id)
        expect = self._owner._rx_seq_expect.get(key)
        if expect is not None and seq != expect:
            _STREAM_SEQ_GAPS.inc()
            log.warning(
                "stream %d from %s: sequence discontinuity "
                "(expected %d, got %d)",
                self._stream_id, self._peer_key, expect, seq,
            )
        self._owner._rx_seq_expect[key] = (seq + 1) & 0xFFFF_FFFF

    def _begin_fragment(
        self, ring: bytearray, pos: int, avail: int, hdr: int, length: int
    ) -> int:
        """Consume one sub-chunk continuation frame's header + whatever of
        its bytes the ring already holds, landing them at the fragment's
        offset in the shared assembly buffer. Returns the new parse
        position; -1 = header incomplete (wait for more bytes, nothing
        consumed); -2 = protocol error, connection closed. Leaves the
        connection in direct mode (``_body`` set) when the fragment's tail
        is still in flight."""
        owner = self._owner
        if avail - hdr < wire.FRAG_HDR_LEN:
            return -1
        try:
            if length <= wire.FRAG_HDR_LEN:
                raise ValueError(f"continuation frame of {length} bytes")
            frag_id, total, offset = wire.parse_frag_header(
                memoryview(ring)[pos + hdr : pos + hdr + wire.FRAG_HDR_LEN]
            )
            frag_len = length - wire.FRAG_HDR_LEN
            if offset + frag_len > total:
                raise ValueError("fragment overruns its frame body")
            if total > owner.max_frame_bytes:
                raise ValueError(f"reassembled frame of {total} bytes")
        except ValueError as exc:
            # a malformed continuation header means this stream's framing
            # can no longer be trusted (an offset lie would corrupt a
            # shared assembly buffer): drop the connection, like oversize
            log.warning("bad continuation frame (%s); closing connection", exc)
            owner.dropped += 1
            _DROP_UNDECODABLE.inc()
            assert self._transport is not None
            self._transport.close()
            return -2
        self._check_seq(_U32.unpack_from(ring, pos + 4)[0])
        rec = owner._frag_get((self._peer_key, frag_id), total)
        if rec is None:
            log.warning(
                "continuation frame total mismatch from %s; closing",
                self._peer_key,
            )
            owner.dropped += 1
            _DROP_UNDECODABLE.inc()
            assert self._transport is not None
            self._transport.close()
            return -2
        body_off = pos + hdr + wire.FRAG_HDR_LEN
        got = min(avail - hdr - wire.FRAG_HDR_LEN, frag_len)
        if got:
            rec.buf[offset : offset + got] = memoryview(ring)[
                body_off : body_off + got
            ]
        if got == frag_len:
            owner._frag_advance(
                self, (self._peer_key, frag_id), rec, offset, frag_len
            )
            return body_off + got
        # direct mode into the assembly buffer at the fragment's remaining
        # range — by construction nothing can follow an incomplete body
        self._body = rec.buf
        self._body_base = offset
        self._got = got
        self._need = frag_len
        rec.writers += 1
        self._frag_info = ((self._peer_key, frag_id), rec, offset, frag_len)
        return body_off + got

    def _deliver(self, buf, need: int, *, pooled: bytearray | None) -> None:
        owner = self._owner
        if (
            owner._pool_enabled()
            and (need >= _DECODE_OFFLOAD_MIN or self._decode_busy)
        ):
            # body big enough that the checksum pass beats the thread-hop
            # cost: decode in a pump-pool thread via the connection's
            # ordered queue. This includes STREAM 0 — state-transfer
            # chunks (the >=4MB bodies the pool exists for) ride the
            # control stream, and the per-connection FIFO queue below
            # preserves its ordering guarantees: frames decode strictly
            # in arrival order, only on another thread. Smaller frames
            # decode inline on the loop (measured: at ~1MB frames on a
            # contended box the executor hop LOSES to the native checksum
            # it offloads) — UNLESS an offloaded decode is still in
            # flight, in which case they queue behind it so the
            # connection never reorders. streams=1 never offloads (the
            # pool is off), keeping the legacy plane byte- and
            # behavior-identical.
            if self._decode_q is None:
                self._decode_q = asyncio.Queue()
                # lifecycle is owner-transferred, not protocol-owned: the
                # task joins owner._decoder_tasks (cancelled in the
                # transport's stop()) and connection_lost() ends the pump
                # by queueing the None sentinel
                self._decode_task = observed_task(  # arlint: disable=LIFE001 -- owner-transferred
                    owner._decode_pump(self._decode_q, self),
                    name=f"decode-{self._peer_key}-s{self._stream_id}",
                )
                owner._decoder_tasks.add(self._decode_task)
                self._decode_task.add_done_callback(
                    owner._decoder_tasks.discard
                )
            self._decode_busy += 1
            self._decode_q.put_nowait((buf, need, pooled))
            return
        try:
            dest, msg, tctx = owner._decode_timed(buf, need)
        except Exception as exc:  # malformed body: drop THIS frame
            # framing is length-prefixed, so the stream stays in sync —
            # one bad message must not kill the connection
            log.warning("undecodable frame (%s); dropping", exc)
            owner.dropped += 1
            _DROP_UNDECODABLE.inc()
            if pooled is not None:
                owner._release_recv_buf(pooled)
            return
        owner._inbox.put_nowait((dest, msg, pooled, tctx))


class RemoteTransport:
    """One process's transport: local handlers + remote routes."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        connect_timeout_s: float = 5.0,
    ) -> None:
        self._host = host
        self._port = port
        self.connect_timeout_s = connect_timeout_s
        self._server: asyncio.Server | None = None
        self._handlers: dict[str, Handler] = {}
        self._prefix_handlers: dict[str, PrefixHandler] = {}
        self._routes: dict[str, Endpoint] = {}
        self._prefix_routes: dict[str, Callable[[int], Endpoint | None]] = {}
        # one sender per (endpoint, stream): stream 0 is the legacy control
        # connection, streams 1..N-1 the payload stripes
        self._senders: dict[tuple[Endpoint, int], _Sender] = {}
        self._server_conns: set = set()
        self._inbox: asyncio.Queue[
            tuple[str, Any, bytearray | None]
        ] = asyncio.Queue()
        self._pump: asyncio.Task | None = None
        self._recv_pool: list[bytearray] = []
        self.delivered = 0
        self.dropped = 0
        self.on_send_error: Callable[[Endpoint, Envelope], None] | None = None
        # called after a frame reaches the socket buffer — lets callers treat
        # failure counts as CONSECUTIVE (reset on success) rather than
        # cumulative-since-forever
        self.on_send_ok: Callable[[Endpoint, Envelope], None] | None = None
        # fault injection (the reference tests by omitting messages,
        # SURVEY.md §5): return True to swallow an outgoing envelope
        self.drop_filter: Callable[[Envelope], bool] | None = None
        # the chaos hook point (control/chaos.py): when set, every envelope
        # headed to the wire is offered to plan_send and the returned
        # ChaosAction is applied (drop/fail/delay/duplicate/corrupt)
        self.chaos = None  # control.chaos.ChaosInjector | None
        # send-retry escalation (config.RetryPolicy): reconnect budget and
        # backoff shape per failure burst, distributed via Welcome
        self.retry_policy = RetryPolicy()
        # per-endpoint escalation bookkeeping, exported by the pull-time
        # collector so flight dumps show why a peer was declared dead
        self.endpoint_reconnects: dict[Endpoint, int] = {}
        self.endpoint_backoff: dict[Endpoint, float] = {}
        self._chaos_tasks: set[asyncio.Task] = set()
        self._stopped = False
        # wire compression (MetaDataConfig.wire_dtype == "f16"): float
        # payloads cross the socket at half width; local deliveries and the
        # decode side are unaffected (the flag travels in the frame)
        self.wire_f16 = False
        # multi-stream data plane (DataPlaneConfig, distributed via Welcome
        # like every section): sockets per peer endpoint. 1 = the legacy
        # single-connection wire, byte for byte; > 1 stripes payload frames
        # across streams 1..N-1 by chunk id and shards their encode/
        # checksum/sendmmsg (and inbound decode) into the pump pool.
        self.streams = 1
        self.pump_pool_size = 0  # 0 = auto (streams x endpoints, capped)
        # data plane v3 levers (DataPlaneConfig, BENCHMARKS.md round 9),
        # each defaulting OFF so a legacy config negotiates them down:
        # io_uring burst submission in the sender threads (runtime-probed;
        # _uring_off latches after the first kernel refusal), intra-chunk
        # striping of payload frames at/above the byte bar, and the
        # congestion-aware stripe scheduler (control/stripes.py)
        self.uring = False
        self.intra_chunk_min_bytes = 0
        self.congestion = False
        self._uring_off = False
        self._stripe_sched: dict[Endpoint, StripeScheduler] = {}
        # in-flight sub-chunk reassemblies, keyed (peer key, frag id) —
        # loop-only (the receive path is loop-only), bounded by
        # _FRAG_ASM_MAX
        self._frag_asm: dict[tuple, _FragAssembly] = {}
        self._next_frag_id = 0
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        # the loop the transport runs on, captured at first stream send —
        # sender threads post their loop-side callbacks through it
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stats_lock = threading.Lock()
        self._decoder_tasks: set[asyncio.Task] = set()
        # per-endpoint bandwidth telemetry (OBSERVABILITY.md): bytes moved
        # to/from each peer, exported by the pull-time collector as
        # transport.endpoint.<host:port>.tx_bytes/rx_bytes/stream_count
        self.endpoint_tx: dict[str, int] = {}
        self.endpoint_rx: dict[str, int] = {}
        self._rx_streams: dict[str, int] = {}
        # per-(peer endpoint, stream) inbound sequence expectation — on the
        # transport, NOT the connection, so it survives reconnects (see
        # _FrameReceiver._check_seq). Bounded by peers x streams; only the
        # event loop touches it (the receive path is loop-only).
        self._rx_seq_expect: dict[tuple[str | None, int], int] = {}
        # per-stage wall-time accounting (VERDICT r3 #8): where a node's
        # protocol budget goes — codec vs socket vs engine. Two
        # perf_counter calls per message per stage on >=KB-scale frames;
        # noise next to the work being measured.
        self.stage_seconds: dict[str, float] = {
            "encode": 0.0,  # wire.encode_frame_parts (+ checksum pass)
            "socket_write": 0.0,  # connect + vectored sendmsg + coalesce flush
            "decode": 0.0,  # wire.decode_frame_body (views into recv buffer)
            "handler": 0.0,  # engine: buffer store/reduce + replies built
        }
        # the registry sees this transport's stage/drop totals at snapshot
        # time (pull-model collector — zero registry writes on the hot path)
        _live_transports.add(self)

    def configure_data_plane(self, dp) -> None:
        """Adopt a ``DataPlaneConfig`` (ctor / Welcome / standby takeover
        — every site must arm the same knobs, so there is ONE of these):
        stream count, pump pool, and the three v3 levers. A config from an
        older master simply lacks the new fields' section and lands on the
        defaults — every lever negotiates down."""
        self.streams = dp.streams
        self.pump_pool_size = dp.pump_pool
        self.uring = dp.uring
        self.intra_chunk_min_bytes = dp.intra_chunk_min_bytes
        self.congestion = dp.congestion

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> Endpoint:
        self._stopped = False
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            lambda: _FrameReceiver(self), self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]
        self._pump = observed_task(self._pump_inbox(), name="transport-pump")
        return self.endpoint

    @property
    def endpoint(self) -> Endpoint:
        return Endpoint(self._host, self._port)

    async def stop(self) -> None:
        self._stopped = True
        # held (delayed) chaos frames must not re-open senders mid-teardown
        for task in list(self._chaos_tasks):
            task.cancel()
        self._chaos_tasks.clear()
        if self._server is not None:
            self._server.close()
        # close accepted connections BEFORE wait_closed: on Python >= 3.12
        # wait_closed waits for them
        for transport in list(self._server_conns):
            transport.close()
        self._server_conns.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        if self._pump is not None:
            # re-cancel until the task actually ends: a wait_for inside the
            # pump's write path (connect) can eat one cancellation on
            # Python < 3.12 when its future completes in the same tick
            while not self._pump.done():
                self._pump.cancel()
                await asyncio.wait([self._pump], timeout=1.0)
            self._pump = None
        writers = [
            s.writer_task
            for s in self._senders.values()
            if s.writer_task is not None and not s.writer_task.done()
        ]
        if writers:
            # bounded courtesy drain BEFORE teardown: send() returns at
            # enqueue time, so a goodbye frame (LeaveCluster) may still sit
            # in a sender queue — give the writers one timeout window to
            # flush it; a stalled peer is already bounded by their own waits
            await asyncio.wait(writers, timeout=self.connect_timeout_s)
        for task in list(self._decoder_tasks):
            task.cancel()
        self._decoder_tasks.clear()
        # teardown ordering for the data-plane threads: flag senders closed
        # under their conds (sender threads observe it at the next wait
        # wakeup or SO_SNDTIMEO slice), cancel loop-task writers, JOIN the
        # threads and the pool's in-flight decode jobs, and only then close
        # the sockets — closing an fd a thread still has in a syscall could
        # hand its number to an unrelated new socket
        for sender in self._senders.values():
            with sender.cond:
                sender.closed = True
                sender.cond.notify_all()
            task = sender.writer_task
            if (
                task is not None
                and not task.done()
                and task is not asyncio.current_task()
            ):
                task.cancel()
            sender.wake_waiters()
        loop = asyncio.get_running_loop()
        threads = [
            s.thread
            for s in self._senders.values()
            if s.thread is not None and s.thread.is_alive()
        ]
        for thread in threads:
            await loop.run_in_executor(
                None, thread.join, self.connect_timeout_s + 2 * _SEND_SLICE_S
            )
        if self._pool is not None:
            pool, self._pool = self._pool, None
            await loop.run_in_executor(None, pool.shutdown)
        for sender in self._senders.values():
            sender.close()
        if writers:
            await asyncio.gather(*writers, return_exceptions=True)
        self._senders.clear()
        self._recv_pool.clear()
        self._frag_asm.clear()
        self._stripe_sched.clear()

    # -- receive-buffer pool ----------------------------------------------------

    # Bound on pooled buffers (count and per-buffer bytes): payload frames at
    # the benchmark scale are ~1-4 MB, so a handful of retained buffers serve
    # a steady stream without per-frame allocation; anything larger is given
    # back to the allocator.
    _recv_pool_max = 8
    _recv_buf_max = 16 << 20

    def _acquire_recv_buf(self, length: int) -> bytearray:
        pool = self._recv_pool
        best = -1
        for i, b in enumerate(pool):
            if len(b) >= length and (best < 0 or len(b) < len(pool[best])):
                best = i
        if best >= 0:
            return pool.pop(best)
        return bytearray(length)

    def _release_recv_buf(self, buf: bytearray) -> None:
        if (
            len(self._recv_pool) >= self._recv_pool_max
            or len(buf) > self._recv_buf_max
        ):
            return
        try:
            # a bytearray with live buffer exports refuses to resize — the
            # exact guard we need: if any decoded view still aliases this
            # buffer (a handler kept the payload), recycling would corrupt
            # it, so the buffer is simply dropped instead of pooled
            last = buf.pop()
        except (BufferError, IndexError):
            return
        buf.append(last)
        self._recv_pool.append(buf)

    # -- sub-chunk reassembly (intra-chunk striping) -----------------------------

    def _frag_get(self, key: tuple, total: int) -> _FragAssembly | None:
        """The assembly record for split frame ``key``, created on the
        first stripe (one pooled frame-sized buffer every later stripe
        lands in directly). None = the peer re-used a frag id with a
        different total — a protocol error the caller treats like a bad
        length prefix."""
        rec = self._frag_asm.get(key)
        if rec is not None:
            return rec if rec.total == total else None
        while len(self._frag_asm) >= _FRAG_ASM_MAX:
            # bound memory against stripes that will never complete (a
            # sender dead-lettered mid-frame): evict the OLDEST assembly —
            # at-most-once absorbs the loss, the counter makes it visible.
            # The buffer is DROPPED, never pooled: a connection may still
            # be mid-recv into it (direct mode), and pooling it would hand
            # the same bytearray to a second assembly — two writers, one
            # buffer. The GC reclaims it once the last writer lets go.
            self._frag_asm.pop(next(iter(self._frag_asm)))
            self.dropped += 1
            _DROP_FRAG_STALE.inc()
        rec = _FragAssembly(self._acquire_recv_buf(total), total)
        self._frag_asm[key] = rec
        return rec

    def _frag_advance(
        self, conn: "_FrameReceiver", key: tuple, rec: _FragAssembly,
        offset: int, frag_len: int,
    ) -> None:
        """One stripe of ``key`` fully landed: deliver the reassembled
        frame once every body byte has (whichever stream carried the last
        stripe delivers — stripe arrival order is free)."""
        if offset in rec.seen:
            return  # duplicate stripe (sender reconnect resend): the
            # rewrite was byte-identical, the count must not move
        rec.seen.add(offset)
        rec.got += frag_len
        if rec.got < rec.total:
            return
        # identity-guarded pop: ``rec`` may have been cap-evicted and its
        # key since reused by a NEWER assembly — completing the orphan
        # must not tear the replacement out of the table (the orphan's
        # data is complete and correct, so it still delivers; a duplicate
        # of the frame is at-most-once's bread and butter)
        if self._frag_asm.get(key) is rec:
            self._frag_asm.pop(key)
        _FRAGS_REASSEMBLED.inc()
        # pool the buffer only when NO connection is still direct-recving
        # into it (a late duplicate stripe): pooling under a live writer
        # would hand the next inbound frame a buffer that stripe keeps
        # scribbling on
        conn._deliver(
            rec.buf, rec.total,
            pooled=rec.buf if rec.writers == 0 else None,
        )

    # -- per-endpoint telemetry lifecycle ----------------------------------------

    def forget_endpoint(self, ep: Endpoint) -> None:
        """Evict every per-endpoint accounting row for ``ep`` — called when
        MEMBERSHIP expels the peer, so the registry snapshot stops carrying
        dead ``transport.endpoint.<host:port>.*`` rows forever (they are
        otherwise cumulative: before this hook the adapt controller's
        bandwidth arm had to special-case frozen rows as permanent
        straggler pressure). A peer that re-joins regrows its rows from
        zero, which is also the honest reading of a fresh process.

        The peer's senders close too — an expelled endpoint is one this
        process has stopped dialing, and a live sender would re-seed the
        collector's row (its stream_count gauge) on the next snapshot.
        Queued frames are DEAD-LETTERED, never silently cleared: the
        at-most-once error callback per envelope is what lets higher
        layers repair themselves (the state-transfer push dedup un-marks
        a lost ChunkData on ``on_send_error`` and re-pushes next lap — a
        silent drop here once wedged replication for a whole run when a
        transient phi flap shrank the address book)."""
        log.info("evicting endpoint %s (telemetry rows + senders)", ep)
        for skey in [k for k in self._senders if k[0] == ep]:
            snd = self._senders.pop(skey)
            if snd.thread is not None:
                # the sender THREAD owns its socket and its queue: flag it
                # closed and let the thread dead-letter the leftovers and
                # close the fd on its way out (draining from here would
                # race the thread's in-flight batch bookkeeping, and
                # closing the fd could yank it mid-syscall)
                with snd.cond:
                    snd.closed = True
                    snd.cond.notify_all()
                continue
            # loop-task sender: cancel the writer FIRST (it is parked at an
            # await and cannot resume before this method returns, so the
            # cancellation lands at its await point — never inside its
            # post-send queue bookkeeping), then _fail_sender drains with
            # the full at-most-once accounting
            task = snd.writer_task
            if task is not None and not task.done():
                task.cancel()
            snd.closed = True
            self._fail_sender(ep, snd, OSError("endpoint evicted"))
        key = f"{ep.host}:{ep.port}"
        with self._stats_lock:
            self.endpoint_tx.pop(key, None)
            self.endpoint_rx.pop(key, None)
            self.endpoint_reconnects.pop(ep, None)
            self.endpoint_backoff.pop(ep, None)
        # loop-only structures (the receive path and the scheduler map are
        # owned by the event loop this runs on)
        self._rx_streams.pop(key, None)
        self._stripe_sched.pop(ep, None)
        for k in [k for k in self._rx_seq_expect if k[0] == key]:
            del self._rx_seq_expect[k]
        for k in [k for k in self._frag_asm if k[0] == key]:
            # dropped, never pooled: a connection may still be mid-recv
            # into the assembly (see _frag_get's eviction note)
            self._frag_asm.pop(k)

    # -- pump pool (multi-stream data plane) ------------------------------------

    def _pool_enabled(self) -> bool:
        return self.streams > 1

    def _executor(self) -> concurrent.futures.ThreadPoolExecutor:
        """The transport's pump pool, created lazily at first payload-stream
        use: sized streams x live endpoints (capped) unless pinned by
        DataPlaneConfig.pump_pool."""
        pool = self._pool
        if pool is None:
            eps = {k[0] for k in self._senders} | set(self._routes.values())
            size = self.pump_pool_size or min(
                _PUMP_POOL_CAP, max(2, self.streams * max(1, len(eps)))
            )
            pool = self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=size, thread_name_prefix="aw-pump"
            )
        return pool

    def _note_rx(self, key: str | None, nbytes: int) -> None:
        if key is not None:
            self.endpoint_rx[key] = self.endpoint_rx.get(key, 0) + nbytes

    def _decode_timed(self, buf, need: int):
        """One frame body -> (dest, msg, tctx), with the decode stage timer
        charged under the stats lock (this runs on the event loop for
        legacy connections and in pump-pool threads for payload streams)."""
        t0 = time.perf_counter()
        out = wire.decode_frame_body_ex(memoryview(buf)[:need])
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self.stage_seconds["decode"] += dt
        _flight.set_state("transport.last_stage", "decode")
        return out

    async def _decode_pump(
        self, q: asyncio.Queue, conn: "_FrameReceiver"
    ) -> None:
        """Per-connection ordered decode: frames of ONE connection decode
        strictly in arrival order (so stream 0 keeps its FIFO contract and
        a payload stream's sequence stays meaningful), but offload-scale
        checksum/frombuffer work runs in a pump-pool thread — connection
        A's decode overlaps connection B's handler. Sub-threshold frames
        land here only when queued behind an in-flight offload (ordering),
        and decode inline."""
        loop = asyncio.get_running_loop()
        while True:
            item = await q.get()
            if item is None:
                return  # connection closed and queue drained
            buf, need, pooled = item
            try:
                if need >= _DECODE_OFFLOAD_MIN:
                    dest, msg, tctx = await loop.run_in_executor(
                        self._executor(), self._decode_timed, buf, need
                    )
                else:
                    dest, msg, tctx = self._decode_timed(buf, need)
            except asyncio.CancelledError:
                raise  # transport teardown, not a bad frame
            except Exception as exc:
                log.warning("undecodable frame (%s); dropping", exc)
                self.dropped += 1
                _DROP_UNDECODABLE.inc()
                if pooled is not None:
                    self._release_recv_buf(pooled)
                continue
            finally:
                conn._decode_busy -= 1
            self._inbox.put_nowait((dest, msg, pooled, tctx))

    # -- registration / routing -------------------------------------------------

    def register(self, addr: str, handler: Handler) -> None:
        self._handlers[addr] = handler

    def register_prefix(self, prefix: str, handler: PrefixHandler) -> None:
        self._prefix_handlers[prefix] = handler

    def set_route(self, addr: str, endpoint: Endpoint) -> None:
        self._routes[addr] = endpoint

    def set_prefix_route(
        self, prefix: str, resolver: Callable[[int], Endpoint | None]
    ) -> None:
        self._prefix_routes[prefix] = resolver

    def _local_handler(self, dest: str) -> Callable[[Any], list[Envelope]] | None:
        handler = self._handlers.get(dest)
        if handler is not None:
            return handler
        prefix, _, suffix = dest.rpartition(":")
        ph = self._prefix_handlers.get(prefix)
        if ph is not None and suffix.lstrip("-").isdigit():
            return lambda m, _ph=ph, _id=int(suffix): _ph(_id, m)
        return None

    def _resolve(self, dest: str) -> Endpoint | None:
        ep = self._routes.get(dest)
        if ep is not None:
            return ep
        prefix, _, suffix = dest.rpartition(":")
        resolver = self._prefix_routes.get(prefix)
        if resolver is not None and suffix.lstrip("-").isdigit():
            return resolver(int(suffix))
        return None

    # -- sending -----------------------------------------------------------------

    async def send(self, env: Envelope) -> None:
        if self.drop_filter is not None and self.drop_filter(env):
            self.dropped += 1
            _DROP_FILTERED.inc()
            return
        # the round trace rides every hop: an explicit envelope context
        # wins, otherwise the CURRENT context (set by the pump around the
        # handler that built this reply) propagates
        tctx = env.trace if env.trace is not None else _trace.current()
        if env.via is None:
            handler = self._local_handler(env.dest)
            if handler is not None:  # local delivery: no wire, same FIFO inbox
                await self._inbox.put((env.dest, env.msg, None, tctx))
                return
        chaos = self.chaos
        if chaos is not None:
            act = chaos.plan_send(env)
            if act is not None:
                await self._apply_chaos(env, tctx, act)
                return
        await self._send_wire(env, tctx)

    async def _apply_chaos(self, env: Envelope, tctx, act) -> None:
        """Mechanics for a ChaosAction (control/chaos.py) on this envelope."""
        if act.drop or act.fail:
            self.dropped += 1
            _DROP_CHAOS.inc()
            if act.fail and self.on_send_error is not None:
                # partition semantics: the loss is OBSERVABLE, like a refused
                # connection — failure counting (and thus rejoin-on-heal)
                # must see it, unlike the silent packet-loss `drop`. An
                # unroutable dest gets no callback, matching the normal
                # no-route drop (the callback contract promises an Endpoint)
                ep = env.via if env.via is not None else self._resolve(env.dest)
                if ep is not None:
                    self.on_send_error(ep, env)
            return
        if act.delay_s > 0:
            # hold the frame WITHOUT blocking the caller: later sends to the
            # same peer overtake it, so delay doubles as reordering pressure
            task = observed_task(
                self._chaos_delayed(env, tctx, act), name="chaos-delay"
            )
            self._chaos_tasks.add(task)
            task.add_done_callback(self._chaos_tasks.discard)
            return
        await self._send_wire(env, tctx, chaos_act=act)
        if act.duplicate:
            await self._send_wire(env, tctx)

    async def _chaos_delayed(self, env: Envelope, tctx, act) -> None:
        await asyncio.sleep(act.delay_s)
        if self._stopped:
            return
        await self._send_wire(env, tctx, chaos_act=act)
        if act.duplicate:
            await self._send_wire(env, tctx)

    def _pick_stream(self, ep: Endpoint, env: Envelope, nbytes: int) -> int:
        """Which payload stream of ``ep`` carries this frame: by chunk id
        (deterministic — a chaos-delayed resend of the same chunk rides
        the same stream), or through the endpoint's congestion-aware
        :class:`StripeScheduler` when the lever is on — a persistently
        slow stream then sheds assignment weight instead of gating every
        round that owns a chunk on it."""
        n_payload = self.streams - 1
        if self.congestion and n_payload > 1:
            sched = self._stripe_sched.get(ep)
            if sched is None:
                sched = self._stripe_sched[ep] = StripeScheduler(n_payload)
            return 1 + sched.pick(nbytes, time.monotonic())
        return 1 + (env.msg.chunk_id % n_payload)

    async def _send_wire(self, env: Envelope, tctx, *, chaos_act=None) -> None:
        if self._stopped:
            return  # a held chaos frame outlived the transport
        ep = env.via if env.via is not None else self._resolve(env.dest)
        if ep is None:
            log.warning("no route for %s; dropping", env.dest)
            self.dropped += 1
            _DROP_NO_ROUTE.inc()
            return
        if self.streams > 1 and type(env.msg) in _STRIPED_TYPES:
            await self._send_wire_payload(env, tctx, ep, chaos_act)
            return
        t0 = time.perf_counter()
        parts = wire.encode_frame_parts(
            env.dest, env.msg, f16=self.wire_f16, wire=env.wire, trace=tctx
        )
        if chaos_act is not None and chaos_act.corrupt:
            parts = self.chaos.corrupt_frame_parts(parts, chaos_act)
        with self._stats_lock:
            self.stage_seconds["encode"] += time.perf_counter() - t0
        _flight.set_state("transport.last_stage", "encode")
        sender = self._senders.get((ep, 0))
        if sender is None or sender.closed:
            sender = self._senders[(ep, 0)] = _Sender()
        nbytes = sum(len(p) for p in parts)
        tail = sender.queue[-1] if sender.queue else None
        if (
            nbytes <= _COALESCE_MAX
            and tail is not None
            and tail.coalesced
            and not tail.inflight
            and tail.nbytes + nbytes <= _COALESCE_ENTRY_MAX
        ):
            # small control frame: merge into the queue's coalesce tail — a
            # burst of heartbeats/acks becomes one segment of one sendmsg
            tail.parts[0] += b"".join(parts)
            tail.envs.append(env)
            tail.nbytes += nbytes
            frame = tail
        elif nbytes <= _COALESCE_MAX:
            frame = _Frame([bytearray(b"".join(parts))], [env], nbytes, True)
            sender.queue.append(frame)
        else:
            # payload frame: the segments (header bytes + payload view of
            # the engine's memory) go on the queue as-is — the vectored
            # write is the first and only place the payload bytes move
            frame = _Frame(parts, [env], nbytes, False)
            sender.queue.append(frame)
        sender.queued_bytes += nbytes
        loop = asyncio.get_running_loop()
        if sender.writer_task is None or sender.writer_task.done():
            sender.writer_task = observed_task(
                self._drain_sender(ep, sender), name=f"writer-{ep}"
            )
        if sender.queued_bytes > self.write_buffer_high_water:
            await self._backpressure_wait(ep, sender, frame, loop)

    async def _send_wire_payload(
        self, env: Envelope, tctx, ep: Endpoint, chaos_act
    ) -> None:
        """Route a payload frame onto the endpoint's payload streams with
        its encode DEFERRED to the sender thread(s): the thread runs
        encode + checksum + chaos corruption just before the batch
        syscall, so peer A's codec work overlaps peer B's handler on the
        loop — and the enqueue here is the loop's ONLY involvement per
        frame (no per-batch executor round-trips). Backpressure is charged
        NOW — ``wire.payload_frame_nbytes`` is exact without encoding.

        Frames whose encoded body reaches ``intra_chunk_min_bytes`` (and
        the endpoint has >= 2 payload streams to split across) go through
        the intra-chunk path instead: sub-frames striped across streams,
        so a ONE-chunk round no longer serializes onto one socket."""
        mode = wire._wire_mode(self.wire_f16, env.wire)
        nbytes = wire.payload_frame_nbytes(
            env.dest, env.msg, mode, tctx is not None
        )
        if (
            self.intra_chunk_min_bytes
            and self.streams >= 3
            and nbytes >= self.intra_chunk_min_bytes
        ):
            await self._send_wire_striped(env, tctx, ep, chaos_act, mode, nbytes)
            return
        stream = self._pick_stream(ep, env, nbytes)
        # + 4: the per-stream seq header the sender thread stamps between
        # the length prefix and the body ([u32 len][u32 seq][body])
        frame = _Frame(None, [env], nbytes + 4, False)
        frame.encode_job = (env, tctx, mode, chaos_act)
        loop = asyncio.get_running_loop()
        sender = self._enqueue_stream_frame(ep, stream, frame)
        if sender.queued_bytes > self.stream_write_buffer_high_water:
            await self._backpressure_wait(ep, sender, frame, loop)

    async def _send_wire_striped(
        self, env: Envelope, tctx, ep: Endpoint, chaos_act, mode: str,
        nbytes: int,
    ) -> None:
        """Intra-chunk striping: split ONE payload frame's encoded body
        into sub-frames across the endpoint's payload streams. The encode
        stays deferred and runs ONCE (``_SharedEncode`` — whichever sender
        thread drains a stripe first pays it); each stripe is its own
        ``[u32 len][u32 seq]`` frame wrapping a continuation header plus a
        zero-copy slice of the shared body, and the receive side lands
        every stripe at its offset in one pooled buffer — no join copy,
        the PR-1 contract end to end."""
        n_payload = self.streams - 1
        body_len = nbytes - 4  # the u32 length prefix is per-stripe framing
        # enough stripes to use the streams, but never stripes so small
        # the continuation framing outweighs the parallelism (each stripe
        # carries at least ~half the bar)
        nstripes = min(
            n_payload,
            max(2, body_len // max(1, self.intra_chunk_min_bytes // 2)),
        )
        frag_sz = -(-body_len // nstripes)  # ceil
        frag_id = self._next_frag_id
        self._next_frag_id = (frag_id + 1) & 0xFFFF_FFFF
        shared = _SharedEncode(env, tctx, mode, chaos_act)
        loop = asyncio.get_running_loop()
        sched = None
        if self.congestion and n_payload > 1:
            sched = self._stripe_sched.get(ep)
            if sched is None:
                sched = self._stripe_sched[ep] = StripeScheduler(n_payload)
        now = time.monotonic()
        pressured: dict[_Sender, _Frame] = {}
        for i in range(nstripes):
            offset = i * frag_sz
            ln = min(frag_sz, body_len - offset)
            if ln <= 0:
                break
            stream = (
                1 + sched.pick(ln, now)
                if sched is not None
                else 1 + ((frag_id + i) % n_payload)
            )
            frame = _Frame(
                None, [env], 4 + 4 + wire.FRAG_HDR_LEN + ln, False
            )
            frame.frag = (shared, frag_id, body_len, offset, ln)
            sender = self._enqueue_stream_frame(ep, stream, frame)
            _FRAGS_SENT.inc()
            if sender.queued_bytes > self.stream_write_buffer_high_water:
                pressured[sender] = frame
        for sender, frame in pressured.items():
            await self._backpressure_wait(ep, sender, frame, loop)

    def _enqueue_stream_frame(
        self, ep: Endpoint, stream: int, frame: _Frame
    ) -> _Sender:
        """Land ``frame`` on the (endpoint, stream) sender's queue, waking
        (or starting) its dedicated thread."""
        self._loop = asyncio.get_running_loop()
        while True:
            sender = self._senders.get((ep, stream))
            if sender is None or sender.closed:
                sender = self._senders[(ep, stream)] = _Sender(stream)
            with sender.cond:
                # closed is re-checked UNDER the cond: the sender thread
                # sets it in _dead_letter_stream from its own lock scope,
                # so an unlocked check could land a frame in a queue that
                # was already drained and abandoned — never sent, never
                # dead-lettered, invisible to on_send_error
                if sender.closed:
                    continue  # lost the race: rebuild a fresh sender
                sender.queue.append(frame)
                sender.queued_bytes += frame.nbytes
                sender.cond.notify()
                break
        if sender.thread is None:
            # With data-plane threads live, the GIL switch interval IS the
            # frame handoff latency: a sender thread woken by the enqueue's
            # notify still waits for the loop thread's next GIL release —
            # up to the default 5ms — before it can even read the queue.
            # 1ms keeps the handoff off the round's critical path; the
            # extra switch overhead is noise against MB-scale frames (and
            # single-threaded streams=1 processes never reach this line).
            if sys.getswitchinterval() > 0.001:
                sys.setswitchinterval(0.001)
            sender.thread = threading.Thread(
                target=self._stream_sender_loop,
                args=(ep, sender),
                name=f"aw-stream-{ep.host}:{ep.port}-s{stream}",
                daemon=True,
            )
            sender.thread.start()
        return sender

    async def _backpressure_wait(
        self, ep: Endpoint, sender: _Sender, frame: _Frame, loop
    ) -> None:
        # Bounded user-space buffering, with a DEADLINE: a dead peer
        # empties the queue via the writer's own bounded waits, but a
        # trickling peer (accepts a few bytes per writability window)
        # could otherwise park the pump here indefinitely — the stalled
        # peer must become dropped messages, never a stalled control
        # plane. On timeout this send's frame is withdrawn (at-most-
        # once) unless the writer already has its buffers on the wire.
        fut = loop.create_future()
        sender.waiters.append(fut)
        timer = loop.call_later(
            self.connect_timeout_s,
            lambda: None if fut.done() else fut.set_result("timeout"),
        )
        try:
            timed_out = (await fut) == "timeout"
        finally:
            timer.cancel()
        if not timed_out:
            return
        # withdrawal races the sender thread on payload streams, so the
        # inflight check and the removal are one critical section (the
        # control sender's loop-task writer never contends — the lock is
        # uncontended there)
        with sender.cond:
            if frame.inflight:
                return
            try:
                sender.queue.remove(frame)
            except ValueError:
                return  # completed/dropped while we timed out
            sender.queued_bytes -= frame.nbytes
        self._note_stripe_dropped(ep, sender, frame.nbytes)
        for e in frame.envs:
            self.dropped += 1
            _DROP_BACKPRESSURE.inc()
            if self.on_send_error is not None:
                self.on_send_error(ep, e)

    async def send_all(self, envelopes: list[Envelope]) -> None:
        for env in envelopes:
            await self.send(env)

    # Largest frame we will buffer from a peer: a corrupt length prefix must
    # not turn into an unbounded allocation. Generous for real payloads
    # (dominated by max_chunk_size floats; 256 MB = a 64M-float chunk).
    max_frame_bytes = 256 << 20

    # Back-pressure point: a send whose endpoint has more than this many
    # bytes queued-but-unsent waits for the writer to drain below it, so a
    # slow peer bounds memory instead of growing the queue forever.
    write_buffer_high_water = 1 << 20

    # Back-pressure point for PAYLOAD streams (streams > 1). These queue
    # deferred-encode frames drained by a dedicated thread, and payload
    # frames are MB-scale — against the 1 MB control high-water every send
    # would trip backpressure and lock-step the producer coroutine with the
    # sender thread (enqueue -> park -> cross-thread wake per frame), which
    # is exactly the serialization the sharded plane exists to remove. At
    # 8 MB a stream holds a few payload frames in flight, so the engine's
    # next chunk overlaps the thread's encode+sendmmsg; a dead peer is
    # still bounded (per stream) and at-most-once drop semantics on
    # timeout are unchanged.
    stream_write_buffer_high_water = 8 << 20

    # Cap on frames/bytes folded into one sendmsg batch: bounds both the
    # iovec count and how much a single syscall can monopolize the writer.
    _batch_max_frames = 16
    _batch_max_bytes = 8 << 20

    async def _connect_sender(self, ep: Endpoint, sender: _Sender) -> None:
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            await asyncio.wait_for(
                loop.sock_connect(sock, (ep.host, ep.port)),
                self.connect_timeout_s,
            )
        except BaseException:
            sock.close()
            raise
        # control frames: latency-sensitive (vectored writes already emit
        # whole frames, so Nagle only adds latency here)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF_BYTES
            )
        except OSError:  # pragma: no cover - kernel may clamp/refuse
            pass
        sender.sock = sock
        # with streams > 1 every connection (stream 0 included) announces
        # itself, so the receive side can attribute rx bytes to the peer's
        # canonical endpoint; at streams=1 nothing is prepended and the
        # wire stays byte-identical to the legacy transport
        sender.need_preamble = self.streams > 1

    async def _sendmsg(self, sock: socket.socket, views: list[memoryview]) -> None:
        """Vectored write of ``views``, bounded: a peer that stops reading
        turns into dropped messages (TimeoutError via the writability
        wait), never a stalled control plane."""
        loop = asyncio.get_running_loop()
        while views:
            try:
                n = sock.sendmsg(views)
            except (BlockingIOError, InterruptedError):
                n = 0
            if n:
                while n:
                    head = views[0]
                    if n >= len(head):
                        n -= len(head)
                        views.pop(0)
                    else:
                        views[0] = head[n:]
                        n = 0
                if not views:
                    return
            await _wait_writable(loop, sock, self.connect_timeout_s)

    def _fail_sender(self, ep: Endpoint, sender: _Sender, exc: BaseException) -> None:
        """At-most-once: everything queued for the dead endpoint drops, with
        the error callback fired per envelope (consecutive-failure counting
        at the control plane relies on per-send callbacks).

        This fires only after the writer's full escalation — a bounded send
        on the existing connection, then ``retry_policy.max_retries``
        reconnect-and-resend cycles with jittered backoff — has failed, so
        a burst of callbacks here means the peer was unresponsive across
        several connection lifetimes, not one transient stall; a
        briefly-slow peer is absorbed by the retries and the kernel
        buffer."""
        log.warning("send to %s failed: %s", ep, exc)
        frames = list(sender.queue)
        sender.queue.clear()
        sender.queued_bytes = 0
        sender.close_sock()
        # the burst is over: a LATER send to this endpoint starts a fresh
        # retry budget (the peer may have come back); locked like
        # _note_retry's read-modify-write — sender threads and the stats
        # collector touch the same dict
        sender.attempts = 0
        with self._stats_lock:
            self.endpoint_backoff[ep] = 0.0
        sender.wake_waiters()
        for frame in frames:
            for env in frame.envs:
                self.dropped += 1
                _DROP_SEND_FAILED.inc()
                if self.on_send_error is not None:
                    self.on_send_error(ep, env)

    def _note_retry(self, ep: Endpoint, sender: _Sender) -> float | None:
        """Burn one retry of the burst's budget (``retry_policy``): record
        the escalation and return the jittered backoff to sleep, or
        ``None`` when the budget is exhausted — the caller escalates to
        ``_fail_sender``. The sleep itself belongs to the CALLER, outside
        the stage-timing window (idle backoff must never read as
        socket_write time in the per-stage profile)."""
        sender.attempts += 1
        if sender.attempts > self.retry_policy.max_retries or sender.closed:
            return None
        backoff = self.retry_policy.backoff_s(
            sender.attempts - 1, random.random()
        )
        # sender THREADS reach here too (payload streams): the read-modify-
        # write must not lose counts to a concurrent stream of the same
        # endpoint, and the stats collector snapshots these dicts
        with self._stats_lock:
            self.endpoint_reconnects[ep] = (
                self.endpoint_reconnects.get(ep, 0) + 1
            )
            self.endpoint_backoff[ep] = backoff
        _RECONNECTS.inc()
        log.info(
            "send to %s failed; retry %d/%d after %.3fs backoff",
            ep, sender.attempts, self.retry_policy.max_retries, backoff,
        )
        return backoff

    async def _drain_sender(self, ep: Endpoint, sender: _Sender) -> None:
        """The endpoint's single writer: drains whole frames, in order, in
        multi-frame vectored batches; a failure burst escalates through the
        RetryPolicy's reconnect budget (exponential backoff, full jitter)
        before the queue is declared dead."""
        backoff: float | None = None
        try:
            while sender.queue and not sender.closed:
                if backoff is not None:
                    await asyncio.sleep(backoff)
                    backoff = None
                    if sender.closed:
                        return
                t0 = time.perf_counter()
                try:
                    if sender.sock is None:
                        try:
                            await self._connect_sender(ep, sender)
                        except (OSError, asyncio.TimeoutError) as exc:
                            backoff = self._note_retry(ep, sender)
                            if backoff is not None:
                                continue
                            self._fail_sender(ep, sender, exc)
                            return
                    batch: list[_Frame] = []
                    views: list[memoryview] = []
                    batch_bytes = 0
                    if sender.need_preamble:
                        views.append(
                            memoryview(
                                wire.encode_stream_preamble(
                                    0, self.streams, self._host, self._port
                                )
                            )
                        )
                    for frame in sender.queue:
                        frame.inflight = True
                        batch.append(frame)
                        views.extend(_byte_views(frame.parts))
                        batch_bytes += frame.nbytes
                        if (
                            len(batch) >= self._batch_max_frames
                            or batch_bytes >= self._batch_max_bytes
                        ):
                            break
                    try:
                        await self._sendmsg(sender.sock, views)
                        sender.need_preamble = False
                    except (OSError, asyncio.TimeoutError) as exc:
                        # frames stay queued: a retry resends them whole on a
                        # fresh connection (the peer discards the partial
                        # frame with the broken stream)
                        sender.close_sock()
                        backoff = self._note_retry(ep, sender)
                        if backoff is not None:
                            continue
                        self._fail_sender(ep, sender, exc)
                        return
                finally:
                    with self._stats_lock:
                        self.stage_seconds["socket_write"] += (
                            time.perf_counter() - t0
                        )
                    _flight.set_state("transport.last_stage", "socket_write")
                if sender.attempts:
                    sender.attempts = 0  # a sent batch ends the burst
                    with self._stats_lock:
                        self.endpoint_backoff[ep] = 0.0
                key = f"{ep.host}:{ep.port}"
                # locked like the thread-side update: payload sender
                # threads increment the same key for this endpoint
                with self._stats_lock:
                    self.endpoint_tx[key] = (
                        self.endpoint_tx.get(key, 0) + batch_bytes
                    )
                for frame in batch:
                    sender.queue.popleft()
                    sender.queued_bytes -= frame.nbytes
                    if self.on_send_ok is not None:
                        for env in frame.envs:
                            self.on_send_ok(ep, env)
                if sender.queued_bytes <= self.write_buffer_high_water:
                    sender.wake_waiters()
        finally:
            sender.wake_waiters()

    # -- payload-stream senders (dedicated threads) ------------------------------

    def _stream_sender_loop(self, ep: Endpoint, sender: _Sender) -> None:
        """THREAD: a payload stream's single writer — same queue/retry/
        backoff shape as ``_drain_sender``, but the whole drain (connect,
        encode+checksum, batch syscall) lives in ONE dedicated thread on a
        BLOCKING socket. The event loop's only per-frame cost is the
        enqueue+notify in ``_send_wire_stream``; there are no per-batch
        loop round-trips, so this stream's byte-moving never serializes
        with another peer's decode or the engine's handler. Exits when the
        sender closes (teardown) or its retry budget dies (dead-letter)."""
        backoff: float | None = None
        try:
            while True:
                batch: list[_Frame] = []
                batch_bytes = 0
                evicted = False
                with sender.cond:
                    while not sender.queue and not sender.closed:
                        # bounded wait: a lost wakeup degrades to a 1s poll
                        sender.cond.wait(timeout=_SEND_SLICE_S)
                    if sender.closed:
                        # closed from OUTSIDE the thread (endpoint
                        # eviction) with frames still queued: they get the
                        # full dead-letter accounting below — a silent
                        # drop would leave senders (statetransfer's push
                        # dedup above all) believing the frames arrived.
                        # Teardown (_stopped) keeps the historical
                        # silent-drop semantics: callbacks into a stopping
                        # control plane help nobody.
                        evicted = bool(sender.queue) and not self._stopped
                        if not evicted:
                            return
                if evicted:
                    self._dead_letter_stream(
                        ep, sender, OSError("endpoint evicted")
                    )
                    return
                with sender.cond:
                    for frame in sender.queue:
                        frame.inflight = True
                        batch.append(frame)
                        batch_bytes += frame.nbytes
                        if (
                            len(batch) >= self._batch_max_frames
                            or batch_bytes >= self._batch_max_bytes
                        ):
                            break
                if backoff is not None:
                    time.sleep(backoff)  # outside the stage-timing window
                    backoff = None
                    if sender.closed:
                        # an evicted endpoint's sender is USUALLY here (its
                        # sends were failing — that is why it got expelled):
                        # the queue still gets the dead-letter accounting, a
                        # silent exit would strand the frames unreported
                        if sender.queue and not self._stopped:
                            self._dead_letter_stream(
                                ep, sender, OSError("endpoint evicted")
                            )
                        return
                if sender.sock is None:
                    try:
                        self._connect_stream_blocking(ep, sender)
                    except (OSError, asyncio.TimeoutError) as exc:
                        with sender.cond:  # retried frames re-batch fresh
                            for frame in batch:
                                frame.inflight = False
                        backoff = self._note_retry(ep, sender)
                        if backoff is not None:
                            continue
                        self._dead_letter_stream(ep, sender, exc)
                        return
                try:
                    sent = self._blocking_send_batch(sender, batch)
                except (OSError, asyncio.TimeoutError) as exc:
                    sender.close_sock()
                    with sender.cond:
                        for frame in batch:
                            frame.inflight = False
                    backoff = self._note_retry(ep, sender)
                    if backoff is not None:
                        continue
                    self._dead_letter_stream(ep, sender, exc)
                    return
                if sender.attempts:
                    sender.attempts = 0  # a sent batch ends the burst
                    with self._stats_lock:
                        self.endpoint_backoff[ep] = 0.0
                key = f"{ep.host}:{ep.port}"
                with self._stats_lock:
                    self.endpoint_tx[key] = (
                        self.endpoint_tx.get(key, 0) + sent
                    )
                if self.congestion and sender.stream_id >= 1:
                    # drain feedback for the congestion-aware scheduler: a
                    # stream that stops moving its assigned bytes sheds
                    # assignment weight (control/stripes.py)
                    sched = self._stripe_sched.get(ep)
                    if sched is not None:
                        sched.note_sent(
                            sender.stream_id - 1, sent, time.monotonic()
                        )
                sent_envs: list = []
                with sender.cond:
                    for frame in batch:
                        sender.queue.popleft()
                        sender.queued_bytes -= frame.nbytes
                        sent_envs.extend(frame.envs)
                self._post_to_loop(self._stream_batch_sent, ep, sender, sent_envs)
        except BaseException as exc:  # noqa: BLE001 - the thread must never
            # die silently: anything the retry paths above did not expect
            # (a deferred-encode bug, native.batch_send raising after a
            # library unload, chaos corrupt_frame_parts on a malformed
            # frame) is NOT retryable — a wedged (endpoint, stream) stripe
            # with closed=False would otherwise swallow every later frame
            # with no dead-letter and no on_send_error, invisible to the
            # control plane's failure accounting.
            self._dead_letter_stream(ep, sender, exc)
        finally:
            ring, sender.uring = sender.uring, None
            if ring is not None:  # the ring belongs to this thread
                try:
                    ring.close()
                except Exception:  # pragma: no cover - teardown best effort
                    pass
            if sender.closed:
                # a sender closed from outside (eviction, teardown) hands
                # the fd close to THIS thread — the only place it is
                # guaranteed out of any syscall (transport.stop() joins
                # before its own close_sock pass, which then no-ops)
                sender.close_sock()
            self._post_to_loop(sender.wake_waiters)

    def _stream_batch_sent(self, ep: Endpoint, sender: _Sender, envs: list) -> None:
        """LOOP: post-send bookkeeping a thread must not run — success
        callbacks (control-plane failure counting expects loop context)
        and waking backpressure waiters (futures belong to the loop)."""
        if self.on_send_ok is not None:
            for env in envs:
                self.on_send_ok(ep, env)
        if sender.queued_bytes <= self.stream_write_buffer_high_water:
            sender.wake_waiters()

    def _dead_letter_stream(
        self, ep: Endpoint, sender: _Sender, exc: BaseException
    ) -> None:
        """THREAD: the stream's retry budget is exhausted — drain the queue
        under the lock, mark the sender dead (the next send builds a fresh
        one with a fresh budget), and hand the dropped envelopes to the
        loop for the at-most-once error callbacks (``_fail_sender``'s
        contract, split across the thread boundary)."""
        log.warning("send to %s failed: %s", ep, exc)
        with sender.cond:
            frames = list(sender.queue)
            sender.queue.clear()
            sender.queued_bytes = 0
            sender.closed = True
        sender.close_sock()
        sender.attempts = 0
        # sender-thread side of the same dict _note_retry and the loop's
        # _fail_sender write: every cross-context mutation holds the lock
        with self._stats_lock:
            self.endpoint_backoff[ep] = 0.0
        self._note_stripe_dropped(
            ep, sender, sum(f.nbytes for f in frames)
        )
        envs = [env for frame in frames for env in frame.envs]
        self._post_to_loop(self._stream_dead_letter_cb, ep, sender, envs)

    def _note_stripe_dropped(
        self, ep: Endpoint, sender: _Sender, nbytes: int
    ) -> None:
        """Reconcile the congestion scheduler's backlog for frames dropped
        UNSENT (dead-letter, backpressure withdrawal): phantom outstanding
        bytes never produce a ``note_sent`` and would otherwise read as
        permanent congestion, pinning the stream at the weight floor."""
        if not nbytes or not self.congestion or sender.stream_id < 1:
            return
        sched = self._stripe_sched.get(ep)
        if sched is not None:
            sched.note_dropped(
                sender.stream_id - 1, nbytes, time.monotonic()
            )

    def _stream_dead_letter_cb(
        self, ep: Endpoint, sender: _Sender, envs: list
    ) -> None:
        """LOOP: the dead-lettered envelopes become per-send error
        callbacks + drop accounting, and any backpressure waiters wake."""
        for env in envs:
            self.dropped += 1
            _DROP_SEND_FAILED.inc()
            if self.on_send_error is not None:
                self.on_send_error(ep, env)
        sender.wake_waiters()

    def _post_to_loop(self, fn, *args) -> None:
        """THREAD: schedule ``fn(*args)`` on the transport's loop; a loop
        already torn down just drops it (teardown has its own wakeups)."""
        loop = self._loop
        if loop is None:
            return
        try:
            loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:  # loop closed mid-teardown
            pass

    def _connect_stream_blocking(self, ep: Endpoint, sender: _Sender) -> None:
        """THREAD: blocking connect for a payload stream. The socket stays
        kernel-blocking with an SO_SNDTIMEO slice, so the native batch
        syscalls block productively (GIL released) yet the thread re-checks
        teardown/progress every ``_SEND_SLICE_S``."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self.connect_timeout_s)
        try:
            sock.connect((ep.host, ep.port))
        except BaseException:
            sock.close()
            raise
        sock.setblocking(True)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF_BYTES
            )
        except OSError:  # pragma: no cover - kernel may clamp/refuse
            pass
        sock.setsockopt(
            socket.SOL_SOCKET,
            socket.SO_SNDTIMEO,
            struct.pack(
                "ll", int(_SEND_SLICE_S), int((_SEND_SLICE_S % 1.0) * 1e6)
            ),
        )
        sender.sock = sock
        sender.need_preamble = True

    def _blocking_send_batch(self, sender: _Sender, batch: list[_Frame]) -> int:
        """THREAD: encode deferred frames, stamp per-stream sequence
        headers, and drain the whole batch — one ``sendmmsg`` per syscall
        when the native path is live, a ``sendmsg`` loop otherwise (same
        bytes either way). Returns bytes sent."""
        enc = 0.0
        frames_views: list[list[memoryview]] = []
        if sender.need_preamble:
            frames_views.append(
                [
                    memoryview(
                        wire.encode_stream_preamble(
                            sender.stream_id,
                            self.streams,
                            self._host,
                            self._port,
                        )
                    )
                ]
            )
        for frame in batch:
            seq_hdr = _U32.pack(sender.seq)
            sender.seq = (sender.seq + 1) & 0xFFFF_FFFF
            if frame.frag is not None:
                # one stripe of a split frame: the shared encode runs once
                # (whichever stripe's thread gets here first pays it), and
                # this frame's views are a continuation header plus a
                # zero-copy slice of the shared body
                shared, frag_id, total, offset, ln = frame.frag
                parts, enc_dt = shared.ensure(self)
                enc += enc_dt
                frames_views.append(
                    [
                        memoryview(_U32.pack(wire.FRAG_HDR_LEN + ln)),
                        memoryview(seq_hdr),
                        memoryview(
                            wire.encode_frag_header(frag_id, total, offset)
                        ),
                        *wire.slice_parts(parts[1:], offset, offset + ln),
                    ]
                )
                continue
            if frame.parts is None:
                env, tctx, mode, act = frame.encode_job
                t0 = time.perf_counter()
                parts = wire.encode_frame_parts(
                    env.dest, env.msg, wire=mode, trace=tctx
                )
                if act is not None and act.corrupt and self.chaos is not None:
                    parts = self.chaos.corrupt_frame_parts(parts, act)
                enc += time.perf_counter() - t0
                frame.parts = parts
            # frame views: [u32 len][u32 seq][body...] — the length prefix
            # is parts[0]; the sequence is FRAMING, assigned per attempt
            # (a reconnect resets the receiver's expectation with the
            # connection, so retried frames re-number cleanly)
            frames_views.append(
                [
                    memoryview(frame.parts[0]),
                    memoryview(seq_hdr),
                    *_byte_views(frame.parts[1:]),
                ]
            )
        t0 = time.perf_counter()
        try:
            sent = self._send_views_blocking(sender, frames_views)
        finally:
            sock_dt = time.perf_counter() - t0
            with self._stats_lock:
                self.stage_seconds["encode"] += enc
                self.stage_seconds["socket_write"] += sock_dt
        sender.need_preamble = False
        return sent

    # kernel answers that latch io_uring OFF for the whole transport (a
    # kernel that probed fine may still refuse the op — 5.1/5.2 without
    # SENDMSG answer EINVAL, a policy change answers EPERM); everything
    # else is an ordinary socket error for the retry path
    _URING_DISABLE_ERRNOS = frozenset(
        {_errno.ENOSYS, _errno.EINVAL, _errno.EOPNOTSUPP, _errno.EPERM}
    )

    def _uring_ring(self, sender: _Sender):
        """THREAD: the sender's submission ring, created on first use —
        None when the lever is off, the probe failed, or a prior submit
        latched the transport back to the batch syscalls."""
        if not self.uring or self._uring_off:
            return None
        if sender.uring is None:
            try:
                sender.uring = native.UringRing()
            except RuntimeError as exc:
                # check-and-set under the lock: N sender threads race to
                # their first batch before any latch lands, and the
                # fallback must count (and log) once per transport, not
                # once per thread
                with self._stats_lock:
                    first = not self._uring_off
                    self._uring_off = True
                if first:
                    _URING_FALLBACKS.inc()
                    log.info(
                        "io_uring unavailable (%s); staying on batch "
                        "syscalls",
                        exc,
                    )
                return None
        return sender.uring

    def _drop_uring(self, sender: _Sender) -> None:
        """THREAD: the kernel refused a submit the probe promised — latch
        the whole transport off io_uring (once) and fall back."""
        with self._stats_lock:
            first = not self._uring_off
            self._uring_off = True
        if first:
            _URING_FALLBACKS.inc()
            log.warning(
                "io_uring submit refused; falling back to batch syscalls"
            )
        ring, sender.uring = sender.uring, None
        if ring is not None:
            ring.close()

    def _send_views_blocking(
        self, sender: _Sender, frames: list[list[memoryview]]
    ) -> int:
        """THREAD: push every byte of ``frames`` out, advancing across
        short writes; stalls are bounded like the event-loop writers — any
        progress resets a ``connect_timeout_s`` deadline, no progress past
        it raises ``asyncio.TimeoutError`` for the writer's retry path.

        With the io_uring lever on, the whole burst goes through ONE ring
        submission (a single SENDMSG op gathering every segment). The op
        is submitted non-blocking — a stalled peer surfaces as EAGAIN and
        parks in the bounded select below, never inside an uninterruptible
        ring enter — so the teardown/deadline discipline is identical to
        the batch-syscall path."""
        sock = sender.sock
        assert sock is not None
        use_native = native.batch_send_available()
        ring = self._uring_ring(sender)
        deadline = time.monotonic() + self.connect_timeout_s
        total = 0
        while frames:
            if sender.closed:
                raise OSError("sender closed during send")
            try:
                if ring is not None:
                    try:
                        n = ring.send(
                            sock.fileno(),
                            [v for frame in frames for v in frame],
                        )
                        _URING_SUBMITS.inc()
                    except BlockingIOError:
                        raise
                    except OSError as exc:
                        if exc.errno in self._URING_DISABLE_ERRNOS:
                            self._drop_uring(sender)
                            ring = None
                            continue
                        raise
                elif use_native:
                    n = native.batch_send(sock.fileno(), frames)
                else:
                    n = sock.sendmsg(
                        [v for frame in frames for v in frame]
                    )
            except (BlockingIOError, InterruptedError):
                n = 0
            if n:
                deadline = time.monotonic() + self.connect_timeout_s
                total += n
                while n and frames:
                    head = frames[0]
                    while n and head:
                        seg = head[0]
                        if n >= len(seg):
                            n -= len(seg)
                            head.pop(0)
                        else:
                            head[0] = seg[n:]
                            n = 0
                    if not head:
                        frames.pop(0)
            elif time.monotonic() > deadline:
                raise asyncio.TimeoutError("socket write stalled")
            else:
                # bounded wait for socket room: the blocking-socket paths
                # already waited an SO_SNDTIMEO slice inside the syscall;
                # the non-blocking uring submit parks here instead (same
                # slice, same teardown re-check cadence)
                select.select([], [sock], [], _SEND_SLICE_S)
        return total

    # -- receiving ----------------------------------------------------------------

    async def _pump_inbox(self) -> None:
        """Single consumer: every handler runs one message at a time.

        Each delivery runs under the message's trace context (set for the
        handler AND the replies it sends, so the round trace propagates
        hop to hop), wrapped in a ``transport.handle`` span when the
        context is sampled — the per-node transport layer of the merged
        round timeline.
        """
        while True:
            dest, msg, buf, tctx = await self._inbox.get()
            handler = self._local_handler(dest)
            if handler is None:
                log.warning("no handler for %s; dropping", dest)
                self.dropped += 1
                _DROP_NO_HANDLER.inc()
                if buf is not None:
                    self._release_recv_buf(buf)
                continue
            # the whole delivery — handler AND the replies it returns —
            # runs under the message's context; one token reset restores
            # the pre-delivery state on every exit path
            token = _trace._current.set(tctx)
            try:
                hspan = (
                    _trace.start_span(
                        "transport.handle", msg=type(msg).__name__
                    )
                    if tctx is not None and tctx.sampled and _trace.enabled()
                    else None
                )
                if hspan is not None:
                    _trace._current.set(hspan.context)
                try:
                    t0 = time.perf_counter()
                    out = handler(msg)
                    # pump-pool threads charge stage_seconds["decode"] under
                    # this lock; the loop's handler timer must match
                    with self._stats_lock:
                        self.stage_seconds["handler"] += (
                            time.perf_counter() - t0
                        )
                    _flight.set_state("transport.last_stage", "handler")
                except asyncio.CancelledError:
                    # defense-in-depth for the arlint ASYNC004 shape: today
                    # the try body has no await (cancellation lands at the
                    # queue get / send_all instead), but a future await
                    # inside a handler must find teardown cancellation
                    # escaping, not absorbed into the broad handler-crash
                    # arm below
                    raise
                except Exception:
                    log.exception(
                        "handler for %s failed on %s", dest, type(msg).__name__
                    )
                    _HANDLER_ERRORS.inc()
                    msg = None
                    if buf is not None:
                        self._release_recv_buf(buf)
                    continue
                finally:
                    if hspan is not None:
                        hspan.end()
                self.delivered += 1
                _DELIVERED.inc()
                # drop our reference to the decoded payload views BEFORE
                # recycling; the export check in _release_recv_buf protects
                # against anything the handler (or the replies) retained
                msg = None
                await self.send_all(out)
            finally:
                _trace._current.reset(token)
            if buf is not None:
                self._release_recv_buf(buf)

    async def drain(self, timeout: float = 5.0) -> None:
        """Wait until the local inbox is empty (test convenience).

        Polls with a growing sleep (1ms -> 50ms) instead of a fixed tight
        interval, on the RUNNING loop's clock — shutdown paths that call
        this must never busy-spin the event loop."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        pause = 0.001
        while not self._inbox.empty():
            if loop.time() > deadline:
                raise TimeoutError("transport did not drain")
            await asyncio.sleep(pause)
            pause = min(pause * 2.0, 0.05)


async def _wait_writable(
    loop: asyncio.AbstractEventLoop, sock: socket.socket, timeout: float
) -> None:
    """Wait until ``sock`` accepts more bytes (writev drained), raising
    ``asyncio.TimeoutError`` after ``timeout``.

    Deliberately NOT ``asyncio.wait_for``: this wait sits under every frame
    write, and on Python < 3.12 ``wait_for`` can swallow an external task
    cancellation that races the future's completion — a cancelled pump that
    keeps running turns ``transport.stop()`` into a deadlock. A plain
    ``await fut`` with a manual timer propagates cancellation verbatim."""
    fut = loop.create_future()
    fd = sock.fileno()

    def ready() -> None:
        if not fut.done():
            fut.set_result(None)

    def timed_out() -> None:
        if not fut.done():
            fut.set_exception(asyncio.TimeoutError("socket write stalled"))

    loop.add_writer(fd, ready)
    timer = loop.call_later(timeout, timed_out)
    try:
        await fut
    finally:
        timer.cancel()
        loop.remove_writer(fd)


async def run_periodic(
    interval_s: float, fn: Callable[[], Awaitable[None]]
) -> None:
    """Fixed-interval async ticker (heartbeats, detector polls)."""
    while True:
        await asyncio.sleep(interval_s)
        await fn()
