"""Remote transport: asyncio TCP delivery of control-plane envelopes.

The reference's L0 is Akka remoting — ``ActorSelection ! msg`` serialized by
Netty onto TCP (SURVEY.md §2 L0). This is the same layer, idiomatic Python:
each process runs one ``RemoteTransport`` = one inbound TCP server + a pool of
outbound connections + a single-consumer delivery loop, so every local handler
processes one message at a time (the actor guarantee the reference's buffers
rely on — SURVEY.md §6 "Race detection": actor model, buffers actor-private).

Routing mirrors ``LocalRouter`` (control/local.py) but resolves non-local
addresses to endpoints: exact routes ("master" -> seed) and prefix resolvers
("worker:<id>" -> the owning node's endpoint via the address book). Delivery
is at-most-once: a dead or unknown destination drops the message — exactly the
reference's remoting semantics, and what the threshold design expects
(SURVEY.md §4.2: rounds complete at threshold, never wait for lost messages).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import time
from typing import Any, Awaitable, Callable

from akka_allreduce_tpu.control import wire
from akka_allreduce_tpu.control.cluster import Endpoint
from akka_allreduce_tpu.control.envelope import Envelope

log = logging.getLogger(__name__)

Handler = Callable[[Any], list[Envelope]]
PrefixHandler = Callable[[int, Any], list[Envelope]]
_U32 = wire._U32


class RemoteTransport:
    """One process's transport: local handlers + remote routes."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        connect_timeout_s: float = 5.0,
    ) -> None:
        self._host = host
        self._port = port
        self.connect_timeout_s = connect_timeout_s
        self._server: asyncio.Server | None = None
        self._handlers: dict[str, Handler] = {}
        self._prefix_handlers: dict[str, PrefixHandler] = {}
        self._routes: dict[str, Endpoint] = {}
        self._prefix_routes: dict[str, Callable[[int], Endpoint | None]] = {}
        self._conns: dict[Endpoint, asyncio.StreamWriter] = {}
        self._conn_locks: dict[Endpoint, asyncio.Lock] = {}
        self._inbox: asyncio.Queue[tuple[str, Any]] = asyncio.Queue()
        self._pump: asyncio.Task | None = None
        self._reader_tasks: set[asyncio.Task] = set()
        self.delivered = 0
        self.dropped = 0
        self.on_send_error: Callable[[Endpoint, Envelope], None] | None = None
        # called after a frame reaches the socket buffer — lets callers treat
        # failure counts as CONSECUTIVE (reset on success) rather than
        # cumulative-since-forever
        self.on_send_ok: Callable[[Endpoint, Envelope], None] | None = None
        # fault injection (the reference tests by omitting messages,
        # SURVEY.md §5): return True to swallow an outgoing envelope
        self.drop_filter: Callable[[Envelope], bool] | None = None
        # wire compression (MetaDataConfig.wire_dtype == "f16"): float
        # payloads cross the socket at half width; local deliveries and the
        # decode side are unaffected (the flag travels in the frame)
        self.wire_f16 = False
        # per-stage wall-time accounting (VERDICT r3 #8): where a node's
        # protocol budget goes — codec vs socket vs engine. Two
        # perf_counter calls per message per stage on >=KB-scale frames;
        # noise next to the work being measured.
        self.stage_seconds: dict[str, float] = {
            "encode": 0.0,  # wire.encode_frame (single-copy frame build)
            "socket_write": 0.0,  # connect + write + bounded drain
            "decode": 0.0,  # wire.decode_frame_body (zero-copy payloads)
            "handler": 0.0,  # engine: buffer store/reduce + replies built
        }

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> Endpoint:
        self._server = await asyncio.start_server(
            self._serve_connection, self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]
        self._pump = asyncio.create_task(self._pump_inbox())
        return self.endpoint

    @property
    def endpoint(self) -> Endpoint:
        return Endpoint(self._host, self._port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # cancel connection handlers BEFORE wait_closed: on Python >= 3.12 it
        # waits for them, and they loop on readexactly until cancelled
        for task in list(self._reader_tasks):
            task.cancel()
        if self._reader_tasks:
            await asyncio.gather(*self._reader_tasks, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass
            self._pump = None
        for w in self._conns.values():
            w.close()
        self._conns.clear()
        self._conn_locks.clear()

    # -- registration / routing -------------------------------------------------

    def register(self, addr: str, handler: Handler) -> None:
        self._handlers[addr] = handler

    def register_prefix(self, prefix: str, handler: PrefixHandler) -> None:
        self._prefix_handlers[prefix] = handler

    def set_route(self, addr: str, endpoint: Endpoint) -> None:
        self._routes[addr] = endpoint

    def set_prefix_route(
        self, prefix: str, resolver: Callable[[int], Endpoint | None]
    ) -> None:
        self._prefix_routes[prefix] = resolver

    def _local_handler(self, dest: str) -> Callable[[Any], list[Envelope]] | None:
        handler = self._handlers.get(dest)
        if handler is not None:
            return handler
        prefix, _, suffix = dest.rpartition(":")
        ph = self._prefix_handlers.get(prefix)
        if ph is not None and suffix.lstrip("-").isdigit():
            return lambda m, _ph=ph, _id=int(suffix): _ph(_id, m)
        return None

    def _resolve(self, dest: str) -> Endpoint | None:
        ep = self._routes.get(dest)
        if ep is not None:
            return ep
        prefix, _, suffix = dest.rpartition(":")
        resolver = self._prefix_routes.get(prefix)
        if resolver is not None and suffix.lstrip("-").isdigit():
            return resolver(int(suffix))
        return None

    # -- sending -----------------------------------------------------------------

    async def send(self, env: Envelope) -> None:
        if self.drop_filter is not None and self.drop_filter(env):
            self.dropped += 1
            return
        if env.via is None:
            handler = self._local_handler(env.dest)
            if handler is not None:  # local delivery: no wire, same FIFO inbox
                await self._inbox.put((env.dest, env.msg))
                return
        ep = env.via if env.via is not None else self._resolve(env.dest)
        if ep is None:
            log.warning("no route for %s; dropping", env.dest)
            self.dropped += 1
            return
        t0 = time.perf_counter()
        frame = wire.encode_frame(env.dest, env.msg, f16=self.wire_f16)
        self.stage_seconds["encode"] += time.perf_counter() - t0
        # One reconnect-and-retry: a cached connection whose peer restarted
        # fails on the first write after the restart — that staleness is this
        # transport's problem, not the control plane's. A failure on a FRESH
        # connection means the peer is genuinely gone: drop (at-most-once).
        for attempt in (0, 1):
            try:
                await self._write(ep, frame)
                if self.on_send_ok is not None:
                    self.on_send_ok(ep, env)
                return
            except (OSError, asyncio.TimeoutError) as exc:
                had_conn = ep in self._conns
                writer = self._conns.pop(ep, None)
                if writer is not None:
                    writer.close()
                if attempt == 1 or not had_conn:
                    self.dropped += 1
                    log.warning(
                        "send to %s (%s) failed: %s", env.dest, ep, exc
                    )
                    self._conn_locks.pop(ep, None)
                    if self.on_send_error is not None:
                        self.on_send_error(ep, env)
                    return

    async def send_all(self, envelopes: list[Envelope]) -> None:
        for env in envelopes:
            await self.send(env)

    # Largest frame we will buffer from a peer: a corrupt length prefix must
    # not turn into an unbounded allocation. Generous for real payloads
    # (dominated by max_chunk_size floats; 256 MB = a 64M-float chunk).
    max_frame_bytes = 256 << 20

    # Back-pressure point: drain (bounded) only once this much is buffered.
    # Draining every frame costs a timer + task round-trip through the event
    # loop per message; letting the OS buffer absorb bursts nearly doubles
    # small-chunk message rate while still bounding memory at an
    # unresponsive peer (the drain timeout turns a stalled peer into
    # dropped messages, not a stalled control plane).
    write_buffer_high_water = 1 << 20

    async def _write(self, ep: Endpoint, frame: bytes) -> None:
        # Bounded connect/drain: sends run inline in the pump consumer, so an
        # unresponsive peer (SYN blackhole) must not stall the whole control
        # plane for the kernel's TCP timeout — it becomes a dropped message.
        lock = self._conn_locks.setdefault(ep, asyncio.Lock())
        async with lock:  # serialize connect + write per peer
            # stage timing starts INSIDE the lock (a sender parked on the
            # lock must not double-count its peer's interval) and accrues
            # through try/finally so failed connects/drains — the stalls
            # this accounting exists to expose — are attributed here, not
            # to "event-loop wait"
            t0 = time.perf_counter()
            try:
                writer = self._conns.get(ep)
                if writer is None or writer.is_closing():
                    _, writer = await asyncio.wait_for(
                        asyncio.open_connection(ep.host, ep.port),
                        self.connect_timeout_s,
                    )
                    sock = writer.get_extra_info("socket")
                    if sock is not None:  # control frames: latency-sensitive
                        sock.setsockopt(
                            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                        )
                    self._conns[ep] = writer
                writer.write(frame)
                if (
                    writer.transport.get_write_buffer_size()
                    > self.write_buffer_high_water
                ):
                    await asyncio.wait_for(
                        writer.drain(), self.connect_timeout_s
                    )
            finally:
                self.stage_seconds["socket_write"] += (
                    time.perf_counter() - t0
                )

    # -- receiving ----------------------------------------------------------------

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        assert task is not None
        self._reader_tasks.add(task)
        try:
            while True:
                header = await reader.readexactly(4)
                (length,) = _U32.unpack(header)
                if length > self.max_frame_bytes:
                    # a corrupt/hostile length prefix must not make us
                    # buffer gigabytes; drop the connection (the peer's
                    # framing is gone — nothing after this parses)
                    log.warning(
                        "frame length %d exceeds limit %d; closing connection",
                        length,
                        self.max_frame_bytes,
                    )
                    self.dropped += 1
                    break
                body = await reader.readexactly(length)
                try:
                    t0 = time.perf_counter()
                    dest, msg = wire.decode_frame_body(body)
                    self.stage_seconds["decode"] += time.perf_counter() - t0
                except Exception as exc:  # malformed body: drop THIS frame
                    # framing is length-prefixed, so the stream stays in
                    # sync — one bad message must not kill the connection
                    log.warning("undecodable frame (%s); dropping", exc)
                    self.dropped += 1
                    continue
                await self._inbox.put((dest, msg))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # peer closed; at-most-once semantics, nothing to recover
        except asyncio.CancelledError:
            pass
        finally:
            self._reader_tasks.discard(task)
            writer.close()

    async def _pump_inbox(self) -> None:
        """Single consumer: every handler runs one message at a time."""
        while True:
            dest, msg = await self._inbox.get()
            handler = self._local_handler(dest)
            if handler is None:
                log.warning("no handler for %s; dropping", dest)
                self.dropped += 1
                continue
            try:
                t0 = time.perf_counter()
                out = handler(msg)
                self.stage_seconds["handler"] += time.perf_counter() - t0
            except Exception:
                log.exception("handler for %s failed on %s", dest, type(msg).__name__)
                continue
            self.delivered += 1
            await self.send_all(out)

    async def drain(self, timeout: float = 5.0) -> None:
        """Wait until the local inbox is empty (test convenience)."""
        deadline = asyncio.get_event_loop().time() + timeout
        while not self._inbox.empty():
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("transport did not drain")
            await asyncio.sleep(0.01)


async def run_periodic(
    interval_s: float, fn: Callable[[], Awaitable[None]]
) -> None:
    """Fixed-interval async ticker (heartbeats, detector polls)."""
    while True:
        await asyncio.sleep(interval_s)
        await fn()
