"""Multi-process cluster bootstrap: master and node roles over TCP.

The reference's deployment (SURVEY.md §2 L4, §4.1): one ``main`` per role; the
master JVM binds a seed address, worker JVMs join via Akka Cluster, the grid
master organizes lines and rounds begin. Here:

- ``MasterProcess`` — binds the seed endpoint; owns the ``GridMaster`` (and
  thus every ``LineMaster``), the address book, and the phi-accrual
  ``HeartbeatMonitor``. Nodes join with ``JoinCluster``, are ``Welcome``d with
  an assigned node id + the cluster config, then heartbeat. Silence trips the
  detector -> ``member_unreachable`` -> re-organize (SURVEY.md §4.5); a
  late joiner re-runs the Prepare/Confirm handshake.
- ``NodeProcess`` — dials the seed, then hosts one ``AllreduceNode`` (one
  worker per grid dimension) whose scatter/reduce chunks travel as wire frames
  directly between nodes — the master never relays payloads, matching the
  reference where workers message peers point-to-point.

Addressing: ``master`` and every ``line_master:<id>`` live on the master
process; ``worker:<id>`` lives on node ``id // dims``; ``client:<port>`` is a
pre-welcome return address (the joiner does not yet know its node id);
``node:<id>`` receives master broadcasts (address book, shutdown).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable

from akka_allreduce_tpu.config import AllreduceConfig
from akka_allreduce_tpu.control import cluster as cl
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.failure import (
    HeartbeatMonitor,
    MemberState,
    PhiAccrualFailureDetector,
)
from akka_allreduce_tpu.control.grid_master import GridMaster
from akka_allreduce_tpu.control.node import AllreduceNode
from akka_allreduce_tpu.control.remote import RemoteTransport, run_periodic
from akka_allreduce_tpu.control.worker import DataSink, DataSource

log = logging.getLogger(__name__)


class MasterProcess:
    """Seed-node role: membership, line organization, round scheduling."""

    def __init__(
        self,
        config: AllreduceConfig,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        phi_threshold: float = 8.0,
    ) -> None:
        self.config = config
        self.clock = clock
        self.grid = GridMaster(
            config.threshold, config.master, config.line_master
        )
        self.monitor = HeartbeatMonitor(
            PhiAccrualFailureDetector(
                threshold=phi_threshold,
                first_heartbeat_estimate=config.master.heartbeat_interval_s,
            )
        )
        self.book: dict[int, cl.Endpoint] = {}
        self.unreachable: set[int] = set()
        self.transport = RemoteTransport(host, port)
        self.transport.register("master", self._on_cluster_msg)
        self.transport.register_prefix("line_master", self.grid.handle_for_line)
        self.transport.set_prefix_route("worker", self._worker_endpoint)
        self.transport.set_prefix_route("node", self.book.get)
        self._poll_task: asyncio.Task | None = None
        self._done = asyncio.Event()

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> cl.Endpoint:
        ep = await self.transport.start()
        interval = self.config.master.heartbeat_interval_s
        self._poll_task = asyncio.create_task(
            run_periodic(interval, self._poll_detector)
        )
        log.info("master listening on %s", ep)
        return ep

    async def stop(self) -> None:
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
            self._poll_task = None
        await self.transport.stop()

    async def run_until_done(self, timeout: float | None = None) -> None:
        """Wait for every line to finish ``max_rounds``, then broadcast
        ``Shutdown`` (requires ``line_master.max_rounds >= 0``)."""
        await asyncio.wait_for(self._done.wait(), timeout)
        await self.transport.send_all(self._broadcast(cl.Shutdown("done")))

    # -- routing helpers -------------------------------------------------------

    def _worker_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        nid = worker_id // self.config.master.dimensions
        return None if nid in self.unreachable else self.book.get(nid)

    def _broadcast(self, msg: Any) -> list[Envelope]:
        return [
            Envelope(f"node:{nid}", msg)
            for nid in sorted(self.book)
            if nid not in self.unreachable
        ]

    # -- cluster protocol ------------------------------------------------------

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        now = self.clock()
        if isinstance(msg, cl.JoinCluster):
            return self._on_join(msg, now)
        if isinstance(msg, cl.Heartbeat):
            return self._on_heartbeat(msg.node_id, now)
        if isinstance(msg, cl.LeaveCluster):
            self.monitor.leave(msg.node_id, now)
            out = self.grid.member_unreachable(msg.node_id)
            self.book.pop(msg.node_id, None)
            self.unreachable.discard(msg.node_id)
            return out + self._broadcast(self._address_book())
        raise TypeError(f"master cannot handle {type(msg).__name__}")

    def _on_join(self, msg: cl.JoinCluster, now: float) -> list[Envelope]:
        nid = msg.preferred_node_id
        if nid < 0 or (
            nid in self.book and self.book[nid] != cl.Endpoint(msg.host, msg.port)
        ):
            nid = max(self.book, default=-1) + 1
        self.book[nid] = cl.Endpoint(msg.host, msg.port)
        self.unreachable.discard(nid)
        # pre-welcome return address: the joiner doesn't know its id yet
        self.transport.set_route(
            f"client:{msg.port}", cl.Endpoint(msg.host, msg.port)
        )
        self.monitor.heartbeat(nid, now)
        log.info("master: node %d joined from %s:%d", nid, msg.host, msg.port)
        out = [
            Envelope(
                f"client:{msg.port}",
                cl.Welcome(nid, self.config.to_json()),
            )
        ]
        out.extend(self._broadcast(self._address_book()))
        out.extend(self.grid.member_up(nid))
        return out

    def _on_heartbeat(self, node_id: int, now: float) -> list[Envelope]:
        if node_id not in self.book:
            return []  # stale heartbeat from a node we already expelled
        event = self.monitor.heartbeat(node_id, now)
        if event is not None and node_id not in self.grid.nodes:
            # silence marked it unreachable but the process lives: rejoin it
            log.info("master: node %d heartbeat resumed -> rejoin", node_id)
            self.unreachable.discard(node_id)
            return self._broadcast(self._address_book()) + self.grid.member_up(
                node_id
            )
        return []

    def _address_book(self) -> cl.AddressBook:
        return cl.AddressBook(
            tuple(
                (nid, ep.host, ep.port)
                for nid, ep in sorted(self.book.items())
                if nid not in self.unreachable
            )
        )

    async def _poll_detector(self) -> None:
        now = self.clock()
        out: list[Envelope] = []
        expelled = False
        for event in self.monitor.poll(now):
            if event.state is MemberState.UNREACHABLE:
                log.info(
                    "master: node %d unreachable (phi=%.1f)",
                    event.node_id,
                    event.phi,
                )
                out.extend(self.grid.member_unreachable(event.node_id))
                # stop dialing and advertising the silent endpoint, but keep
                # its book entry + detector state: if the process is alive and
                # heartbeats resume, _on_heartbeat re-lines it without a new
                # JoinCluster; a genuine restart re-joins explicitly.
                self.unreachable.add(event.node_id)
                expelled = True
        if expelled:
            out.extend(self._broadcast(self._address_book()))
        if out:
            await self.transport.send_all(out)
        if self.grid.is_done:
            self._done.set()

    @property
    def rounds_completed(self) -> int:
        """Line-rounds completed across ALL configurations, not just the
        current one (re-organization replaces the line masters)."""
        return self.grid.total_completed


class NodeProcess:
    """Worker-node role: joins the seed, hosts one worker per dimension."""

    def __init__(
        self,
        seed: cl.Endpoint,
        data_source: DataSource,
        data_sink: DataSink,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        preferred_node_id: int = -1,
    ) -> None:
        self.seed = seed
        self.data_source = data_source
        self.data_sink = data_sink
        self.preferred_node_id = preferred_node_id
        self.node_id: int | None = None
        self.node: AllreduceNode | None = None
        self.config: AllreduceConfig | None = None
        self.book = cl.AddressBook(())
        self.transport = RemoteTransport(host, port)
        self.transport.set_route("master", seed)
        self.transport.set_prefix_route("line_master", lambda _lid: seed)
        self.transport.set_prefix_route("worker", self._peer_endpoint)
        self._heartbeat_task: asyncio.Task | None = None
        self._welcomed = asyncio.Event()
        self._shutdown = asyncio.Event()
        self.shutdown_reason: str | None = None

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        ep = await self.transport.start()
        self.transport.register_prefix(
            "client", lambda _port, msg: self._on_cluster_msg(msg)
        )
        await self.transport.send(
            Envelope(
                "master",
                cl.JoinCluster(ep.host, ep.port, self.preferred_node_id),
            )
        )

    async def wait_welcomed(self, timeout: float = 10.0) -> int:
        await asyncio.wait_for(self._welcomed.wait(), timeout)
        assert self.node_id is not None
        return self.node_id

    async def run_until_shutdown(self, timeout: float | None = None) -> str:
        await asyncio.wait_for(self._shutdown.wait(), timeout)
        return self.shutdown_reason or "done"

    async def leave(self) -> None:
        """Graceful departure (the reference's Cluster leave)."""
        if self.node_id is not None:
            await self.transport.send(
                Envelope("master", cl.LeaveCluster(self.node_id))
            )

    async def stop(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except asyncio.CancelledError:
                pass
            self._heartbeat_task = None
        await self.transport.stop()

    # -- routing helpers -------------------------------------------------------

    def _peer_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        if self.config is None:
            return None
        return self.book.endpoint_of(
            worker_id // self.config.master.dimensions
        )

    # -- cluster protocol ------------------------------------------------------

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        if isinstance(msg, cl.Welcome):
            return self._on_welcome(msg)
        if isinstance(msg, cl.AddressBook):
            self.book = msg
            return []
        if isinstance(msg, cl.Shutdown):
            self.shutdown_reason = msg.reason
            self._shutdown.set()
            return []
        raise TypeError(f"node cannot handle {type(msg).__name__}")

    def _on_welcome(self, msg: cl.Welcome) -> list[Envelope]:
        self.config = AllreduceConfig.from_json(msg.config_json)
        self.node_id = msg.node_id
        dims = self.config.master.dimensions
        self.node = AllreduceNode(
            msg.node_id,
            dims,
            self.data_source,
            self.data_sink,
            self.config.metadata,
            self.config.threshold,
            self.config.worker,
        )
        for dim in range(dims):
            wid = msg.node_id * dims + dim
            self.transport.register(
                f"worker:{wid}",
                lambda m, _wid=wid: self.node.handle(_wid, m),
            )
        self.transport.register_prefix(
            "node", lambda _nid, m: self._on_cluster_msg(m)
        )
        interval = self.config.master.heartbeat_interval_s
        self._heartbeat_task = asyncio.create_task(
            run_periodic(interval, self._send_heartbeat)
        )
        self._welcomed.set()
        log.info("node %d welcomed (dims=%d)", msg.node_id, dims)
        return []

    async def _send_heartbeat(self) -> None:
        assert self.node_id is not None
        await self.transport.send(
            Envelope("master", cl.Heartbeat(self.node_id))
        )
