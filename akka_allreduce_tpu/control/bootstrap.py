"""Multi-process cluster bootstrap: master and node roles over TCP.

The reference's deployment (SURVEY.md §2 L4, §4.1): one ``main`` per role; the
master JVM binds a seed address, worker JVMs join via Akka Cluster, the grid
master organizes lines and rounds begin. Here:

- ``MasterProcess`` — binds the seed endpoint; owns the ``GridMaster`` (and
  thus every ``LineMaster``), the address book, and the phi-accrual
  ``HeartbeatMonitor``. Nodes join with ``JoinCluster``, are ``Welcome``d with
  an assigned node id + the cluster config, then heartbeat. Silence trips the
  detector -> ``member_unreachable`` -> re-organize (SURVEY.md §4.5); a
  late joiner re-runs the Prepare/Confirm handshake.
- ``NodeProcess`` — dials the seed, then hosts one ``AllreduceNode`` (one
  worker per grid dimension) whose scatter/reduce chunks travel as wire frames
  directly between nodes — the master never relays payloads, matching the
  reference where workers message peers point-to-point.

Addressing: ``master`` and every ``line_master:<id>`` live on the master
process; ``worker:<id>`` lives on node ``id // dims``; ``client:<port>`` is a
pre-welcome return address (the joiner does not yet know its node id);
``node:<id>`` receives master broadcasts (address book, shutdown).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import time
from typing import Any, Callable

from akka_allreduce_tpu.config import AllreduceConfig
from akka_allreduce_tpu.control import cluster as cl
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.failure import (
    HeartbeatMonitor,
    LeaderLease,
    MemberState,
    PhiAccrualFailureDetector,
)
from akka_allreduce_tpu.control import gossip as gsp
from akka_allreduce_tpu.control.grid_master import GridMaster
from akka_allreduce_tpu.control.node import AllreduceNode
from akka_allreduce_tpu.control.remote import (
    RemoteTransport,
    observed_task,
    run_periodic,
)
from akka_allreduce_tpu.control import statetransfer as st
from akka_allreduce_tpu.control.worker import DataSink, DataSource
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics

log = logging.getLogger(__name__)

# master-HA observability (OBSERVABILITY.md): the current leadership epoch,
# takeover/fence/walk counters, and the digest stream volume — the signals a
# failover post-mortem reads next to the chaos event log
_EPOCH_GAUGE = _metrics.gauge("master.epoch")
# the AdaptiveController's registry evidence (control/adapt.py): cumulative
# counters whose window deltas are degrade pressure / restore blockers —
# held as objects so the per-round gather is attribute reads, not lookups
_EV_RESTARTS = _metrics.counter("master.rounds_restarted")
_EV_RECONNECTS = _metrics.counter("remote.endpoint_reconnects")
_EV_DROPS = _metrics.counter("chaos.injected.drop")
_EV_REORGS = _metrics.counter("master.reorganizations")
_TAKEOVERS = _metrics.counter("failover.takeovers")
_DIGESTS_SENT = _metrics.counter("failover.digests_sent")
_DIGESTS_RECEIVED = _metrics.counter("failover.digests_received")
_FENCED = _metrics.counter("failover.fenced")
_WALKS = _metrics.counter("failover.walks")
_SOLICITS = _metrics.counter("failover.advert_solicits")
# decentralized-membership observability (RESILIENCE.md "Tier 6"): how
# many expulsions the GOSSIP verdict drove (vs the legacy phi hub's), and
# how often a freshly-admitted member was shielded from a stale rumor
_GOSSIP_EXPULSIONS = _metrics.counter("gossip.expulsions")
_GOSSIP_SHIELDED = _metrics.counter("gossip.rumors_shielded")


class MasterProcess:
    """Seed-node role: membership, line organization, round scheduling.

    Master high availability (RESILIENCE.md "Tier 4 — control-plane
    failover"): every master runs with a monotonically-bumped leadership
    ``epoch`` stamped onto all master->node control messages (nodes fence
    stale-epoch senders, so a zombie deposed leader can never split-brain
    a healed partition). With ``standby_of`` set, this process is a WARM
    STANDBY instead: it registers with the leader, absorbs the replicated
    :class:`cl.StateDigest` stream (membership + incarnations, round
    counters, the peer-checkpoint holder registry, the full config), and
    takes over — bumping the epoch — when its :class:`LeaderLease` expires
    on digest silence. Nodes then walk the standby list distributed via
    ``Welcome``/``AddressBook`` and re-join the new leader.
    """

    def __init__(
        self,
        config: AllreduceConfig,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        phi_threshold: float = 8.0,
        metrics=None,  # utils.metrics.MetricsLogger | None
        epoch: int = 1,
        standby_of: cl.Endpoint | None = None,
        allow_crash: bool = False,
        chaos_log: str | None = None,
    ) -> None:
        self.config = config
        self.clock = clock
        self.metrics = metrics
        self.epoch = epoch
        self.standby_of = standby_of
        self.allow_crash = allow_crash
        self.chaos_log = chaos_log
        self._took_over = False
        self._fenced_out = False
        self.shutdown_reason: str | None = None
        # standby endpoints registered with THIS leader, in registration
        # order (the walk order nodes follow on leader loss)
        self.standby_eps: list[cl.Endpoint] = []
        self._digest_seq = 0
        # the digest's slow-moving half (config, membership, the ckpt
        # registry) is cached between state changes AS SERIALIZED JSON:
        # the per-tick lease heartbeat pays for the tiny round-counter
        # object and a string splice, not a full re-serialization of the
        # config and every retained checkpoint manifest
        self._digest_static: str | None = None
        # standby-side lease on the leader, renewed per received digest
        self._lease = LeaderLease(
            threshold=phi_threshold,
            first_heartbeat_estimate=config.master.heartbeat_interval_s,
        )
        self._last_digest: cl.StateDigest | None = None
        self._register_countdown = 0
        self._standby_task: asyncio.Task | None = None
        # observers the CLI can hook (the chaos-failover drill watches the
        # TAKEOVER line this callback prints)
        self.on_takeover: Callable[["MasterProcess"], None] | None = None
        self.watchdog = None
        if config.master.round_deadline_s > 0:
            from akka_allreduce_tpu.obs.watchdog import RoundWatchdog

            self.watchdog = RoundWatchdog(
                config.master.round_deadline_s, clock=clock
            )
        # closed-loop adaptive degradation (control/adapt.py): the LEADER
        # drives it; a passive standby builds its own at takeover (from
        # the adopted config) and inherits the level via the digest
        self.adapt = None
        if config.adapt.enabled and standby_of is None:
            from akka_allreduce_tpu.control.adapt import AdaptiveController

            self.adapt = AdaptiveController(config.adapt, config.threshold)
        self.grid = self._build_grid()
        self.monitor = HeartbeatMonitor(
            PhiAccrualFailureDetector(
                threshold=phi_threshold,
                first_heartbeat_estimate=config.master.heartbeat_interval_s,
            )
        )
        self.book: dict[int, cl.Endpoint] = {}
        self.unreachable: set[int] = set()
        self._incarnations: dict[int, int] = {}
        # last superseded incarnation per node id: (incarnation, endpoint) of
        # the process whose id was reclaimed — so its surviving heartbeats can
        # be answered with a Shutdown instead of silently orphaning it
        self._superseded: dict[int, tuple[int, cl.Endpoint]] = {}
        self.transport = RemoteTransport(host, port)
        self.transport.wire_f16 = config.metadata.wire_dtype == "f16"
        self.transport.retry_policy = config.master.retry
        self.transport.configure_data_plane(config.data_plane)
        if config.chaos.enabled:
            self._arm_chaos()
        # peer checkpoint registry (statetransfer, RESILIENCE.md "Recovery"):
        # origin node id -> newest advertised manifest + which nodes hold it.
        # The master never touches chunk BYTES — it is the directory a
        # rejoiner consults for "what was my newest state, who has it".
        self._ckpt: dict[int, dict] = {}
        self.transport.register("master", self._on_cluster_msg)
        # forwarding lambda, NOT the bound method: a standby takeover
        # replaces self.grid wholesale, and the registration must follow it
        self.transport.register_prefix(
            "line_master", lambda lid, m: self.grid.handle_for_line(lid, m)
        )
        self.transport.set_prefix_route("worker", self._worker_endpoint)
        # method, not self.book.get: a standby takeover replaces the book
        self.transport.set_prefix_route("node", self._node_book_endpoint)
        self.transport.set_prefix_route("ckpt", self._node_endpoint)
        self.transport.set_prefix_route("gossip", self._gossip_endpoint)
        # SWIM gossip membership (control/gossip.py, RESILIENCE.md
        # "Tier 6"): with it enabled, nodes stop heartbeating into this
        # process's phi detector — the master becomes ONE member of the
        # probe ring and the HeartbeatMonitor a SUBSCRIBER of the gossip
        # verdict (mirror-refreshed for live members, force_unreachable
        # on confirmed deaths). A passive standby builds its own ring
        # identity at takeover (fresh epoch = fresh incarnation).
        self.gossip: gsp.GossipState | None = None
        self._gossip_agent: gsp.GossipAgent | None = None
        # members observed HUB-HEARTBEATING under a gossip-enabled config:
        # a legacy node that negotiated down (it never joined the ring)
        # stays under the phi hub's judgement — gossip's verdict never
        # expels it, its own heartbeats keep the monitor fresh. Everyone
        # else is the ring's to judge from the moment of admission (the
        # capability must default ring-ward: learning it per member takes
        # O(N) probe periods, far longer than a phi timeout).
        self._hub_speakers: set[int] = set()
        # clock of each member's latest (re)admission: a DEAD rumor that
        # predates the admission window is a stale slander about the old
        # process, never grounds to expel the one just welcomed
        self._gossip_admitted: dict[int, float] = {}
        if config.gossip.enabled and standby_of is None:
            self._build_gossip()
        self._poll_task: asyncio.Task | None = None
        self._done = asyncio.Event()

    def _build_grid(self) -> GridMaster:
        """One definition of the grid wiring — the ctor and a standby
        takeover (which replaces the grid under the adopted config) must
        never drift apart."""
        grid = GridMaster(
            self.config.threshold,
            self.config.master,
            self.config.line_master,
            on_round_complete=(
                self._on_round_complete
                if (self.metrics or self.watchdog or self.adapt)
                else None
            ),
            on_round_start=(
                self.watchdog.round_started if self.watchdog else None
            ),
            # a re-mesh abandons the replaced lines' rounds by design —
            # their deadlines must retire with them, not fire as stalls
            on_reorganize=(self.watchdog.reset if self.watchdog else None),
            epoch=self.epoch,
        )
        if self.adapt is not None:
            # the controller's current level survives grid rebuilds (a
            # takeover replaces the grid wholesale mid-incident)
            grid.set_policy(self.adapt.policy())
        return grid

    def _build_gossip(self) -> None:
        """One definition of the master's ring identity — the ctor and a
        standby takeover (fresh epoch) must never drift apart."""
        self.gossip = gsp.GossipState(
            gsp.MASTER_ID,
            self.epoch,
            self.config.gossip,
            seed=self.config.gossip.seed,
        )
        self._gossip_agent = gsp.GossipAgent(
            self.transport,
            self.gossip,
            clock=self.clock,
            # a fenced-out / finished master must not keep acking probes:
            # its silence is what lets the ring converge on the successor
            gate=lambda: self.active and not self._done.is_set(),
            on_message=self._on_gossip_msg,
        )

    def _gossip_roster(self) -> None:
        """Re-derive the probe ring's member set from the authoritative
        membership (book minus unreachable) after any change."""
        if self.gossip is not None:
            self.gossip.set_members(set(self.book) - self.unreachable)

    def _on_gossip_msg(self, msg) -> list[Envelope] | None:
        """Pre-handle hook on every inbound gossip frame: the unknown-
        pinger arm — a REPLACEMENT master that does not know the sender
        replies ``Rejoin`` + ``AdvertSolicit``, exactly like the hub's
        unknown-heartbeat path (a gossip cluster must not lose that
        recovery)."""
        sender = getattr(msg, "sender", None)
        if not isinstance(sender, int) or sender < 0 or not self.active:
            return None
        if sender in self.book:
            inc = getattr(msg, "incarnation", None)
            if inc is not None:
                sup = self._superseded.get(sender)
                if sup is not None and sup[0] == inc:
                    # zombie: the REMEMBERED superseded predecessor of
                    # the id's current holder is gossiping — the hub's
                    # heartbeat path had exactly this guard; tell the
                    # ghost to stand down like the hub did.
                    return [
                        Envelope(
                            f"node:{sender}",
                            cl.Shutdown("superseded", self.epoch),
                            via=sup[1],
                        )
                    ]
                if inc < self._incarnations.get(sender, inc):
                    # BELOW the admitted cluster incarnation: a stale
                    # predecessor we don't remember — not evidence, not
                    # healable. Strictly-below only: the HOLDER's gossip
                    # incarnation legitimately drifts ABOVE its cluster
                    # incarnation with every slander refutation
                    # (GossipState bumps itself past the rumor), and a
                    # `!=` check here once locked a refuted-then-expelled
                    # healthy node out of the heal arm forever.
                    return None
            # a ring member speaking gossip is certainly not negotiated
            # down — clear any stale legacy marking from a predecessor
            self._hub_speakers.discard(sender)
            if sender in self.unreachable:
                # an EXPELLED member is alive and talking to us: the hub
                # flow healed this through resumed heartbeats
                # (_on_heartbeat's re-line path); the ring edition heals
                # it here — without this, a member expelled on a
                # transient freeze could never get back in (its gossip
                # record was dropped with the roster, so no vouch arm
                # can fire for it)
                log.info(
                    "master: expelled node %d is gossiping -> rejoin",
                    sender,
                )
                return self._readmit(sender, self.clock())
            return None
        if isinstance(msg, gsp.Ping) and msg.port > 0:
            via = cl.Endpoint(msg.host, msg.port)
            _SOLICITS.inc()
            return [
                Envelope(
                    f"node:{sender}", cl.Rejoin("unknown-node", self.epoch),
                    via=via,
                ),
                Envelope(
                    f"node:{sender}", st.AdvertSolicit("unknown-node"),
                    via=via,
                ),
            ]
        return None

    def _readmit(self, nid: int, now: float) -> list[Envelope]:
        """ONE definition of re-lining a member whose process turned out
        to be alive (resumed heartbeats, or gossip frames from an
        expelled member): clear the unreachable mark, reset the detector
        history (the outage gap must not poison the inter-arrival
        model), refresh the ring record + admission-grace window, and
        re-run the membership machinery. Three call sites used to
        hand-roll drifting copies of this."""
        self.unreachable.discard(nid)
        self.monitor.detector.remove(nid)
        self.monitor.heartbeat(nid, now)
        self._gossip_roster()
        if self.gossip is not None:
            self.gossip.reset_member(nid, self._incarnations.get(nid, 0))
            self._gossip_admitted[nid] = now
        self._digest_static = None
        return (
            self._broadcast(self._address_book())
            + self.grid.member_up(nid)
            + self._digest_envelopes()
        )

    def _arm_chaos(self) -> None:
        from akka_allreduce_tpu.control.chaos import (
            MASTER_ROLE,
            ChaosInjector,
        )

        self.transport.chaos = ChaosInjector(
            self.config.chaos.seed,
            self.config.chaos.spec,
            role=MASTER_ROLE,
            dims=self.config.master.dimensions,
            # crash:node=m fires for real only in a real OS process (the
            # CLI roles arm this) — in-process masters record a
            # suppressed crash, exactly like nodes
            allow_crash=self.allow_crash,
            log_path=self.chaos_log,
        )

    @property
    def active(self) -> bool:
        """Leading right now: a plain master or a standby post-takeover —
        unless a newer epoch fenced us out (a deposed leader must stop
        ANSWERING the cluster protocol too, or it would keep Welcoming
        walking nodes into a dead end while its scheduler is silenced)."""
        return (
            self.standby_of is None or self._took_over
        ) and not self._fenced_out

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> cl.Endpoint:
        ep = await self.transport.start()
        interval = self.config.master.heartbeat_interval_s
        self._poll_task = observed_task(
            run_periodic(interval, self._poll_detector), name="master-detector"
        )
        if self.watchdog is not None:
            self.watchdog.start()  # its own observed_task poll loop
        if self.standby_of is not None:
            # standby replication lease loop: (re-)register with the leader
            # and take over when the digest stream goes silent
            self._standby_task = observed_task(
                run_periodic(interval, self._standby_poll),
                name="standby-lease",
            )
            log.info(
                "standby listening on %s (leader %s)", ep, self.standby_of
            )
        else:
            _EPOCH_GAUGE.set(self.epoch)
            log.info("master listening on %s (epoch %d)", ep, self.epoch)
        if self._gossip_agent is not None:
            self.gossip.host, self.gossip.port = ep.host, ep.port
            self._gossip_agent.start()
        return ep

    async def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._gossip_agent is not None:
            await self._gossip_agent.stop()
        for attr in ("_poll_task", "_standby_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)
        await self.transport.stop()

    async def run_until_done(self, timeout: float | None = None) -> None:
        """Wait until every line finished ``max_rounds`` (requires
        ``line_master.max_rounds >= 0``); the detector poll loop broadcasts
        ``Shutdown`` to all nodes the moment that happens."""
        await asyncio.wait_for(self._done.wait(), timeout)

    async def shutdown(self, reason: str = "terminated") -> None:
        """End an open-ended run from the outside (SIGTERM in the CLI, the
        chaos runner's --duration mode): broadcast ``Shutdown`` so nodes
        exit cleanly — flushing metrics and chaos logs — then release
        ``run_until_done``. Registered standbys are released too (a
        finished run must not read as a dead leader and trigger a
        takeover)."""
        await self.transport.send_all(
            self._broadcast(cl.Shutdown(reason, self.epoch))
            + self._standby_shutdowns(reason)
        )
        self.shutdown_reason = reason
        self._done.set()

    def _standby_shutdowns(self, reason: str) -> list[Envelope]:
        return [
            Envelope("master", cl.Shutdown(reason, self.epoch), via=ep)
            for ep in self.standby_eps
        ]

    # -- routing helpers -------------------------------------------------------

    def _worker_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        nid = worker_id // self.config.master.dimensions
        return None if nid in self.unreachable else self.book.get(nid)

    def _node_endpoint(self, node_id: int) -> cl.Endpoint | None:
        return None if node_id in self.unreachable else self.book.get(node_id)

    def _node_book_endpoint(self, node_id: int) -> cl.Endpoint | None:
        return self.book.get(node_id)

    def _gossip_endpoint(self, node_id: int) -> cl.Endpoint | None:
        # the master never dials its own ring address; expelled members
        # leave the roster, so probing stops with the membership
        return self.book.get(node_id) if node_id >= 0 else None

    def _broadcast(self, msg: Any) -> list[Envelope]:
        return [
            Envelope(f"node:{nid}", msg)
            for nid in sorted(self.book)
            if nid not in self.unreachable
        ]

    # -- cluster protocol ------------------------------------------------------

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        now = self.clock()
        # failover protocol first: these arms exist in BOTH roles
        if isinstance(msg, cl.StateDigest):
            return self._on_state_digest(msg, now)
        if isinstance(msg, cl.StandbyRegister):
            return self._on_standby_register(msg)
        if isinstance(msg, cl.Shutdown):
            return self._on_peer_shutdown(msg)
        if not self.active:
            # a PASSIVE standby must not answer the cluster protocol:
            # welcoming a join (or feeding the detector) before the lease
            # expires would split the membership between two masters —
            # nodes that walked here early just keep retrying until the
            # takeover makes this process answerable
            return []
        if isinstance(msg, cl.JoinCluster):
            return self._on_join(msg, now) + self._digest_envelopes()
        if isinstance(msg, cl.Heartbeat):
            return self._on_heartbeat(msg, now)
        if isinstance(msg, st.CheckpointAdvert):
            # NO digest piggyback here: adverts arrive in bursts (every
            # holding of every member after one solicit), and the per-tick
            # lease digest already replicates the registry within one
            # heartbeat — per-advert full-state sends would be O(members x
            # holdings) redundant serializations in a single tick
            return self._on_ckpt_advert(msg)
        if isinstance(msg, st.ManifestRequest):
            return self._on_manifest_request(msg)
        if isinstance(msg, cl.LeaveCluster):
            self.monitor.leave(msg.node_id, now)
            out = self.grid.member_unreachable(msg.node_id)
            self.book.pop(msg.node_id, None)
            self.unreachable.discard(msg.node_id)
            self._incarnations.pop(msg.node_id, None)
            self._superseded.pop(msg.node_id, None)
            if self.gossip is not None:
                self.gossip.remove_member(msg.node_id)
                self._hub_speakers.discard(msg.node_id)
                self._gossip_admitted.pop(msg.node_id, None)
            self._digest_static = None  # membership changed
            # a departed process can no longer serve chunks; its manifests
            # stay known (replicas may still hold the bytes)
            self._drop_ckpt_holder(msg.node_id)
            return (
                out
                + self._broadcast(self._address_book())
                + self._digest_envelopes()
            )
        raise TypeError(f"master cannot handle {type(msg).__name__}")

    # -- master HA: digests, standby registration, takeover --------------------

    def _on_standby_register(self, msg: cl.StandbyRegister) -> list[Envelope]:
        if not self.active:
            return []  # standbys do not chain
        ep = cl.Endpoint(msg.host, msg.port)
        out: list[Envelope] = []
        if ep not in self.standby_eps:
            self.standby_eps.append(ep)
            self._digest_static = None  # standby list changed
            log.info("master: standby registered at %s", ep)
            _flight.note(
                "failover", event="standby_register", endpoint=str(ep)
            )
            # nodes already in the cluster learn the standby list via the
            # address-book broadcast (Welcome only covers future joiners)
            out.extend(self._broadcast(self._address_book()))
        # ack with a full digest either way: registration is idempotent,
        # periodically re-sent, and the digest warms a fresh standby NOW
        # instead of at the next state change
        out.extend(self._digest_envelopes(only=ep))
        return out

    def _on_state_digest(
        self, msg: cl.StateDigest, now: float
    ) -> list[Envelope]:
        if self.active:
            if msg.epoch == self.epoch and not (
                msg.host == self.transport.endpoint.host
                and msg.port == self.transport.endpoint.port
            ):
                # two ACTIVE claimants of the SAME epoch (co-promoted from
                # disjoint histories): neither outranks the other, so break
                # the tie deterministically by endpoint — the greater
                # (host, port) yields, the lesser deposes it. Both sides
                # apply the same rule, so exactly one survives.
                me = (self.transport.endpoint.host, self.transport.endpoint.port)
                if me > (msg.host, msg.port):
                    self._stand_down(f"equal-epoch tiebreak vs {msg.host}:{msg.port}")
                    return []
                log.warning(
                    "master epoch %d: deposing equal-epoch co-claimant at "
                    "%s:%d (endpoint tiebreak)",
                    self.epoch, msg.host, msg.port,
                )
                return [
                    Envelope(
                        "master",
                        cl.Shutdown("superseded-epoch", self.epoch),
                        via=cl.Endpoint(msg.host, msg.port),
                    )
                ]
            if msg.epoch < self.epoch:
                # a fenced zombie leader is still replicating to us: tell
                # it to stand down — this closes the split-brain loop (the
                # zombie's own digest stream is what delivers its fencing)
                log.warning(
                    "master epoch %d: fencing zombie leader at %s:%d "
                    "(epoch %d)",
                    self.epoch, msg.host, msg.port, msg.epoch,
                )
                return [
                    Envelope(
                        "master",
                        cl.Shutdown("superseded-epoch", self.epoch),
                        via=cl.Endpoint(msg.host, msg.port),
                    )
                ]
            if msg.epoch > self.epoch:
                # someone with a NEWER epoch is leading: WE are the zombie
                self._stand_down(f"superseded by epoch {msg.epoch}")
            return []
        _DIGESTS_RECEIVED.inc()
        prev = self._last_digest
        if prev is not None and msg.epoch < prev.epoch:
            # an epoch-REGRESSING digest is a not-yet-fenced zombie still
            # replicating: its pre-failover state must not shadow the
            # successor's (a takeover from it would resurrect dead
            # membership and collide with the successor's epoch history)
            return []
        if prev is not None and msg.epoch == prev.epoch and msg.seq <= prev.seq:
            return []  # reordered/duplicate digest: keep the newer state
        if prev is not None and msg.epoch > prev.epoch:
            # a NEW leader identity: its digest cadence must not inherit
            # the dead leader's inter-arrival model
            self._lease.reset()
        self._last_digest = msg
        self._lease.renew(now)
        # follow the leadership: periodic re-registration must go to
        # whoever is digesting us NOW — after a failover the promoted
        # master is the one to re-register with, not the dead seed
        leader = cl.Endpoint(msg.host, msg.port)
        if leader != self.standby_of:
            log.info(
                "standby: following new leader %s (epoch %d)",
                leader, msg.epoch,
            )
            self.standby_of = leader
        return []

    def _on_peer_shutdown(self, msg: cl.Shutdown) -> list[Envelope]:
        if not self.active:
            # the leader ended the run gracefully: release this standby
            # (a finished run must not read as a dead leader)
            log.info("standby released: %s", msg.reason)
            self.shutdown_reason = msg.reason
            self._done.set()
            return []
        if msg.epoch > self.epoch or msg.reason == "superseded-epoch":
            self._stand_down(msg.reason)
        return []

    def _stand_down(self, reason: str) -> None:
        """Fenced out by a newer leadership epoch: stop acting as master.

        The poll loop goes quiet (no more expulsions, re-prepares, round
        restarts or broadcasts) and ``run_until_done`` returns so the CLI
        can exit — a deposed leader must drain, not fight the fence."""
        if self._fenced_out:
            return
        self._fenced_out = True
        self.shutdown_reason = reason
        log.warning("master epoch %d fenced out: %s", self.epoch, reason)
        _flight.note(
            "failover", event="stand_down", epoch=self.epoch, reason=reason
        )
        self._done.set()

    def _digest_state(self) -> str:
        """The compact replicated state a warm standby needs to take over:
        enough to keep scheduling (round counters, config), keep membership
        (book + incarnations), and keep answering ``ManifestRequest`` (the
        peer-checkpoint holder registry). The slow-moving half is rebuilt
        only when a state change invalidated it (``_digest_static``) — the
        per-tick lease heartbeat pays for the round counters and one dump,
        not a config reparse plus the whole manifest registry."""
        if self._digest_static is None:
            static = {
                "config": json.loads(self.config.to_json()),
                "book": [
                    [nid, ep.host, ep.port]
                    for nid, ep in sorted(self.book.items())
                ],
                "incarnations": {
                    str(n): i for n, i in self._incarnations.items()
                },
                "unreachable": sorted(self.unreachable),
                "ckpt": {
                    str(origin): {
                        "manifests": {
                            str(s): m for s, m in rec["manifests"].items()
                        },
                        "holders": {
                            str(n): s for n, s in rec["holders"].items()
                        },
                    }
                    for origin, rec in self._ckpt.items()
                },
                "standbys": [
                    [ep.host, ep.port] for ep in self.standby_eps
                ],
                # per-shard replication (RESILIENCE.md "Scale"): each
                # live line's worker set and the per-worker resume
                # floors change only on reorganization — every
                # reorganization path invalidates this cache, so the
                # static half stays truthful
                "lines": self.grid.lines_static_state(),
                "floors": self.grid.resume_floor_state(),
            }
            if self.gossip is not None:
                # the ring's judgement rides failover too: a promoted
                # standby inherits WHO was suspect/dead mid-incident and
                # which members actually speak gossip, instead of
                # re-learning both from scratch under a fresh epoch
                static["gossip_view"] = self.gossip.digest_state()
                static["hub_speakers"] = sorted(self._hub_speakers)
            # serialized once per state change, held OPEN (trailing `}`
            # stripped) so the per-tick round counters splice in cheaply
            self._digest_static = json.dumps(static)[:-1]
        round_state = {
            "next": max(
                (lm.next_round for lm in self.grid.line_masters.values()),
                default=self.grid.resume_round,
            ),
            # per-shard round counters, one per live line: a promoted
            # standby resumes EVERY shard past its own sequence instead
            # of snapping all of them to the global max (the shard-blind
            # path the PR-10 sharding left behind)
            "shards": self.grid.lines_round_state(),
            "completed": self.grid.total_completed,
            "config_id": self.grid.config_id,
        }
        if self.adapt is not None:
            # the controller's level/dwell/baseline ride the per-tick half:
            # a promoted standby inherits the CURRENT policy mid-incident
            # instead of resetting to full fidelity (RESILIENCE.md Tier 5)
            round_state["adapt"] = self.adapt.digest()
        return (
            self._digest_static + ', "round": ' + json.dumps(round_state) + "}"
        )

    def _digest_envelopes(
        self, only: cl.Endpoint | None = None
    ) -> list[Envelope]:
        """StateDigest envelopes for the registered standbys — piggybacked
        after every state-changing event AND once per detector poll (the
        lease heartbeat)."""
        targets = [only] if only is not None else list(self.standby_eps)
        if not self.active or self._fenced_out or not targets:
            return []
        self._digest_seq += 1
        me = self.transport.endpoint
        msg = cl.StateDigest(
            self.epoch, self._digest_seq, me.host, me.port,
            self._digest_state(),
        )
        _DIGESTS_SENT.inc(len(targets))
        return [Envelope("master", msg, via=ep) for ep in targets]

    async def _standby_poll(self) -> None:
        """The standby's lease loop (one tick per heartbeat interval)."""
        if self.active or self._done.is_set():
            return
        now = self.clock()
        if self._last_digest is None or self._register_countdown <= 0:
            # (re-)register: idempotent at the leader, and a RESTARTED
            # leader (fresh process, empty standby list) re-learns us
            self._register_countdown = 5
            me = self.transport.endpoint
            await self.transport.send(
                Envelope(
                    "master",
                    cl.StandbyRegister(me.host, me.port),
                    via=self.standby_of,
                )
            )
        else:
            self._register_countdown -= 1
        if self._lease.expired(now):
            self._takeover(now)

    def _takeover(self, now: float) -> None:
        """The lease expired: become the leader under a bumped epoch.

        Restores the digest's membership, round counters and checkpoint
        registry, adopts the dead leader's config (chaos + retry knobs
        included), and waits for nodes to walk the standby list and
        re-join — each re-join of a known member forces a reorganization,
        so rounds resume once the quorum is back, numbered PAST everything
        the old epoch started. A digest that lagged the leader's death by
        a round is absorbed by the workers' cross-epoch flush floor (a
        re-issued round id is re-asserted, never re-applied)."""
        digest = self._last_digest
        assert digest is not None
        state = json.loads(digest.state_json)
        self.config = AllreduceConfig.from_json(json.dumps(state["config"]))
        # epoch bump, tie-broken by standby RANK in the replicated list:
        # two standbys whose leases expire on the same silence must not
        # both claim the same epoch (an equal-epoch pair could never fence
        # each other). Rank 0 takes +1, rank 1 takes +2, ... — distinct by
        # construction, and the higher-ranked (later-registered) standby's
        # digests depose a lower-ranked co-claimant within one exchange;
        # the equal-epoch arm in _on_state_digest is the defense in depth
        # for claimants from disjoint histories.
        me = self.transport.endpoint
        rank = next(
            (
                i
                for i, (h, p) in enumerate(state["standbys"])
                if cl.Endpoint(h, int(p)) == me
            ),
            0,
        )
        self.epoch = max(self.epoch, digest.epoch) + 1 + rank
        self._took_over = True
        # speak the dead leader's wire dialect: nodes were welcomed with
        # these knobs
        self.transport.wire_f16 = self.config.metadata.wire_dtype == "f16"
        self.transport.retry_policy = self.config.master.retry
        self.transport.configure_data_plane(self.config.data_plane)
        if self.config.chaos.enabled and self.transport.chaos is None:
            self._arm_chaos()
            from akka_allreduce_tpu.control.chaos import MASTER_ROLE

            for f in self.transport.chaos.faults:
                if f.name == "crash" and f.node == MASTER_ROLE:
                    # the leader-kill fault consumed its one shot on the
                    # epoch that died of it: a digest that lagged the death
                    # (round counters below the trigger) must not let the
                    # PROMOTED master arm the same fault and kill itself
                    # mid-failover
                    f.done = True
        fresh_watchdog = None
        if self.watchdog is None and self.config.master.round_deadline_s > 0:
            # the leader ran a round-stall watchdog: the promoted master
            # must keep watching (the standby's placeholder config has no
            # deadline, so none was built at construction)
            from akka_allreduce_tpu.obs.watchdog import RoundWatchdog

            fresh_watchdog = self.watchdog = RoundWatchdog(
                self.config.master.round_deadline_s, clock=self.clock
            )
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass  # driven synchronously (sims/tests): caller owns pacing
        else:
            if fresh_watchdog is not None:
                fresh_watchdog.start()
            # re-pace the detector/reprepare/restart/digest loop at the
            # ADOPTED heartbeat interval — the standby's placeholder
            # cadence may differ from the cluster's
            if self._poll_task is not None:
                self._poll_task.cancel()
            self._poll_task = observed_task(
                run_periodic(
                    self.config.master.heartbeat_interval_s,
                    self._poll_detector,
                ),
                name="master-detector",
            )
        self.book = {
            int(nid): cl.Endpoint(h, int(p)) for nid, h, p in state["book"]
        }
        self._incarnations = {
            int(n): int(i) for n, i in state["incarnations"].items()
        }
        self.unreachable = {int(n) for n in state["unreachable"]}
        self._superseded.clear()
        self._ckpt = {
            int(origin): {
                "manifests": {
                    int(s): m for s, m in rec["manifests"].items()
                },
                "holders": {int(n): int(s) for n, s in rec["holders"].items()},
            }
            for origin, rec in state["ckpt"].items()
        }
        me = self.transport.endpoint
        self.standby_eps = [
            cl.Endpoint(h, int(p))
            for h, p in state["standbys"]
            if cl.Endpoint(h, int(p)) != me
        ]
        self._digest_static = None  # everything above changed
        # the grid continues the dead leader's numbering under the adopted
        # config: organized with the known-live member set, so the first
        # re-join (a "restart" of a known member) drives the reorganize
        # that re-prepares everyone under the new epoch
        rnd = state["round"]
        if self.config.adapt.enabled:
            # inherit the dead leader's controller mid-incident: level,
            # dwell and counter watermarks come from the digest, so the
            # promoted master's FIRST Prepare carries the inherited policy
            # and the hysteresis clock does not reset with the leader
            from akka_allreduce_tpu.control.adapt import AdaptiveController

            self.adapt = AdaptiveController(
                self.config.adapt, self.config.threshold
            )
            self.adapt.restore(rnd.get("adapt"))
        self.grid = self._build_grid()  # stamps the bumped epoch + policy
        live = set(self.book) - self.unreachable
        self.grid.nodes = set(live)
        self.grid.organized = bool(live)
        self.grid.resume_round = int(rnd["next"])
        self.grid.config_id = int(rnd["config_id"])
        self.grid._completed_before_reorg = int(rnd["completed"])
        # per-shard resume: the replicated floors + each replicated
        # line's live next round over its worker set — the takeover's
        # first reorganization resumes every shard past ITS OWN
        # high-water (a digest without the fields restores the legacy
        # global-max behavior through resume_round above)
        self.grid.restore_shard_state(
            state.get("floors"), state.get("lines"), rnd.get("shards"),
            fallback_round=int(rnd["next"]),
            fallback_workers=[
                nid * self.config.master.dimensions + d
                for nid in live
                for d in range(self.config.master.dimensions)
            ],
        )
        # seed the detector with the members we expect back: one that
        # never re-joins is expelled by the normal poll path
        for nid in sorted(live):
            self.monitor.heartbeat(nid, now)
        if self.config.gossip.enabled:
            # join the probe ring under the bumped epoch (a fresh leader
            # identity — nodes' record of gossip:-1 updates to the higher
            # incarnation on first contact), inheriting the replicated
            # view and the per-member speaker capability
            self._build_gossip()
            self._gossip_roster()
            self.gossip.restore_state(state.get("gossip_view"))
            self._hub_speakers = {
                int(n) for n in state.get("hub_speakers", [])
            }
            self._gossip_admitted = {nid: now for nid in live}
            me_ep = self.transport.endpoint
            self.gossip.host, self.gossip.port = me_ep.host, me_ep.port
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                pass  # sync-driven sims tick the state machine directly
            else:
                self._gossip_agent.start()
        _EPOCH_GAUGE.set(self.epoch)
        _TAKEOVERS.inc()
        _flight.note(
            "failover",
            event="takeover",
            epoch=self.epoch,
            members=sorted(live),
            resume_round=self.grid.resume_round,
            completed=self.grid._completed_before_reorg,
        )
        log.warning(
            "standby TAKEOVER: epoch %d, %d member(s), resume round %d, "
            "%d completed round(s) carried, %d checkpoint origin(s)",
            self.epoch, len(live), self.grid.resume_round,
            self.grid._completed_before_reorg, len(self._ckpt),
        )
        if self.on_takeover is not None:
            self.on_takeover(self)

    # -- peer checkpoint registry ----------------------------------------------

    #: manifests remembered per origin — enough to fall back past an
    #: owner-only newest step (saved, crashed before replication finished)
    _CKPT_KEEP = 3

    def _on_ckpt_advert(self, msg: st.CheckpointAdvert) -> list[Envelope]:
        rec = self._ckpt.setdefault(msg.origin, {"manifests": {}, "holders": {}})
        if msg.manifest_json:
            manifests = rec["manifests"]
            manifests[msg.step] = msg.manifest_json
            for old in sorted(manifests)[: -self._CKPT_KEEP]:
                manifests.pop(old)
        holders = rec["holders"]
        holders[msg.node_id] = max(holders.get(msg.node_id, -1), msg.step)
        self._digest_static = None  # holder registry changed
        log.info(
            "master: node %d holds checkpoint of node %d at step %d",
            msg.node_id, msg.origin, msg.step,
        )
        return []

    def _on_manifest_request(self, msg: st.ManifestRequest) -> list[Envelope]:
        """Answer with the NEWEST step that has at least one live holder
        other than the requester — not merely the newest step advertised:
        an owner that saved and then crashed before replication finished
        must get its replicas' (slightly older) step back, not an
        unservable newest step and a dead end.

        When NO step has a complete live holder (the owner died mid-
        replication — partial replicas hold chunks but never advertised),
        fall back to SCAVENGE mode: offer the OLDEST remembered manifest
        (its chunks were pushed first, so they are the most likely to have
        landed) with every live member as a candidate — content addressing
        plus the rejoiner's per-chunk ChunkMissing failover reassemble the
        state from whatever partial replicas hold; a chunk that truly
        exists nowhere surfaces as an incomplete restore, not a wedge."""
        rec = self._ckpt.get(msg.node_id)
        reply = st.ManifestReply(-1, "", ())
        if rec is not None and rec["manifests"]:
            for step in sorted(rec["manifests"], reverse=True):
                holders = tuple(
                    sorted(
                        nid
                        for nid, hstep in rec["holders"].items()
                        if hstep >= step
                        and nid != msg.node_id
                        and nid in self.book
                        and nid not in self.unreachable
                    )
                )
                if holders:
                    reply = st.ManifestReply(
                        step, rec["manifests"][step], holders
                    )
                    break
            else:
                candidates = tuple(
                    sorted(
                        nid
                        for nid in self.book
                        if nid != msg.node_id and nid not in self.unreachable
                    )
                )
                if candidates:
                    oldest = min(rec["manifests"])
                    log.info(
                        "master: no complete holder for node %d; offering "
                        "step %d for scavenge from %s",
                        msg.node_id, oldest, candidates,
                    )
                    reply = st.ManifestReply(
                        oldest, rec["manifests"][oldest], candidates
                    )
        out = [Envelope(st.ChunkService.addr(msg.node_id), reply)]
        if reply.step < 0 or not reply.holders:
            # a dead-end answer from a (possibly replacement) master whose
            # holder registry is empty or stale: solicit adverts from every
            # live member so the requester's retry rounds find the state
            # that actually survived (regression-pinned: a restore issued
            # immediately after a master restart must still converge)
            solicit = [
                Envelope(f"node:{nid}", st.AdvertSolicit("manifest-miss"))
                for nid in sorted(self.book)
                if nid != msg.node_id and nid not in self.unreachable
            ]
            if solicit:
                _SOLICITS.inc(len(solicit))
                log.info(
                    "master: no holders for node %d; soliciting adverts "
                    "from %d member(s)", msg.node_id, len(solicit),
                )
            out.extend(solicit)
        return out

    def _drop_ckpt_holder(self, node_id: int) -> None:
        """``node_id``'s process is gone (leave, or restart with a new
        incarnation): whatever its old process advertised holding is no
        longer servable — and after a disk loss may not even exist. Its
        next adverts rebuild the truth from what actually survived."""
        for rec in self._ckpt.values():
            rec["holders"].pop(node_id, None)
        self._digest_static = None  # holder registry changed

    def _on_join(self, msg: cl.JoinCluster, now: float) -> list[Envelope]:
        nid = msg.preferred_node_id
        ep = cl.Endpoint(msg.host, msg.port)
        # A join retry must resolve to the id assigned on the FIRST attempt,
        # even with auto-assigned ids (preferred -1): match by incarnation +
        # endpoint before minting a fresh id, or the retry would admit the
        # same process as a ghost second member
        for known_nid, inc in self._incarnations.items():
            if inc == msg.incarnation and self.book.get(known_nid) == ep:
                nid = known_nid
                break
        else:
            # a preferred id may be reclaimed from a NEW endpoint when its
            # previous holder is dead (crashed on another port) — only a
            # LIVE member's identity is protected from takeover
            taken = (
                nid in self.book
                and self.book[nid] != ep
                and nid in self.grid.nodes
            )
            if nid < 0 or taken:
                # an endpoint hosts at most one node process, so a fresh
                # incarnation from a booked endpoint is that node reborn —
                # reclaim its id; otherwise mint the next one
                reborn = next(
                    (k for k, v in self.book.items() if v == ep), None
                )
                nid = (
                    reborn
                    if reborn is not None
                    else max(self.book, default=-1) + 1
                )
        # Welcome goes straight to the joiner's endpoint (``via``): it doesn't
        # know its node id yet, so it can't be in any route table.
        welcome = Envelope(
            "client",
            cl.Welcome(
                nid, self.config.to_json(), self.epoch, self._standby_tuple()
            ),
            via=ep,
        )
        if (
            self._incarnations.get(nid) == msg.incarnation
            and nid in self.grid.nodes
        ):
            # join RETRY from a node we already admitted: its Welcome was
            # lost in flight — re-send it, change no membership state
            self.monitor.heartbeat(nid, now)
            return [welcome]
        restarted = nid in self.grid.nodes
        # a NEW incarnation under this id is a new process: anything the old
        # process claimed to hold may have died with it (or its disk) — its
        # own fresh adverts will restore the holder map from what survived
        self._drop_ckpt_holder(nid)
        prev_inc = self._incarnations.get(nid)
        prev_ep = self.book.get(nid)
        if prev_inc is not None and prev_ep is not None and prev_ep != ep:
            # id reclaimed from a different endpoint: remember the superseded
            # process so a late heartbeat from it gets a Shutdown reply
            self._superseded[nid] = (prev_inc, prev_ep)
        self.book[nid] = ep
        self._incarnations[nid] = msg.incarnation
        self.unreachable.discard(nid)
        self._digest_static = None  # membership changed
        # a new incarnation is a new process: its predecessor's inter-arrival
        # history (and the death gap since) must not poison the detector —
        # this covers the fast same-endpoint restart where the monitor state
        # is still UP and HeartbeatMonitor's own reset branch would not run
        self.monitor.detector.remove(nid)
        self.monitor.heartbeat(nid, now)
        if self.gossip is not None:
            # the probe ring adopts the admission: fresh ALIVE record at
            # the cluster incarnation (a predecessor's DEAD record must
            # not shadow the process the master just vouched for), and a
            # fresh grace window against rumors that predate it
            self._gossip_roster()
            self.gossip.reset_member(nid, msg.incarnation)
            self._gossip_admitted[nid] = now
            self._hub_speakers.discard(nid)  # re-learned per process
        log.info("master: node %d joined from %s:%d", nid, msg.host, msg.port)
        out = [welcome]
        out.extend(self._broadcast(self._address_book()))
        if restarted:
            # same identity re-joining before the detector noticed the crash:
            # its workers are fresh and unconfigured, so member_up's no-op is
            # wrong — force the Prepare/Confirm handshake for everyone
            log.info("master: node %d restarted -> reorganize", nid)
            out.extend(self.grid.reorganize())
        else:
            out.extend(self.grid.member_up(nid))
        return out

    def _on_heartbeat(self, msg: cl.Heartbeat, now: float) -> list[Envelope]:
        node_id, incarnation = msg.node_id, msg.incarnation
        if node_id not in self.book:
            # A heartbeat from a node this master has never admitted: either a
            # stale beat from an expelled node, or — the dangerous case — this
            # is a REPLACEMENT master (restarted on the seed endpoint, empty
            # book) and the sender is a healthy member of its predecessor.
            # Its sends all succeed, so the node's failure counter never
            # trips; without a reply it heartbeats into the void forever.
            # Tell it to re-run the join handshake at its advertised
            # endpoint — and solicit its checkpoint adverts NOW, so a
            # replacement master's empty holder registry repopulates
            # before the first restore asks for it (not only after the
            # full rejoin lands).
            if msg.port > 0:
                via = cl.Endpoint(msg.host, msg.port)
                _SOLICITS.inc()
                return [
                    Envelope(
                        f"node:{node_id}",
                        cl.Rejoin("unknown-node", self.epoch),
                        via=via,
                    ),
                    Envelope(
                        f"node:{node_id}",
                        st.AdvertSolicit("unknown-node"),
                        via=via,
                    ),
                ]
            return []
        if self._incarnations.get(node_id) != incarnation:
            # zombie: a partitioned process whose id was reclaimed by a newer
            # joiner — its stale heartbeats must not alias the current
            # holder's liveness. Tell it to stand down rather than letting it
            # run (and heartbeat) orphaned forever.
            sup = self._superseded.get(node_id)
            if sup is not None and sup[0] == incarnation:
                return [
                    Envelope(
                        f"node:{node_id}",
                        cl.Shutdown("superseded", self.epoch),
                        via=sup[1],
                    )
                ]
            return []
        if self.gossip is not None:
            # a member hub-heartbeating under a gossip-enabled config
            # negotiated down (legacy binary): the phi detector keeps
            # owning its liveness, and the ring's inevitable slander of
            # the never-acking member is ignored (_consume_gossip)
            self._hub_speakers.add(node_id)
        event = self.monitor.heartbeat(node_id, now)
        if event is not None and node_id not in self.grid.nodes:
            # silence marked it unreachable but the process lives: rejoin it
            log.info("master: node %d heartbeat resumed -> rejoin", node_id)
            return self._readmit(node_id, now)
        return []

    def _on_round_complete(
        self, line_id: int, r: int, latency_s: float, done: int, n: int
    ) -> None:
        """Per-round observability (SURVEY.md §6): one JSONL record per
        completed line-round — latency, contributors at threshold, config —
        the watchdog's completion signal (retires the round's deadline),
        and one tick of straggler evidence into the AdaptiveController
        (RESILIENCE.md "Tier 5"): the master gathers the grid's lag map
        and the registry counters HERE and hands them in, so the
        controller stays a pure, replayable state machine."""
        if self.watchdog is not None:
            self.watchdog.round_completed(line_id, r)
        if self.adapt is not None and self.active:
            # the O(lines x workers) lag merge + counter snapshot are only
            # read on the window-boundary call — skip the gather otherwise
            if self.adapt.deciding_next:
                lags = self.grid.worker_lags()
                counters = {
                    "restarts": _EV_RESTARTS.value,
                    "reconnects": _EV_RECONNECTS.value,
                    "drops": _EV_DROPS.value,
                    "reorgs": _EV_REORGS.value,
                }
                bandwidth = self._gather_bandwidth()
            else:
                lags, counters, bandwidth = {}, {}, None
            pol = self.adapt.observe_round(
                r, lags, counters, latency_s=latency_s, bandwidth=bandwidth
            )
            if pol is not None:
                # rounds started from now on (this very completion's
                # window refill included) carry the new stamp; the level
                # rides the digest's per-tick round state, so the standby
                # learns it within one lease heartbeat
                self.grid.set_policy(pol)
                if self.metrics is not None and self.adapt.last_decision:
                    self.metrics.log_event(
                        kind="adapt", **self.adapt.last_decision
                    )
        if self.metrics is not None:
            self.metrics.log_event(
                kind="round",
                line=line_id,
                round=r,
                latency_s=round(latency_s, 6),
                completions=done,
                workers=n,
                config=self.grid.config_id,
                data_bytes=self.config.metadata.data_size * 4,
            )

    def _forget_endpoint_rows(self, node_id: int) -> None:
        """Membership just expelled ``node_id``: evict its per-endpoint
        transport telemetry rows (tx/rx/stream/reconnect gauges are
        otherwise cumulative forever — a dead peer's frozen row polluted
        every later snapshot, and PR 10's bandwidth arm had to
        special-case it). A re-joining process regrows rows from zero."""
        ep = self.book.get(node_id)
        if ep is not None:
            self.transport.forget_endpoint(ep)

    def _gather_bandwidth(self) -> dict[str, float] | None:
        """Per-endpoint cumulative tx+rx bytes from PR-9's transport
        gauges, as visible to THIS process (in-process transports all
        report through the shared registry collector) — the bandwidth
        evidence arm's input, gathered only on window-boundary calls.
        None when the arm is disabled (skips the collector sweep)."""
        if self.adapt is None or self.adapt.config.bw_degrade_ratio <= 0:
            return None
        prefix = "transport.endpoint."
        out: dict[str, float] = {}
        for key, value in _metrics.REGISTRY.snapshot().items():
            if not key.startswith(prefix):
                continue
            endpoint, _, field = key[len(prefix):].rpartition(".")
            if field in ("tx_bytes", "rx_bytes") and endpoint:
                out[endpoint] = out.get(endpoint, 0.0) + float(value)
        return out

    def _standby_tuple(self) -> tuple[tuple[str, int], ...]:
        return tuple((ep.host, ep.port) for ep in self.standby_eps)

    def _address_book(self) -> cl.AddressBook:
        return cl.AddressBook(
            tuple(
                (nid, ep.host, ep.port)
                for nid, ep in sorted(self.book.items())
                if nid not in self.unreachable
            ),
            self.epoch,
            self._standby_tuple(),
        )

    async def _poll_detector(self) -> None:
        if not self.active or self._fenced_out:
            return  # passive standby / deposed leader: no scheduling
        now = self.clock()
        out: list[Envelope] = []
        expelled = False
        if self.gossip is not None:
            out2, expelled2 = self._consume_gossip(now)
            out.extend(out2)
            expelled = expelled or expelled2
        for event in self.monitor.poll(now):
            if event.state is MemberState.UNREACHABLE:
                log.info(
                    "master: node %d unreachable (phi=%.1f)",
                    event.node_id,
                    event.phi,
                )
                out.extend(self.grid.member_unreachable(event.node_id))
                # stop dialing and advertising the silent endpoint, but keep
                # its book entry + detector state: if the process is alive and
                # heartbeats resume, _on_heartbeat re-lines it without a new
                # JoinCluster; a genuine restart re-joins explicitly.
                self.unreachable.add(event.node_id)
                self._forget_endpoint_rows(event.node_id)
                self._digest_static = None  # membership changed
                expelled = True
        if expelled:
            out.extend(self._broadcast(self._address_book()))
        # at-most-once delivery can eat a Prepare (e.g. into a connection
        # whose peer just restarted): re-send to unconfirmed workers. The
        # same discipline covers Start/Complete loss: an in-flight round
        # with no completion progress for several intervals is re-Started
        # at the workers that never reported (idempotent on every path —
        # under sustained loss a bounded round window wedges without this)
        interval = self.config.master.heartbeat_interval_s
        for lm in self.grid.line_masters.values():
            out.extend(lm.reprepare_pending(2.0 * interval))
            out.extend(lm.restart_stalled(5.0 * interval))
        # the digest doubles as the leader's lease heartbeat: one per poll
        # tick keeps the standby's phi detector renewed even when no state
        # changed
        out.extend(self._digest_envelopes())
        if out:
            await self.transport.send_all(out)
        if self.grid.is_done and not self._done.is_set():
            self._done.set()
            await self.transport.send_all(
                self._broadcast(cl.Shutdown("done", self.epoch))
                + self._standby_shutdowns("done")
            )

    def _consume_gossip(self, now: float) -> tuple[list[Envelope], bool]:
        """One subscriber pass over the gossip view (RESILIENCE.md
        "Tier 6"): mirror ALIVE/SUSPECT members into the phi monitor (a
        suspect is innocent until the suspicion times out — the hub's
        clock must never front-run the ring's verdict), then act on the
        edge events: a CONFIRMED death drives the exact
        ``member_unreachable`` path a phi expulsion always drove."""
        assert self.gossip is not None
        out: list[Envelope] = []
        expelled = False
        window = self.config.gossip.suspicion_window_s
        for nid in self.gossip.alive_or_suspect():
            if (
                nid not in self._hub_speakers
                and nid in self.book
                and nid not in self.unreachable
            ):
                event = self.monitor.heartbeat(nid, now)
                if event is not None and nid not in self.grid.nodes:
                    # the ring vouches for a member the grid dropped (a
                    # refutation landed after a phi expulsion): re-line it,
                    # exactly like the hub's heartbeat-resume path
                    log.info(
                        "master: gossip vouches node %d alive -> rejoin", nid
                    )
                    out.extend(self._readmit(nid, now))
        for gev in self.gossip.poll_events():
            nid = gev.node_id
            if nid < 0 or gev.status != gsp.DEAD:
                continue
            if self.gossip.status_of(nid) != gsp.DEAD:
                # a refutation (or direct frame) flipped the record back
                # between the confirm and this poll: the queued verdict
                # is already stale — acting on it would expel a node the
                # ring no longer believes dead, and under the asymmetric
                # partition no direct frame could ever heal it back
                continue
            if nid in self._hub_speakers:
                continue  # negotiated-down legacy member: the phi hub owns it
            if nid not in self.book or nid in self.unreachable:
                continue
            admitted = self._gossip_admitted.get(nid)
            if admitted is not None and now - admitted < window + \
                    self.config.gossip.probe_interval_s:
                # stale slander: this verdict's suspicion predates (or
                # straddles) the member's latest admission — shield the
                # fresh process and outrank the rumor so it dies out
                _GOSSIP_SHIELDED.inc()
                self.gossip.reset_member(nid, gev.incarnation + 1)
                continue
            log.info(
                "master: node %d confirmed dead by gossip (incarnation %d)",
                nid, gev.incarnation,
            )
            _GOSSIP_EXPULSIONS.inc()
            self.monitor.force_unreachable(nid, now)
            out.extend(self.grid.member_unreachable(nid))
            self.unreachable.add(nid)
            self._forget_endpoint_rows(nid)
            self._gossip_roster()
            self._digest_static = None
            expelled = True
        return out, expelled

    @property
    def rounds_completed(self) -> int:
        """Line-rounds completed across ALL configurations, not just the
        current one (re-organization replaces the line masters)."""
        return self.grid.total_completed


_incarnation_counter = itertools.count(1)


def _new_incarnation() -> int:
    """Unique per NodeProcess lifetime across processes on one host."""
    return (os.getpid() << 20) | (next(_incarnation_counter) & 0xFFFFF)


class NodeProcess:
    """Worker-node role: joins the seed, hosts one worker per dimension."""

    def __init__(
        self,
        seed: cl.Endpoint,
        data_source: DataSource,
        data_sink: DataSink,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        preferred_node_id: int = -1,
        join_retry_s: float = 0.5,
        allow_crash: bool = False,
        chaos_log: str | None = None,
        state_dir: str | None = None,
        replicas: int = 2,
    ) -> None:
        self.seed = seed
        self.data_source = data_source
        self.data_sink = data_sink
        self.preferred_node_id = preferred_node_id
        self.join_retry_s = join_retry_s
        # peer state transfer (statetransfer.py): when set, this node hosts
        # a chunk service over the delta-store directory, replicates its
        # saves to `replicas` peers, and can restore from peers on rejoin
        self.state_dir = state_dir
        self.replicas = replicas
        self.state: st.ChunkService | None = None
        self._chunk_store: st.ChunkStore | None = (
            st.ChunkStore(state_dir) if state_dir else None
        )
        # EVERY live replication task, not a single slot: a later save's
        # (insta-skipping) task must not shadow a still-running one at
        # stop() — all of them get cancelled at teardown
        self._replicate_tasks: set[asyncio.Task] = set()
        # chaos plumbing: the spec itself arrives with Welcome (one master
        # flag arms the cluster); allow_crash gates the `crash` fault to
        # REAL subprocesses (the CLI role sets it — an in-process test
        # harness must record a suppressed crash, not kill pytest)
        self.allow_crash = allow_crash
        self.chaos_log = chaos_log
        self._chaos_t0: float | None = None
        self.incarnation = _new_incarnation()
        self.node_id: int | None = None
        self.node: AllreduceNode | None = None
        self.config: AllreduceConfig | None = None
        self.book = cl.AddressBook(())
        self._endpoints: dict[int, cl.Endpoint] = {}
        self.transport = RemoteTransport(host, port)
        self.transport.set_route("master", seed)
        self.transport.set_prefix_route("line_master", lambda _lid: seed)
        self.transport.set_prefix_route("worker", self._peer_endpoint)
        # lambda, not a bound .get: the AddressBook handler REASSIGNS
        # self._endpoints wholesale on every membership change
        self.transport.set_prefix_route(
            "ckpt", lambda nid: self._endpoints.get(nid)
        )
        self.transport.set_prefix_route("gossip", self._gossip_peer_endpoint)
        # SWIM gossip membership (control/gossip.py): built at Welcome
        # when the config arms it — this node then probes peers instead
        # of heartbeating into the master's phi hub
        self.gossip: gsp.GossipState | None = None
        self._gossip_agent: gsp.GossipAgent | None = None
        self._heartbeat_task: asyncio.Task | None = None
        self._join_task: asyncio.Task | None = None
        self._welcomed = asyncio.Event()
        self._shutdown = asyncio.Event()
        self.shutdown_reason: str | None = None
        # master-loss detection: consecutive failed sends to the master seed.
        # The reference restarts its seed JVM and workers re-join via Akka
        # Cluster; here the node notices its heartbeats bouncing and re-runs
        # the join handshake against whatever master now owns the endpoint —
        # or, with a standby list distributed via Welcome/AddressBook, WALKS
        # that list and re-joins the promoted leader (master HA,
        # RESILIENCE.md "Tier 4").
        self._master_send_failures = 0
        self._rejoining = False
        self._left = False  # graceful leave announced; never rejoin after
        self._rejoin_task: asyncio.Task | None = None
        self.rejoin_after_failures = 3
        # leadership-epoch fencing watermark: set by the Welcome that
        # admitted us; anything a master of an OLDER epoch sends afterwards
        # is dropped (split-brain prevention)
        self.master_epoch = -1
        self.standbys: list[cl.Endpoint] = []
        # joins sent per candidate endpoint before walking to the next
        self.failover_walk_attempts = 3
        self.transport.on_send_error = self._on_send_error
        self.transport.on_send_ok = self._on_send_ok
        # workload-resilience seam (RESILIENCE.md "Tier 7"): the trainer
        # loop riding this node can FOLLOW the cluster — on_members fires
        # with the AddressBook's live node ids after every membership
        # change (event-loop context: keep it a cheap cell swap), and
        # policy_wire() reads the newest RoundPolicy wire stamp the
        # workers observed, so one leader controller can drive the
        # trainer's ICI compression too
        self.on_members: Callable[[tuple[int, ...]], None] | None = None
        # the carried policy-wire observation: workers are rebuilt on
        # every re-Welcome (fresh last_policy), but the leader's ladder
        # level did not change just because WE re-joined — the last
        # observed stamp bridges the gap until the new epoch's first
        # Start re-stamps it (otherwise every re-mesh would flap the
        # trainer to full fidelity and back, two spurious re-jits)
        self._policy_wire = ""

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        ep = await self.transport.start()
        self.transport.register(
            "client", lambda msg: self._on_cluster_msg(msg)
        )
        # The joiner owns the handshake retry (Akka Cluster joins the same
        # way): re-send JoinCluster until Welcomed — the Welcome can vanish
        # into a connection whose peer only just noticed we restarted.
        join = cl.JoinCluster(
            ep.host, ep.port, self.preferred_node_id, self.incarnation
        )

        async def join_until_welcomed() -> None:
            while not self._welcomed.is_set():
                await self.transport.send(Envelope("master", join))
                await asyncio.sleep(self.join_retry_s)

        self._join_task = observed_task(join_until_welcomed(), name="node-join")

    async def wait_welcomed(self, timeout: float = 10.0) -> int:
        await asyncio.wait_for(self._welcomed.wait(), timeout)
        assert self.node_id is not None
        return self.node_id

    async def run_until_shutdown(self, timeout: float | None = None) -> str:
        await asyncio.wait_for(self._shutdown.wait(), timeout)
        return self.shutdown_reason or "done"

    async def leave(self) -> None:
        """Graceful departure (the reference's Cluster leave)."""
        # Stop heartbeating BEFORE announcing the leave, and latch _left so a
        # master reply to an already-in-flight heartbeat (Rejoin from a
        # replacement that no longer knows us) cannot drag this node back
        # into the cluster on its way out.
        self._left = True
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        if self._gossip_agent is not None:
            # stop probing (and answering) on the way out: lingering acks
            # from a leaver would keep vouching for a dead membership
            self._gossip_agent.cancel()
        if self.node_id is not None:
            await self.transport.send(
                Envelope("master", cl.LeaveCluster(self.node_id))
            )

    async def stop(self) -> None:
        if self._gossip_agent is not None:
            await self._gossip_agent.stop()
        for attr in ("_heartbeat_task", "_join_task", "_rejoin_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)
        for task in list(self._replicate_tasks):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._replicate_tasks.clear()
        await self.transport.stop()

    # -- routing helpers -------------------------------------------------------

    def _peer_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        if self.config is None:
            return None
        # dict lookup: this resolver runs per outgoing chunk on the data path
        return self._endpoints.get(worker_id // self.config.master.dimensions)

    def policy_wire(self) -> str:
        """The newest RoundPolicy wire stamp this node's workers observed
        ("" until any Start arrived, or when the leader runs full
        fidelity). Survives worker rebuilds (re-Welcome / rejoin): the
        carried value answers until the new epoch's first Start. Reads +
        one reference write, GIL-atomic — safe to poll from a learner
        thread (train/cluster.py's compress-follows-policy loop)."""
        if self.node is not None and self.node.workers:
            w = max(
                self.node.workers.values(),
                key=lambda w: w.last_policy_round,
            )
            if w.last_policy_round >= 0:
                self._policy_wire = w.last_policy.wire
        return self._policy_wire

    def _gossip_peer_endpoint(self, node_id: int) -> cl.Endpoint | None:
        if node_id < 0:
            # the master's ring address follows the leader this node
            # believes in (self.seed is repointed by the failover walk)
            return self.seed
        return self._endpoints.get(node_id)

    # -- cluster protocol ------------------------------------------------------

    #: master-bound destinations for the loss-detection counter (the
    #: gossip ring's master address fails exactly when the master does)
    _MASTER_DESTS = ("master", gsp.gossip_addr(gsp.MASTER_ID))

    def _on_send_ok(self, ep: cl.Endpoint, env: Envelope) -> None:
        # rejoin triggers on CONSECUTIVE master-send failures: a transient
        # blip must not accumulate forever toward a spurious cluster-wide
        # rejoin (the master rarely sends anything back in steady state, so
        # resetting only on inbound traffic would never clear the counter)
        if env.dest in self._MASTER_DESTS:
            self._master_send_failures = 0

    def _on_send_error(self, ep: cl.Endpoint, env: Envelope) -> None:
        if self.state is not None:
            # a lost replication push must be re-pushed next round, not
            # dedup-skipped forever (statetransfer.note_send_failure)
            self.state.note_send_failure(env)
        if (
            env.dest not in self._MASTER_DESTS
            or not self._welcomed.is_set()
            or self._left
        ):
            return
        self._master_send_failures += 1
        if self.gossip is not None:
            # decentralized membership: our own failed sends are ONE
            # vantage point — the master may be fine behind a bad direct
            # link (indirect probes still vouch for it). The walk is
            # triggered by the ring's CONFIRMED verdict on gossip:-1
            # (_on_gossip_events), never by direct loss alone.
            return
        if (
            self._master_send_failures >= self.rejoin_after_failures
            and not self._rejoining
        ):
            self._rejoining = True
            log.info(
                "node %s: master unreachable (%d failed sends) -> re-join",
                self.node_id,
                self._master_send_failures,
            )
            self._rejoin_task = observed_task(
                self._rejoin_master(), name="node-rejoin"
            )

    def _point_master(self, ep: cl.Endpoint) -> None:
        """Route all master-bound traffic (joins, heartbeats, line-master
        confirms/completions, manifest requests) at ``ep`` — the whole
        control-plane conversation follows the leader we believe in."""
        self.seed = ep
        self.transport.set_route("master", ep)
        self.transport.set_prefix_route(
            "line_master", lambda _lid, _ep=ep: _ep
        )

    async def _rejoin_master(self) -> None:
        """The master endpoint stopped answering: run the join handshake
        again (keeping our preferred id) against whatever owns the endpoint
        — and when THAT keeps going unanswered, walk the standby list the
        leader distributed via Welcome/AddressBook (master-HA failover:
        the promoted standby answers once its lease on the dead leader
        expires; until then it ignores joins, so the walk just cycles).

        A rejoin wipes this node's worker state, so it presents a NEW
        incarnation: a replacement master welcomes it normally, and a master
        that was merely unreachable for a moment treats it as a restart and
        re-runs the Prepare handshake — either way the fresh workers get
        configured instead of silently wedging.
        """
        try:
            if self._heartbeat_task is not None:
                self._heartbeat_task.cancel()
                self._heartbeat_task = None
            if self._join_task is not None:
                # the ORIGINAL join task retries until _welcomed is set and
                # may still be sleeping off its first retry interval:
                # clearing _welcomed below would resurrect it, and its join
                # carries the STALE incarnation — the master could admit
                # that ghost identity first and drop the bumped
                # incarnation's heartbeats as a zombie's until this loop's
                # join lands (race found by the chaos partition test)
                self._join_task.cancel()
                self._join_task = None
            self._welcomed.clear()
            self.incarnation = _new_incarnation()
            join = cl.JoinCluster(
                self.transport.endpoint.host,
                self.transport.endpoint.port,
                self.node_id if self.node_id is not None else -1,
                self.incarnation,
            )
            candidates = [self.seed] + [
                s for s in self.standbys if s != self.seed
            ]
            lap = 0
            while not self._welcomed.is_set() and not self._shutdown.is_set():
                target = candidates[lap % len(candidates)]
                if lap > 0 and len(candidates) > 1:
                    _WALKS.inc()
                    _flight.note(
                        "failover", event="walk", node=self.node_id,
                        endpoint=str(target),
                    )
                    log.info(
                        "node %s: walking to candidate master %s",
                        self.node_id, target,
                    )
                self._point_master(target)
                for _ in range(max(1, self.failover_walk_attempts)):
                    if self._welcomed.is_set() or self._shutdown.is_set():
                        break
                    await self.transport.send(Envelope("master", join))
                    await asyncio.sleep(self.join_retry_s)
                lap += 1
        finally:
            self._rejoining = False
            self._master_send_failures = 0

    def _fenced(self, msg: Any) -> bool:
        """True when ``msg`` carries a leadership epoch OLDER than the one
        that welcomed us — a zombie deposed master still sending after a
        failover. The fence is the split-brain guarantee: whatever the old
        leader still believes, its round triggers, address books and
        shutdowns no longer move this node (RESILIENCE.md "Tier 4")."""
        epoch = getattr(msg, "epoch", None)
        if isinstance(epoch, int) and 0 <= epoch < self.master_epoch:
            _FENCED.inc()
            _flight.note(
                "failover", event="fenced", node=self.node_id,
                msg=type(msg).__name__, epoch=epoch,
                current=self.master_epoch,
            )
            log.info(
                "node %s: fenced stale-epoch %d %s (current epoch %d)",
                self.node_id, epoch, type(msg).__name__, self.master_epoch,
            )
            return True
        return False

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        # Welcome is EXEMPT from the fence: a node actively (re)joining has
        # abandoned its cluster state and follows WHOEVER admits it — an
        # operator-restarted replacement master legitimately starts at
        # epoch 1 again, and strict ratcheting would fence it out forever
        # once any failover had happened. A zombie that admits a walking
        # node is only a transient capture: it is stood down through its
        # own digest stream, the node's sends fail again, and the next walk
        # lands at the live leader. A node that is already settled ignores
        # stray Welcomes via the _welcomed guard below.
        if isinstance(msg, cl.Welcome):
            self._master_send_failures = 0
            return self._on_welcome(msg)
        if self._fenced(msg):
            return []  # a zombie master talking must not reset anything
        self._master_send_failures = 0  # the master is talking to us
        if isinstance(msg, cl.AddressBook):
            self.book = msg
            prev = self._endpoints
            self._endpoints = {
                nid: cl.Endpoint(host, port) for nid, host, port in msg.entries
            }
            # a peer the membership dropped (expulsion or leave) takes its
            # per-endpoint transport telemetry rows with it — cumulative
            # gauges must not carry dead peers forever (the master does
            # the same at its expulsion sites)
            live = set(self._endpoints.values())
            for ep in set(prev.values()) - live:
                self.transport.forget_endpoint(ep)
            # a standby registering mid-run reaches us here (Welcome only
            # covers the join); the walk order follows the leader's list
            self.standbys = [
                cl.Endpoint(h, p) for h, p in msg.standbys
            ]
            if self.gossip is not None:
                # the book is the authoritative roster: expelled members
                # leave the ring, admitted ones get fresh ALIVE records
                self.gossip.set_members(
                    set(self._endpoints) | {gsp.MASTER_ID}
                )
            if self.on_members is not None:
                self.on_members(msg.node_ids())
            return []
        if isinstance(msg, st.AdvertSolicit):
            # a (replacement) master wants to know what this disk holds —
            # re-advertise everything without waiting for a full rejoin
            return self._advert_envelopes()
        if isinstance(msg, cl.Shutdown):
            self.shutdown_reason = msg.reason
            self._shutdown.set()
            return []
        if isinstance(msg, cl.Rejoin):
            # the master does not recognize us (replacement master on the
            # seed endpoint): run the join handshake again, fresh incarnation
            # — unless we are the reason it doesn't know us (graceful leave)
            if self._welcomed.is_set() and not self._rejoining and not self._left:
                log.info(
                    "node %s: master replied Rejoin(%s) -> re-join",
                    self.node_id,
                    msg.reason,
                )
                self._rejoining = True
                self._rejoin_task = observed_task(
                    self._rejoin_master(), name="node-rejoin"
                )
            return []
        raise TypeError(f"node cannot handle {type(msg).__name__}")

    def _on_welcome(self, msg: cl.Welcome) -> list[Envelope]:
        if self._welcomed.is_set():
            return []  # duplicate Welcome from a join retry race
        if self._heartbeat_task is not None:  # re-welcome after master loss
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        # the fencing watermark tracks the CURRENT leader (not a max over
        # history): fencing protects a SETTLED node from masters older
        # than the one it follows — a fresh admission re-bases it, so an
        # epoch-1 replacement after a crashed epoch-2 leader still works
        prev_epoch = self.master_epoch
        self.master_epoch = msg.epoch
        self.standbys = [cl.Endpoint(h, p) for h, p in msg.standbys]
        self.config = AllreduceConfig.from_json(msg.config_json)
        # the wire-compression knob arrives with the config, like every
        # other knob: payloads we send from now on ride at the configured
        # width (decode is stateless — the flag travels per frame)
        self.transport.wire_f16 = self.config.metadata.wire_dtype == "f16"
        self.transport.retry_policy = self.config.master.retry
        # the data-plane knobs arrive the same way: connections made
        # BEFORE Welcome (the join itself) were legacy stream-0 links and
        # stay valid; new payload senders stripe (and split, and schedule)
        # from here on
        self.transport.configure_data_plane(self.config.data_plane)
        self.node_id = msg.node_id
        dims = self.config.master.dimensions
        if self.config.chaos.enabled:
            from akka_allreduce_tpu.control.chaos import ChaosInjector

            # anchor the fault timeline ONCE per process: a rejoin rebuilds
            # the injector (the role may even change with the assigned id)
            # but must not restart partition/stall windows from zero
            if self._chaos_t0 is None:
                self._chaos_t0 = time.monotonic()
            prev = self.transport.chaos
            if (
                prev is not None
                and prev.seed == self.config.chaos.seed
                and prev.spec == self.config.chaos.spec
                and prev.role == msg.node_id
            ):
                pass  # re-welcome under the same identity: keep the injector
            else:
                inj = ChaosInjector(
                    self.config.chaos.seed,
                    self.config.chaos.spec,
                    role=msg.node_id,
                    dims=dims,
                    t0=self._chaos_t0,
                    allow_crash=self.allow_crash,
                    log_path=self.chaos_log,
                )
                if prev is not None:
                    # a rejoin (or id change) rebuilds the decision streams,
                    # but the process's event HISTORY must survive — the
                    # exit-time log write reports the whole run, not just
                    # the last membership epoch
                    inj.events = list(prev.events) + inj.events
                self.transport.chaos = inj
        self.node = AllreduceNode(
            msg.node_id,
            dims,
            self.data_source,
            self.data_sink,
            self.config.metadata,
            self.config.threshold,
            self.config.worker,
            # cross-epoch round dedup: the rounds the PREVIOUS instance's
            # workers already flushed stay flushed — a SUCCESSOR epoch
            # re-issuing one of those round ids (stale digest) gets a
            # CompleteAllreduce re-assert, never a second application.
            # Carried ONLY when the welcoming epoch is strictly newer: a
            # promoted standby continues the dead leader's numbering (the
            # overlap is real), but a from-scratch replacement master
            # (equal or lower epoch) legitimately RE-NUMBERS from 0 — a
            # carried floor there would turn this node into a silent
            # yes-asserter for thousands of rounds it never ran. Within
            # one live master's lineage round numbers never regress, so
            # dropping the floor on an equal-epoch re-welcome is safe.
            flush_floors=(
                self.node.flush_floors()
                if self.node is not None and msg.epoch > prev_epoch
                else None
            ),
        )
        for dim in range(dims):
            wid = msg.node_id * dims + dim
            self.transport.register(
                f"worker:{wid}",
                # worker traffic is fenced too: a deposed master's
                # Prepare/StartAllreduce must not reconfigure or trigger us
                lambda m, _wid=wid: (
                    [] if self._fenced(m) else self.node.handle(_wid, m)
                ),
            )
        self.transport.register_prefix(
            "node", lambda _nid, m: self._on_cluster_msg(m)
        )
        out: list[Envelope] = []
        if self._chunk_store is not None:
            # (re)build the chunk service under the assigned identity — the
            # STORE persists across rejoins (it is the disk), the service's
            # per-peer push dedup resets with the membership epoch
            self.state = st.ChunkService(
                self.transport,
                msg.node_id,
                self._chunk_store,
                replicas=self.replicas,
                retry=self.config.master.retry,
            )
            self.transport.register(
                st.ChunkService.addr(msg.node_id), self.state.handle
            )
            # the disk survived whatever restarted us: advertise everything
            # it holds — our OWN state and any replica holdings — so the
            # master's holder map (wiped of our old incarnation's entries)
            # re-learns what actually survived on this disk
            out.extend(self._advert_envelopes())
        if self.config.gossip.enabled:
            # decentralized membership: NO hub heartbeat loop — this node
            # joins the probe ring instead (the master is member -1). A
            # node welcomed WITHOUT the section (a legacy master) lands in
            # the else-branch and heartbeats exactly as before — the
            # negotiate-down contract, pinned in tests/test_gossip.py.
            self._start_gossip(msg.node_id)
        else:
            if self._gossip_agent is not None:
                # re-welcomed by a gossip-DISABLED master (an operator-
                # restarted replacement without --gossip): the old probe
                # loop must die with the old cluster, or it would keep
                # probing a stale roster, eventually confirm the OLD
                # master dead, and walk this healthily-attached node
                # away from the live one
                self._gossip_agent.cancel()
                self._gossip_agent = None
                self.gossip = None
            interval = self.config.master.heartbeat_interval_s
            self._heartbeat_task = observed_task(
                run_periodic(interval, self._send_heartbeat),
                name=f"node-{msg.node_id}-heartbeat",
            )
        self._welcomed.set()
        log.info("node %d welcomed (dims=%d)", msg.node_id, dims)
        return out

    # -- gossip membership (RESILIENCE.md "Tier 6") ----------------------------

    def _start_gossip(self, node_id: int) -> None:
        """(Re)build this node's ring identity under the welcomed id. A
        rejoin re-welcome cancels the old probe loop first — a superseded
        identity must not keep answering probes under a stale address."""
        if self._gossip_agent is not None:
            self._gossip_agent.cancel()
        ep = self.transport.endpoint
        self.gossip = gsp.GossipState(
            node_id,
            self.incarnation,
            self.config.gossip,
            host=ep.host,
            port=ep.port,
        )
        # roster: everyone in the current address book plus the master;
        # refreshed on every AddressBook broadcast
        self.gossip.set_members(set(self._endpoints) | {gsp.MASTER_ID})
        self._gossip_agent = gsp.GossipAgent(
            self.transport,
            self.gossip,
            clock=time.monotonic,
            # a node mid-rejoin (or shutting down) must go quiet: its
            # probes would carry a stale incarnation and its acks would
            # vouch for an identity it has abandoned
            gate=lambda: self._welcomed.is_set()
            and not self._shutdown.is_set(),
            on_message=self._on_gossip_leader_ping,
            on_events=self._on_gossip_events,
        )
        self._gossip_agent.start()

    def _on_gossip_leader_ping(self, msg) -> None:
        """Leadership discovery through the ring: a promoted standby joins
        the ring as member -1 under its bumped epoch and PROBES us from
        its own endpoint. Without this hook those pings would keep our
        master record ALIVE (so the confirmed-dead walk never fires)
        while our master-bound traffic — acks included — still flowed to
        the DEAD seed: the promoted master would read our silence as
        death and expel the whole cluster. A master ping from a NEW
        endpoint at >= the incarnation we know repoints the master route
        and re-runs the join handshake there (the same walk a confirmed
        death starts, aimed by the ring instead of cycling candidates);
        a deposed zombie's lower incarnation cannot steal the route."""
        if (
            not isinstance(msg, gsp.Ping)
            or msg.sender != gsp.MASTER_ID
            or msg.port <= 0
        ):
            return None
        ep = cl.Endpoint(msg.host, msg.port)
        if ep == self.seed:
            return None
        rec = self.gossip.members.get(gsp.MASTER_ID) if self.gossip else None
        if rec is not None and msg.incarnation < rec.incarnation:
            return None  # stale leader identity: ignore
        log.info(
            "node %s: master ring identity moved to %s (incarnation %d) "
            "-> re-join",
            self.node_id, ep, msg.incarnation,
        )
        self._point_master(ep)
        if (
            self._welcomed.is_set()
            and not self._rejoining
            and not self._left
        ):
            self._rejoining = True
            self._rejoin_task = observed_task(
                self._rejoin_master(), name="node-rejoin"
            )
        return None

    def _on_gossip_events(self, events: list[gsp.GossipEvent]) -> None:
        """Subscriber drain: the only verdict a NODE acts on is the ring
        confirming the MASTER dead — that (not direct send loss) starts
        the standby walk, so a bad direct link to the leader can no
        longer make a healthy node abandon its membership."""
        for ev in events:
            if (
                ev.node_id == gsp.MASTER_ID
                and ev.status == gsp.DEAD
                and self._welcomed.is_set()
                and not self._rejoining
                and not self._left
            ):
                log.info(
                    "node %s: gossip confirmed the master dead -> re-join",
                    self.node_id,
                )
                self._rejoining = True
                self._rejoin_task = observed_task(
                    self._rejoin_master(), name="node-rejoin"
                )

    # -- peer state transfer ---------------------------------------------------

    def _advert_envelopes(self) -> list[Envelope]:
        """CheckpointAdverts for everything this node's disk holds — its
        OWN state and any replica holdings. Rides every Welcome, and is
        re-sent on demand when a (replacement) master solicits
        (``st.AdvertSolicit``) so an empty holder registry repopulates
        without waiting for rejoin churn."""
        if self.state is None or self._chunk_store is None:
            return []
        out: list[Envelope] = []
        nid = self.state.node_id
        latest = self._chunk_store.latest()
        if latest is not None:
            out.append(
                Envelope(
                    "master",
                    st.CheckpointAdvert(nid, nid, latest[0], latest[1]),
                )
            )
        for origin in sorted(self._chunk_store.replica_origins()):
            held = self._chunk_store.latest(origin)
            if held is not None:
                out.append(
                    Envelope(
                        "master",
                        st.CheckpointAdvert(nid, origin, held[0], held[1]),
                    )
                )
        return out

    @staticmethod
    def _manifest_leaves(manifest_json: str) -> dict:
        """{leaf key: blob sha} of a manifest — restore evidence callers
        (the chaos-recover drill) can verify against replicas without
        racing this node's later saves and prunes."""
        import json

        try:
            return dict(json.loads(manifest_json).get("leaves", {}))
        except (ValueError, AttributeError):
            return {}

    def replica_peers(self) -> list[int]:
        """Live peers chosen as replica targets (address-book ring)."""
        if self.state is None:
            return []
        return self.state.replica_peers(list(self._endpoints))

    async def save_state(self, step: int, state: dict) -> dict | None:
        """Delta-save a flat ``{name: array}`` state dict, advertise it to
        the master, and kick a bounded background replication to the K
        replica peers (skipped, counted, when one is already in flight).
        Returns the save stats, or None when no state dir is configured."""
        if self.state is None or self._chunk_store is None:
            return None
        # deliberately ON the event loop: ChunkStore is single-threaded by
        # design (prune sweeps tmp files; a concurrent thread's in-flight
        # write would be swept mid-publish), and the whole save is
        # synchronous — nothing else interleaves with it. Demo states are
        # small; big states belong to the train-side AsyncDeltaCheckpointer
        # whose writer THREAD owns its store exclusively.
        stats = self._chunk_store.save_state(step, state)
        latest = self._chunk_store.latest()
        assert latest is not None
        await self.transport.send(
            Envelope(
                "master",
                st.CheckpointAdvert(
                    self.state.node_id, self.state.node_id, latest[0], latest[1]
                ),
            )
        )
        peers = self.replica_peers()
        if peers:
            # replicate_latest self-skips (and COUNTS) when a round is
            # already in flight — no pre-check here, or the documented
            # replicate.skipped_busy metric would never fire on this path
            task = observed_task(
                self.state.replicate_latest(peers),
                name=f"node-{self.node_id}-replicate-{step}",
            )
            self._replicate_tasks.add(task)
            task.add_done_callback(self._replicate_tasks.discard)
        return stats

    async def restore_state(
        self, *, rounds: int = 3, give_up: Callable[[], bool] | None = None
    ) -> dict | None:
        """The rejoin restore path (RESILIENCE.md "Recovery"): prefer the
        local disk when it already holds the newest known step; otherwise
        pull the manifest's chunks from live peer holders — per-chunk
        retry/failover, resumable across ``rounds`` attempts with a FRESH
        holder map each time (a partition heal mid-restore changes who is
        reachable). Returns restore stats (``source`` disk|peer) or None
        when there is nothing to restore anywhere.

        ``give_up`` is the caller's OWN-PROGRESS evidence for the blind
        patience below: a callable answering True once the caller has
        demonstrably moved on (the cluster-node role passes its flushed-
        round count against a couple of save periods). Rounds completing
        THROUGH this node prove the master is alive and scheduling — so
        when the registry still answers "nothing known" while our rounds
        race past the first save window, waiting longer only pushes the
        first checkpoint further out (on a loaded box the restore
        coroutine shares the event loop with round traffic, and each
        manifest exchange can cost a second of queueing — patience that
        outruns a seeded early crash was exactly the chaos-recover
        failure mode). It caps ONLY the nothing-known patience: an active
        chunk pull (holders known) is never abandoned by it."""
        if self.state is None or self._chunk_store is None:
            return None
        hb_interval = (
            self.config.master.heartbeat_interval_s
            if self.config is not None
            else 0.5
        )
        t0 = time.perf_counter()
        reply = await self.state.request_manifest()
        latest = self._chunk_store.latest()
        if latest is None and (reply is None or reply.step < 0):
            # nothing local AND the master knows nothing: a REPLACEMENT
            # master's holder registry starts empty, and our request just
            # made it solicit adverts from every live member — patience
            # (one heartbeat interval per round) converges on the
            # re-advertised holders instead of abandoning live peer state.
            # But patience is bounded by EVIDENCE, not just rounds: on a
            # genuinely fresh cluster the master keeps ANSWERING "nothing
            # known" — after a few explicit misses (each of which already
            # triggered a solicit round-trip) we stop stalling the caller
            # (the cluster-node role gates its first SAVE on this decision,
            # and a long blind wait can push the first checkpoint past an
            # early failure). Silence (no answer at all) keeps the full
            # retry budget: that is a master still coming up.
            interval = hb_interval
            explicit_misses = 1 if reply is not None else 0
            members_seen = len(self._endpoints)

            async def _patient_ask() -> None:
                nonlocal reply, explicit_misses, members_seen
                for _ in range(max(1, rounds)):
                    if explicit_misses >= 3:
                        return
                    await asyncio.sleep(interval)
                    if len(self._endpoints) != members_seen:
                        # membership is still converging on the
                        # (replacement) master — every rejoin may bring a
                        # holder's adverts, so visible progress resets
                        # the miss budget
                        members_seen = len(self._endpoints)
                        explicit_misses = 0
                    r = await self.state.request_manifest()
                    if r is not None:
                        reply = r
                        if r.step >= 0:
                            return
                        explicit_misses += 1

            if give_up is None:
                await _patient_ask()
            else:
                # the caller's round progress bounds the blind window HARD
                # — checked between iterations alone it loses to one slow
                # exchange (the reply queues behind MB-scale round frames
                # in OUR inbox; a single manifest round-trip measured ~10
                # rounds of latency on a saturated box), so the whole
                # patience phase races a cheap progress poll and is
                # cancelled mid-await once rounds outrun it
                ask = observed_task(
                    _patient_ask(), name="restore-patience"
                )
                while not ask.done():
                    # the cut needs BOTH kinds of evidence: our own rounds
                    # outrunning the window AND at least one explicit
                    # "nothing known" answer — pure silence is reply
                    # LATENCY (a busy master, our own backlogged inbox),
                    # and cutting on it alone would abandon peer state a
                    # slow first reply was about to offer (seen against a
                    # freshly promoted standby mid-failover)
                    if explicit_misses >= 1 and give_up():
                        ask.cancel()
                        break
                    await asyncio.sleep(0.05)
                await asyncio.wait([ask])
        known_step = reply.step if reply is not None else -1
        if latest is not None and latest[0] >= known_step:
            stats = {
                "source": "disk",
                "step": latest[0],
                "seconds": round(time.perf_counter() - t0, 3),
                "complete": True,
                "leaves": self._manifest_leaves(latest[1]),
            }
            st.note_disk_restore(stats["seconds"])
            return stats
        if reply is None or reply.step < 0:
            return None
        stats = None
        for attempt in range(max(1, rounds)):
            if not reply.holders:
                break
            if not any(h in self._endpoints for h in reply.holders):
                # right after a (re)join the address book may still be in
                # flight: every ``ckpt:<holder>`` send would drop no_route
                # INSTANTLY, burning the whole per-chunk retry budget in
                # microseconds — give the book one heartbeat to land
                # before spending an attempt
                await asyncio.sleep(hb_interval)
            stats = await self.state.restore_from_peers(
                reply.step, reply.manifest_json, list(reply.holders)
            )
            if stats["complete"]:
                stats["seconds"] = round(time.perf_counter() - t0, 3)
                stats["leaves"] = self._manifest_leaves(reply.manifest_json)
                return stats
            if attempt + 1 < rounds:
                fresh = await self.state.request_manifest()
                if fresh is not None and fresh.step >= reply.step:
                    reply = fresh
        log.warning(
            "node %s: peer restore of step %d incomplete (holders=%s)",
            self.node_id, reply.step, list(reply.holders),
        )
        return stats

    async def _send_heartbeat(self) -> None:
        assert self.node_id is not None
        # advertise our server endpoint: a replacement master (same seed
        # address, empty address book) uses it to reply Rejoin
        ep = self.transport.endpoint
        await self.transport.send(
            Envelope(
                "master",
                cl.Heartbeat(self.node_id, self.incarnation, ep.host, ep.port),
            )
        )
