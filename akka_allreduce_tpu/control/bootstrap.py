"""Multi-process cluster bootstrap: master and node roles over TCP.

The reference's deployment (SURVEY.md §2 L4, §4.1): one ``main`` per role; the
master JVM binds a seed address, worker JVMs join via Akka Cluster, the grid
master organizes lines and rounds begin. Here:

- ``MasterProcess`` — binds the seed endpoint; owns the ``GridMaster`` (and
  thus every ``LineMaster``), the address book, and the phi-accrual
  ``HeartbeatMonitor``. Nodes join with ``JoinCluster``, are ``Welcome``d with
  an assigned node id + the cluster config, then heartbeat. Silence trips the
  detector -> ``member_unreachable`` -> re-organize (SURVEY.md §4.5); a
  late joiner re-runs the Prepare/Confirm handshake.
- ``NodeProcess`` — dials the seed, then hosts one ``AllreduceNode`` (one
  worker per grid dimension) whose scatter/reduce chunks travel as wire frames
  directly between nodes — the master never relays payloads, matching the
  reference where workers message peers point-to-point.

Addressing: ``master`` and every ``line_master:<id>`` live on the master
process; ``worker:<id>`` lives on node ``id // dims``; ``client:<port>`` is a
pre-welcome return address (the joiner does not yet know its node id);
``node:<id>`` receives master broadcasts (address book, shutdown).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import Any, Callable

from akka_allreduce_tpu.config import AllreduceConfig
from akka_allreduce_tpu.control import cluster as cl
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.failure import (
    HeartbeatMonitor,
    MemberState,
    PhiAccrualFailureDetector,
)
from akka_allreduce_tpu.control.grid_master import GridMaster
from akka_allreduce_tpu.control.node import AllreduceNode
from akka_allreduce_tpu.control.remote import (
    RemoteTransport,
    observed_task,
    run_periodic,
)
from akka_allreduce_tpu.control import statetransfer as st
from akka_allreduce_tpu.control.worker import DataSink, DataSource

log = logging.getLogger(__name__)


class MasterProcess:
    """Seed-node role: membership, line organization, round scheduling."""

    def __init__(
        self,
        config: AllreduceConfig,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        phi_threshold: float = 8.0,
        metrics=None,  # utils.metrics.MetricsLogger | None
    ) -> None:
        self.config = config
        self.clock = clock
        self.metrics = metrics
        self.watchdog = None
        if config.master.round_deadline_s > 0:
            from akka_allreduce_tpu.obs.watchdog import RoundWatchdog

            self.watchdog = RoundWatchdog(
                config.master.round_deadline_s, clock=clock
            )
        self.grid = GridMaster(
            config.threshold,
            config.master,
            config.line_master,
            on_round_complete=(
                self._on_round_complete if (metrics or self.watchdog) else None
            ),
            on_round_start=(
                self.watchdog.round_started if self.watchdog else None
            ),
            # a re-mesh abandons the replaced lines' rounds by design —
            # their deadlines must retire with them, not fire as stalls
            on_reorganize=(self.watchdog.reset if self.watchdog else None),
        )
        self.monitor = HeartbeatMonitor(
            PhiAccrualFailureDetector(
                threshold=phi_threshold,
                first_heartbeat_estimate=config.master.heartbeat_interval_s,
            )
        )
        self.book: dict[int, cl.Endpoint] = {}
        self.unreachable: set[int] = set()
        self._incarnations: dict[int, int] = {}
        # last superseded incarnation per node id: (incarnation, endpoint) of
        # the process whose id was reclaimed — so its surviving heartbeats can
        # be answered with a Shutdown instead of silently orphaning it
        self._superseded: dict[int, tuple[int, cl.Endpoint]] = {}
        self.transport = RemoteTransport(host, port)
        self.transport.wire_f16 = config.metadata.wire_dtype == "f16"
        self.transport.retry_policy = config.master.retry
        if config.chaos.enabled:
            from akka_allreduce_tpu.control.chaos import (
                MASTER_ROLE,
                ChaosInjector,
            )

            self.transport.chaos = ChaosInjector(
                config.chaos.seed,
                config.chaos.spec,
                role=MASTER_ROLE,
                dims=config.master.dimensions,
            )
        # peer checkpoint registry (statetransfer, RESILIENCE.md "Recovery"):
        # origin node id -> newest advertised manifest + which nodes hold it.
        # The master never touches chunk BYTES — it is the directory a
        # rejoiner consults for "what was my newest state, who has it".
        self._ckpt: dict[int, dict] = {}
        self.transport.register("master", self._on_cluster_msg)
        self.transport.register_prefix("line_master", self.grid.handle_for_line)
        self.transport.set_prefix_route("worker", self._worker_endpoint)
        self.transport.set_prefix_route("node", self.book.get)
        self.transport.set_prefix_route("ckpt", self._node_endpoint)
        self._poll_task: asyncio.Task | None = None
        self._done = asyncio.Event()

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> cl.Endpoint:
        ep = await self.transport.start()
        interval = self.config.master.heartbeat_interval_s
        self._poll_task = observed_task(
            run_periodic(interval, self._poll_detector), name="master-detector"
        )
        if self.watchdog is not None:
            self.watchdog.start()  # its own observed_task poll loop
        log.info("master listening on %s", ep)
        return ep

    async def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
            self._poll_task = None
        await self.transport.stop()

    async def run_until_done(self, timeout: float | None = None) -> None:
        """Wait until every line finished ``max_rounds`` (requires
        ``line_master.max_rounds >= 0``); the detector poll loop broadcasts
        ``Shutdown`` to all nodes the moment that happens."""
        await asyncio.wait_for(self._done.wait(), timeout)

    async def shutdown(self, reason: str = "terminated") -> None:
        """End an open-ended run from the outside (SIGTERM in the CLI, the
        chaos runner's --duration mode): broadcast ``Shutdown`` so nodes
        exit cleanly — flushing metrics and chaos logs — then release
        ``run_until_done``."""
        await self.transport.send_all(self._broadcast(cl.Shutdown(reason)))
        self._done.set()

    # -- routing helpers -------------------------------------------------------

    def _worker_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        nid = worker_id // self.config.master.dimensions
        return None if nid in self.unreachable else self.book.get(nid)

    def _node_endpoint(self, node_id: int) -> cl.Endpoint | None:
        return None if node_id in self.unreachable else self.book.get(node_id)

    def _broadcast(self, msg: Any) -> list[Envelope]:
        return [
            Envelope(f"node:{nid}", msg)
            for nid in sorted(self.book)
            if nid not in self.unreachable
        ]

    # -- cluster protocol ------------------------------------------------------

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        now = self.clock()
        if isinstance(msg, cl.JoinCluster):
            return self._on_join(msg, now)
        if isinstance(msg, cl.Heartbeat):
            return self._on_heartbeat(msg, now)
        if isinstance(msg, st.CheckpointAdvert):
            return self._on_ckpt_advert(msg)
        if isinstance(msg, st.ManifestRequest):
            return self._on_manifest_request(msg)
        if isinstance(msg, cl.LeaveCluster):
            self.monitor.leave(msg.node_id, now)
            out = self.grid.member_unreachable(msg.node_id)
            self.book.pop(msg.node_id, None)
            self.unreachable.discard(msg.node_id)
            self._incarnations.pop(msg.node_id, None)
            self._superseded.pop(msg.node_id, None)
            # a departed process can no longer serve chunks; its manifests
            # stay known (replicas may still hold the bytes)
            self._drop_ckpt_holder(msg.node_id)
            return out + self._broadcast(self._address_book())
        raise TypeError(f"master cannot handle {type(msg).__name__}")

    # -- peer checkpoint registry ----------------------------------------------

    #: manifests remembered per origin — enough to fall back past an
    #: owner-only newest step (saved, crashed before replication finished)
    _CKPT_KEEP = 3

    def _on_ckpt_advert(self, msg: st.CheckpointAdvert) -> list[Envelope]:
        rec = self._ckpt.setdefault(msg.origin, {"manifests": {}, "holders": {}})
        if msg.manifest_json:
            manifests = rec["manifests"]
            manifests[msg.step] = msg.manifest_json
            for old in sorted(manifests)[: -self._CKPT_KEEP]:
                manifests.pop(old)
        holders = rec["holders"]
        holders[msg.node_id] = max(holders.get(msg.node_id, -1), msg.step)
        log.info(
            "master: node %d holds checkpoint of node %d at step %d",
            msg.node_id, msg.origin, msg.step,
        )
        return []

    def _on_manifest_request(self, msg: st.ManifestRequest) -> list[Envelope]:
        """Answer with the NEWEST step that has at least one live holder
        other than the requester — not merely the newest step advertised:
        an owner that saved and then crashed before replication finished
        must get its replicas' (slightly older) step back, not an
        unservable newest step and a dead end.

        When NO step has a complete live holder (the owner died mid-
        replication — partial replicas hold chunks but never advertised),
        fall back to SCAVENGE mode: offer the OLDEST remembered manifest
        (its chunks were pushed first, so they are the most likely to have
        landed) with every live member as a candidate — content addressing
        plus the rejoiner's per-chunk ChunkMissing failover reassemble the
        state from whatever partial replicas hold; a chunk that truly
        exists nowhere surfaces as an incomplete restore, not a wedge."""
        rec = self._ckpt.get(msg.node_id)
        reply = st.ManifestReply(-1, "", ())
        if rec is not None and rec["manifests"]:
            for step in sorted(rec["manifests"], reverse=True):
                holders = tuple(
                    sorted(
                        nid
                        for nid, hstep in rec["holders"].items()
                        if hstep >= step
                        and nid != msg.node_id
                        and nid in self.book
                        and nid not in self.unreachable
                    )
                )
                if holders:
                    reply = st.ManifestReply(
                        step, rec["manifests"][step], holders
                    )
                    break
            else:
                candidates = tuple(
                    sorted(
                        nid
                        for nid in self.book
                        if nid != msg.node_id and nid not in self.unreachable
                    )
                )
                if candidates:
                    oldest = min(rec["manifests"])
                    log.info(
                        "master: no complete holder for node %d; offering "
                        "step %d for scavenge from %s",
                        msg.node_id, oldest, candidates,
                    )
                    reply = st.ManifestReply(
                        oldest, rec["manifests"][oldest], candidates
                    )
        return [Envelope(st.ChunkService.addr(msg.node_id), reply)]

    def _drop_ckpt_holder(self, node_id: int) -> None:
        """``node_id``'s process is gone (leave, or restart with a new
        incarnation): whatever its old process advertised holding is no
        longer servable — and after a disk loss may not even exist. Its
        next adverts rebuild the truth from what actually survived."""
        for rec in self._ckpt.values():
            rec["holders"].pop(node_id, None)

    def _on_join(self, msg: cl.JoinCluster, now: float) -> list[Envelope]:
        nid = msg.preferred_node_id
        ep = cl.Endpoint(msg.host, msg.port)
        # A join retry must resolve to the id assigned on the FIRST attempt,
        # even with auto-assigned ids (preferred -1): match by incarnation +
        # endpoint before minting a fresh id, or the retry would admit the
        # same process as a ghost second member
        for known_nid, inc in self._incarnations.items():
            if inc == msg.incarnation and self.book.get(known_nid) == ep:
                nid = known_nid
                break
        else:
            # a preferred id may be reclaimed from a NEW endpoint when its
            # previous holder is dead (crashed on another port) — only a
            # LIVE member's identity is protected from takeover
            taken = (
                nid in self.book
                and self.book[nid] != ep
                and nid in self.grid.nodes
            )
            if nid < 0 or taken:
                # an endpoint hosts at most one node process, so a fresh
                # incarnation from a booked endpoint is that node reborn —
                # reclaim its id; otherwise mint the next one
                reborn = next(
                    (k for k, v in self.book.items() if v == ep), None
                )
                nid = (
                    reborn
                    if reborn is not None
                    else max(self.book, default=-1) + 1
                )
        # Welcome goes straight to the joiner's endpoint (``via``): it doesn't
        # know its node id yet, so it can't be in any route table.
        welcome = Envelope(
            "client", cl.Welcome(nid, self.config.to_json()), via=ep
        )
        if (
            self._incarnations.get(nid) == msg.incarnation
            and nid in self.grid.nodes
        ):
            # join RETRY from a node we already admitted: its Welcome was
            # lost in flight — re-send it, change no membership state
            self.monitor.heartbeat(nid, now)
            return [welcome]
        restarted = nid in self.grid.nodes
        # a NEW incarnation under this id is a new process: anything the old
        # process claimed to hold may have died with it (or its disk) — its
        # own fresh adverts will restore the holder map from what survived
        self._drop_ckpt_holder(nid)
        prev_inc = self._incarnations.get(nid)
        prev_ep = self.book.get(nid)
        if prev_inc is not None and prev_ep is not None and prev_ep != ep:
            # id reclaimed from a different endpoint: remember the superseded
            # process so a late heartbeat from it gets a Shutdown reply
            self._superseded[nid] = (prev_inc, prev_ep)
        self.book[nid] = ep
        self._incarnations[nid] = msg.incarnation
        self.unreachable.discard(nid)
        # a new incarnation is a new process: its predecessor's inter-arrival
        # history (and the death gap since) must not poison the detector —
        # this covers the fast same-endpoint restart where the monitor state
        # is still UP and HeartbeatMonitor's own reset branch would not run
        self.monitor.detector.remove(nid)
        self.monitor.heartbeat(nid, now)
        log.info("master: node %d joined from %s:%d", nid, msg.host, msg.port)
        out = [welcome]
        out.extend(self._broadcast(self._address_book()))
        if restarted:
            # same identity re-joining before the detector noticed the crash:
            # its workers are fresh and unconfigured, so member_up's no-op is
            # wrong — force the Prepare/Confirm handshake for everyone
            log.info("master: node %d restarted -> reorganize", nid)
            out.extend(self.grid.reorganize())
        else:
            out.extend(self.grid.member_up(nid))
        return out

    def _on_heartbeat(self, msg: cl.Heartbeat, now: float) -> list[Envelope]:
        node_id, incarnation = msg.node_id, msg.incarnation
        if node_id not in self.book:
            # A heartbeat from a node this master has never admitted: either a
            # stale beat from an expelled node, or — the dangerous case — this
            # is a REPLACEMENT master (restarted on the seed endpoint, empty
            # book) and the sender is a healthy member of its predecessor.
            # Its sends all succeed, so the node's failure counter never
            # trips; without a reply it heartbeats into the void forever.
            # Tell it to re-run the join handshake at its advertised endpoint.
            if msg.port > 0:
                return [
                    Envelope(
                        f"node:{node_id}",
                        cl.Rejoin("unknown-node"),
                        via=cl.Endpoint(msg.host, msg.port),
                    )
                ]
            return []
        if self._incarnations.get(node_id) != incarnation:
            # zombie: a partitioned process whose id was reclaimed by a newer
            # joiner — its stale heartbeats must not alias the current
            # holder's liveness. Tell it to stand down rather than letting it
            # run (and heartbeat) orphaned forever.
            sup = self._superseded.get(node_id)
            if sup is not None and sup[0] == incarnation:
                return [
                    Envelope(
                        f"node:{node_id}",
                        cl.Shutdown("superseded"),
                        via=sup[1],
                    )
                ]
            return []
        event = self.monitor.heartbeat(node_id, now)
        if event is not None and node_id not in self.grid.nodes:
            # silence marked it unreachable but the process lives: rejoin it
            log.info("master: node %d heartbeat resumed -> rejoin", node_id)
            self.unreachable.discard(node_id)
            return self._broadcast(self._address_book()) + self.grid.member_up(
                node_id
            )
        return []

    def _on_round_complete(
        self, line_id: int, r: int, latency_s: float, done: int, n: int
    ) -> None:
        """Per-round observability (SURVEY.md §6): one JSONL record per
        completed line-round — latency, contributors at threshold, config —
        and the watchdog's completion signal (retires the round's deadline)."""
        if self.watchdog is not None:
            self.watchdog.round_completed(line_id, r)
        if self.metrics is not None:
            self.metrics.log_event(
                kind="round",
                line=line_id,
                round=r,
                latency_s=round(latency_s, 6),
                completions=done,
                workers=n,
                config=self.grid.config_id,
                data_bytes=self.config.metadata.data_size * 4,
            )

    def _address_book(self) -> cl.AddressBook:
        return cl.AddressBook(
            tuple(
                (nid, ep.host, ep.port)
                for nid, ep in sorted(self.book.items())
                if nid not in self.unreachable
            )
        )

    async def _poll_detector(self) -> None:
        now = self.clock()
        out: list[Envelope] = []
        expelled = False
        for event in self.monitor.poll(now):
            if event.state is MemberState.UNREACHABLE:
                log.info(
                    "master: node %d unreachable (phi=%.1f)",
                    event.node_id,
                    event.phi,
                )
                out.extend(self.grid.member_unreachable(event.node_id))
                # stop dialing and advertising the silent endpoint, but keep
                # its book entry + detector state: if the process is alive and
                # heartbeats resume, _on_heartbeat re-lines it without a new
                # JoinCluster; a genuine restart re-joins explicitly.
                self.unreachable.add(event.node_id)
                expelled = True
        if expelled:
            out.extend(self._broadcast(self._address_book()))
        # at-most-once delivery can eat a Prepare (e.g. into a connection
        # whose peer just restarted): re-send to unconfirmed workers. The
        # same discipline covers Start/Complete loss: an in-flight round
        # with no completion progress for several intervals is re-Started
        # at the workers that never reported (idempotent on every path —
        # under sustained loss a bounded round window wedges without this)
        interval = self.config.master.heartbeat_interval_s
        for lm in self.grid.line_masters.values():
            out.extend(lm.reprepare_pending(2.0 * interval))
            out.extend(lm.restart_stalled(5.0 * interval))
        if out:
            await self.transport.send_all(out)
        if self.grid.is_done and not self._done.is_set():
            self._done.set()
            await self.transport.send_all(self._broadcast(cl.Shutdown("done")))

    @property
    def rounds_completed(self) -> int:
        """Line-rounds completed across ALL configurations, not just the
        current one (re-organization replaces the line masters)."""
        return self.grid.total_completed


_incarnation_counter = itertools.count(1)


def _new_incarnation() -> int:
    """Unique per NodeProcess lifetime across processes on one host."""
    return (os.getpid() << 20) | (next(_incarnation_counter) & 0xFFFFF)


class NodeProcess:
    """Worker-node role: joins the seed, hosts one worker per dimension."""

    def __init__(
        self,
        seed: cl.Endpoint,
        data_source: DataSource,
        data_sink: DataSink,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        preferred_node_id: int = -1,
        join_retry_s: float = 0.5,
        allow_crash: bool = False,
        chaos_log: str | None = None,
        state_dir: str | None = None,
        replicas: int = 2,
    ) -> None:
        self.seed = seed
        self.data_source = data_source
        self.data_sink = data_sink
        self.preferred_node_id = preferred_node_id
        self.join_retry_s = join_retry_s
        # peer state transfer (statetransfer.py): when set, this node hosts
        # a chunk service over the delta-store directory, replicates its
        # saves to `replicas` peers, and can restore from peers on rejoin
        self.state_dir = state_dir
        self.replicas = replicas
        self.state: st.ChunkService | None = None
        self._chunk_store: st.ChunkStore | None = (
            st.ChunkStore(state_dir) if state_dir else None
        )
        # EVERY live replication task, not a single slot: a later save's
        # (insta-skipping) task must not shadow a still-running one at
        # stop() — all of them get cancelled at teardown
        self._replicate_tasks: set[asyncio.Task] = set()
        # chaos plumbing: the spec itself arrives with Welcome (one master
        # flag arms the cluster); allow_crash gates the `crash` fault to
        # REAL subprocesses (the CLI role sets it — an in-process test
        # harness must record a suppressed crash, not kill pytest)
        self.allow_crash = allow_crash
        self.chaos_log = chaos_log
        self._chaos_t0: float | None = None
        self.incarnation = _new_incarnation()
        self.node_id: int | None = None
        self.node: AllreduceNode | None = None
        self.config: AllreduceConfig | None = None
        self.book = cl.AddressBook(())
        self._endpoints: dict[int, cl.Endpoint] = {}
        self.transport = RemoteTransport(host, port)
        self.transport.set_route("master", seed)
        self.transport.set_prefix_route("line_master", lambda _lid: seed)
        self.transport.set_prefix_route("worker", self._peer_endpoint)
        # lambda, not a bound .get: the AddressBook handler REASSIGNS
        # self._endpoints wholesale on every membership change
        self.transport.set_prefix_route(
            "ckpt", lambda nid: self._endpoints.get(nid)
        )
        self._heartbeat_task: asyncio.Task | None = None
        self._join_task: asyncio.Task | None = None
        self._welcomed = asyncio.Event()
        self._shutdown = asyncio.Event()
        self.shutdown_reason: str | None = None
        # master-loss detection: consecutive failed sends to the master seed.
        # The reference restarts its seed JVM and workers re-join via Akka
        # Cluster; here the node notices its heartbeats bouncing and re-runs
        # the join handshake against whatever master now owns the endpoint.
        self._master_send_failures = 0
        self._rejoining = False
        self._left = False  # graceful leave announced; never rejoin after
        self._rejoin_task: asyncio.Task | None = None
        self.rejoin_after_failures = 3
        self.transport.on_send_error = self._on_send_error
        self.transport.on_send_ok = self._on_send_ok

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        ep = await self.transport.start()
        self.transport.register(
            "client", lambda msg: self._on_cluster_msg(msg)
        )
        # The joiner owns the handshake retry (Akka Cluster joins the same
        # way): re-send JoinCluster until Welcomed — the Welcome can vanish
        # into a connection whose peer only just noticed we restarted.
        join = cl.JoinCluster(
            ep.host, ep.port, self.preferred_node_id, self.incarnation
        )

        async def join_until_welcomed() -> None:
            while not self._welcomed.is_set():
                await self.transport.send(Envelope("master", join))
                await asyncio.sleep(self.join_retry_s)

        self._join_task = observed_task(join_until_welcomed(), name="node-join")

    async def wait_welcomed(self, timeout: float = 10.0) -> int:
        await asyncio.wait_for(self._welcomed.wait(), timeout)
        assert self.node_id is not None
        return self.node_id

    async def run_until_shutdown(self, timeout: float | None = None) -> str:
        await asyncio.wait_for(self._shutdown.wait(), timeout)
        return self.shutdown_reason or "done"

    async def leave(self) -> None:
        """Graceful departure (the reference's Cluster leave)."""
        # Stop heartbeating BEFORE announcing the leave, and latch _left so a
        # master reply to an already-in-flight heartbeat (Rejoin from a
        # replacement that no longer knows us) cannot drag this node back
        # into the cluster on its way out.
        self._left = True
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        if self.node_id is not None:
            await self.transport.send(
                Envelope("master", cl.LeaveCluster(self.node_id))
            )

    async def stop(self) -> None:
        for attr in ("_heartbeat_task", "_join_task", "_rejoin_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)
        for task in list(self._replicate_tasks):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._replicate_tasks.clear()
        await self.transport.stop()

    # -- routing helpers -------------------------------------------------------

    def _peer_endpoint(self, worker_id: int) -> cl.Endpoint | None:
        if self.config is None:
            return None
        # dict lookup: this resolver runs per outgoing chunk on the data path
        return self._endpoints.get(worker_id // self.config.master.dimensions)

    # -- cluster protocol ------------------------------------------------------

    def _on_send_ok(self, ep: cl.Endpoint, env: Envelope) -> None:
        # rejoin triggers on CONSECUTIVE master-send failures: a transient
        # blip must not accumulate forever toward a spurious cluster-wide
        # rejoin (the master rarely sends anything back in steady state, so
        # resetting only on inbound traffic would never clear the counter)
        if env.dest == "master":
            self._master_send_failures = 0

    def _on_send_error(self, ep: cl.Endpoint, env: Envelope) -> None:
        if self.state is not None:
            # a lost replication push must be re-pushed next round, not
            # dedup-skipped forever (statetransfer.note_send_failure)
            self.state.note_send_failure(env)
        if env.dest != "master" or not self._welcomed.is_set() or self._left:
            return
        self._master_send_failures += 1
        if (
            self._master_send_failures >= self.rejoin_after_failures
            and not self._rejoining
        ):
            self._rejoining = True
            log.info(
                "node %s: master unreachable (%d failed sends) -> re-join",
                self.node_id,
                self._master_send_failures,
            )
            self._rejoin_task = observed_task(
                self._rejoin_master(), name="node-rejoin"
            )

    async def _rejoin_master(self) -> None:
        """The master endpoint stopped answering: run the join handshake
        again (keeping our preferred id) against whatever owns the endpoint.

        A rejoin wipes this node's worker state, so it presents a NEW
        incarnation: a replacement master welcomes it normally, and a master
        that was merely unreachable for a moment treats it as a restart and
        re-runs the Prepare handshake — either way the fresh workers get
        configured instead of silently wedging.
        """
        try:
            if self._heartbeat_task is not None:
                self._heartbeat_task.cancel()
                self._heartbeat_task = None
            if self._join_task is not None:
                # the ORIGINAL join task retries until _welcomed is set and
                # may still be sleeping off its first retry interval:
                # clearing _welcomed below would resurrect it, and its join
                # carries the STALE incarnation — the master could admit
                # that ghost identity first and drop the bumped
                # incarnation's heartbeats as a zombie's until this loop's
                # join lands (race found by the chaos partition test)
                self._join_task.cancel()
                self._join_task = None
            self._welcomed.clear()
            self.incarnation = _new_incarnation()
            join = cl.JoinCluster(
                self.transport.endpoint.host,
                self.transport.endpoint.port,
                self.node_id if self.node_id is not None else -1,
                self.incarnation,
            )
            while not self._welcomed.is_set() and not self._shutdown.is_set():
                await self.transport.send(Envelope("master", join))
                await asyncio.sleep(self.join_retry_s)
        finally:
            self._rejoining = False
            self._master_send_failures = 0

    def _on_cluster_msg(self, msg: Any) -> list[Envelope]:
        self._master_send_failures = 0  # the master is talking to us
        if isinstance(msg, cl.Welcome):
            return self._on_welcome(msg)
        if isinstance(msg, cl.AddressBook):
            self.book = msg
            self._endpoints = {
                nid: cl.Endpoint(host, port) for nid, host, port in msg.entries
            }
            return []
        if isinstance(msg, cl.Shutdown):
            self.shutdown_reason = msg.reason
            self._shutdown.set()
            return []
        if isinstance(msg, cl.Rejoin):
            # the master does not recognize us (replacement master on the
            # seed endpoint): run the join handshake again, fresh incarnation
            # — unless we are the reason it doesn't know us (graceful leave)
            if self._welcomed.is_set() and not self._rejoining and not self._left:
                log.info(
                    "node %s: master replied Rejoin(%s) -> re-join",
                    self.node_id,
                    msg.reason,
                )
                self._rejoining = True
                self._rejoin_task = observed_task(
                    self._rejoin_master(), name="node-rejoin"
                )
            return []
        raise TypeError(f"node cannot handle {type(msg).__name__}")

    def _on_welcome(self, msg: cl.Welcome) -> list[Envelope]:
        if self._welcomed.is_set():
            return []  # duplicate Welcome from a join retry race
        if self._heartbeat_task is not None:  # re-welcome after master loss
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        self.config = AllreduceConfig.from_json(msg.config_json)
        # the wire-compression knob arrives with the config, like every
        # other knob: payloads we send from now on ride at the configured
        # width (decode is stateless — the flag travels per frame)
        self.transport.wire_f16 = self.config.metadata.wire_dtype == "f16"
        self.transport.retry_policy = self.config.master.retry
        self.node_id = msg.node_id
        dims = self.config.master.dimensions
        if self.config.chaos.enabled:
            from akka_allreduce_tpu.control.chaos import ChaosInjector

            # anchor the fault timeline ONCE per process: a rejoin rebuilds
            # the injector (the role may even change with the assigned id)
            # but must not restart partition/stall windows from zero
            if self._chaos_t0 is None:
                self._chaos_t0 = time.monotonic()
            prev = self.transport.chaos
            if (
                prev is not None
                and prev.seed == self.config.chaos.seed
                and prev.spec == self.config.chaos.spec
                and prev.role == msg.node_id
            ):
                pass  # re-welcome under the same identity: keep the injector
            else:
                inj = ChaosInjector(
                    self.config.chaos.seed,
                    self.config.chaos.spec,
                    role=msg.node_id,
                    dims=dims,
                    t0=self._chaos_t0,
                    allow_crash=self.allow_crash,
                    log_path=self.chaos_log,
                )
                if prev is not None:
                    # a rejoin (or id change) rebuilds the decision streams,
                    # but the process's event HISTORY must survive — the
                    # exit-time log write reports the whole run, not just
                    # the last membership epoch
                    inj.events = list(prev.events) + inj.events
                self.transport.chaos = inj
        self.node = AllreduceNode(
            msg.node_id,
            dims,
            self.data_source,
            self.data_sink,
            self.config.metadata,
            self.config.threshold,
            self.config.worker,
        )
        for dim in range(dims):
            wid = msg.node_id * dims + dim
            self.transport.register(
                f"worker:{wid}",
                lambda m, _wid=wid: self.node.handle(_wid, m),
            )
        self.transport.register_prefix(
            "node", lambda _nid, m: self._on_cluster_msg(m)
        )
        out: list[Envelope] = []
        if self._chunk_store is not None:
            # (re)build the chunk service under the assigned identity — the
            # STORE persists across rejoins (it is the disk), the service's
            # per-peer push dedup resets with the membership epoch
            self.state = st.ChunkService(
                self.transport,
                msg.node_id,
                self._chunk_store,
                replicas=self.replicas,
                retry=self.config.master.retry,
            )
            self.transport.register(
                st.ChunkService.addr(msg.node_id), self.state.handle
            )
            # the disk survived whatever restarted us: advertise everything
            # it holds — our OWN state and any replica holdings — so the
            # master's holder map (wiped of our old incarnation's entries)
            # re-learns what actually survived on this disk
            latest = self._chunk_store.latest()
            if latest is not None:
                out.append(
                    Envelope(
                        "master",
                        st.CheckpointAdvert(
                            msg.node_id, msg.node_id, latest[0], latest[1]
                        ),
                    )
                )
            for origin in sorted(self._chunk_store.replica_origins()):
                held = self._chunk_store.latest(origin)
                if held is not None:
                    out.append(
                        Envelope(
                            "master",
                            st.CheckpointAdvert(
                                msg.node_id, origin, held[0], held[1]
                            ),
                        )
                    )
        interval = self.config.master.heartbeat_interval_s
        self._heartbeat_task = observed_task(
            run_periodic(interval, self._send_heartbeat),
            name=f"node-{msg.node_id}-heartbeat",
        )
        self._welcomed.set()
        log.info("node %d welcomed (dims=%d)", msg.node_id, dims)
        return out

    # -- peer state transfer ---------------------------------------------------

    @staticmethod
    def _manifest_leaves(manifest_json: str) -> dict:
        """{leaf key: blob sha} of a manifest — restore evidence callers
        (the chaos-recover drill) can verify against replicas without
        racing this node's later saves and prunes."""
        import json

        try:
            return dict(json.loads(manifest_json).get("leaves", {}))
        except (ValueError, AttributeError):
            return {}

    def replica_peers(self) -> list[int]:
        """Live peers chosen as replica targets (address-book ring)."""
        if self.state is None:
            return []
        return self.state.replica_peers(list(self._endpoints))

    async def save_state(self, step: int, state: dict) -> dict | None:
        """Delta-save a flat ``{name: array}`` state dict, advertise it to
        the master, and kick a bounded background replication to the K
        replica peers (skipped, counted, when one is already in flight).
        Returns the save stats, or None when no state dir is configured."""
        if self.state is None or self._chunk_store is None:
            return None
        # deliberately ON the event loop: ChunkStore is single-threaded by
        # design (prune sweeps tmp files; a concurrent thread's in-flight
        # write would be swept mid-publish), and the whole save is
        # synchronous — nothing else interleaves with it. Demo states are
        # small; big states belong to the train-side AsyncDeltaCheckpointer
        # whose writer THREAD owns its store exclusively.
        stats = self._chunk_store.save_state(step, state)
        latest = self._chunk_store.latest()
        assert latest is not None
        await self.transport.send(
            Envelope(
                "master",
                st.CheckpointAdvert(
                    self.state.node_id, self.state.node_id, latest[0], latest[1]
                ),
            )
        )
        peers = self.replica_peers()
        if peers:
            # replicate_latest self-skips (and COUNTS) when a round is
            # already in flight — no pre-check here, or the documented
            # replicate.skipped_busy metric would never fire on this path
            task = observed_task(
                self.state.replicate_latest(peers),
                name=f"node-{self.node_id}-replicate-{step}",
            )
            self._replicate_tasks.add(task)
            task.add_done_callback(self._replicate_tasks.discard)
        return stats

    async def restore_state(self, *, rounds: int = 3) -> dict | None:
        """The rejoin restore path (RESILIENCE.md "Recovery"): prefer the
        local disk when it already holds the newest known step; otherwise
        pull the manifest's chunks from live peer holders — per-chunk
        retry/failover, resumable across ``rounds`` attempts with a FRESH
        holder map each time (a partition heal mid-restore changes who is
        reachable). Returns restore stats (``source`` disk|peer) or None
        when there is nothing to restore anywhere."""
        if self.state is None or self._chunk_store is None:
            return None
        t0 = time.perf_counter()
        reply = await self.state.request_manifest()
        latest = self._chunk_store.latest()
        known_step = reply.step if reply is not None else -1
        if latest is not None and latest[0] >= known_step:
            stats = {
                "source": "disk",
                "step": latest[0],
                "seconds": round(time.perf_counter() - t0, 3),
                "complete": True,
                "leaves": self._manifest_leaves(latest[1]),
            }
            st.note_disk_restore(stats["seconds"])
            return stats
        if reply is None or reply.step < 0:
            return None
        stats = None
        for attempt in range(max(1, rounds)):
            if not reply.holders:
                break
            stats = await self.state.restore_from_peers(
                reply.step, reply.manifest_json, list(reply.holders)
            )
            if stats["complete"]:
                stats["seconds"] = round(time.perf_counter() - t0, 3)
                stats["leaves"] = self._manifest_leaves(reply.manifest_json)
                return stats
            if attempt + 1 < rounds:
                fresh = await self.state.request_manifest()
                if fresh is not None and fresh.step >= reply.step:
                    reply = fresh
        log.warning(
            "node %s: peer restore of step %d incomplete (holders=%s)",
            self.node_id, reply.step, list(reply.holders),
        )
        return stats

    async def _send_heartbeat(self) -> None:
        assert self.node_id is not None
        # advertise our server endpoint: a replacement master (same seed
        # address, empty address book) uses it to reply Rejoin
        ep = self.transport.endpoint
        await self.transport.send(
            Envelope(
                "master",
                cl.Heartbeat(self.node_id, self.incarnation, ep.host, ep.port),
            )
        )
