"""Cluster-wide control plane — the reference's grid ``Master`` (SURVEY.md §3).

Discovers nodes via membership events, organizes them into lines (1D) or a 2D
grid of row/column lines (the butterfly topology, SURVEY.md §4.3), owns one
``LineMaster`` per line, and on any membership change bumps the config id and
re-runs the ``PrepareAllreduce`` -> ``ConfirmPreparation`` handshake so rounds
resume against the new peer set (SURVEY.md §4.5: within-round dropout needs NO
reconfiguration — thresholds absorb it; this path is for actual member loss or
late joiners).

Worker addressing: each node runs one worker per grid dimension (the
reference's ``AllreduceDimensionNode``); worker id = ``node_id * dims + dim``.
"""

from __future__ import annotations

import logging
from typing import Any

from akka_allreduce_tpu.config import (
    LineMasterConfig,
    MasterConfig,
    ThresholdConfig,
)
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.line_master import LineMaster
from akka_allreduce_tpu.obs import metrics as obs_metrics
from akka_allreduce_tpu.obs import trace as obs_trace
from akka_allreduce_tpu.parallel.mesh import grid_factors
from akka_allreduce_tpu.protocol import (
    DEFAULT_POLICY,
    CompleteAllreduce,
    ConfirmPreparation,
)

log = logging.getLogger(__name__)

_REORGANIZATIONS = obs_metrics.counter("master.reorganizations")


def dim_worker_id(node_id: int, dim: int, dims: int) -> int:
    return node_id * dims + dim


class GridMaster:
    """Membership + line organization + reconfiguration handshake."""

    def __init__(
        self,
        threshold: ThresholdConfig,
        config: MasterConfig = MasterConfig(),
        line_master_config: LineMasterConfig = LineMasterConfig(),
        *,
        on_round_complete=None,  # LineMaster RoundObserver, fanned to all lines
        on_round_start=None,  # LineMaster RoundStartObserver, same fan-out
        on_reorganize=None,  # called when a reorganization replaces the lines
        epoch: int = -1,  # leadership epoch stamped onto Prepare/Start
    ) -> None:
        self.threshold = threshold
        self.config = config
        self.line_master_config = line_master_config
        self.on_round_complete = on_round_complete
        self.on_round_start = on_round_start
        self.on_reorganize = on_reorganize
        self.epoch = epoch
        # current RoundPolicy (control/adapt.py): new rounds AND new line
        # configurations start under it; set via set_policy
        self.policy = DEFAULT_POLICY
        self.nodes: set[int] = set()
        self.config_id = 0
        self.organized = False
        self.line_masters: dict[int, LineMaster] = {}
        self._line_of_worker: dict[int, int] = {}
        self.resume_round = 0
        self._completed_before_reorg = 0  # line-rounds of replaced configs

    # -- membership events (reference: Akka Cluster MemberUp/Unreachable) ----

    def member_up(self, node_id: int) -> list[Envelope]:
        if node_id in self.nodes:
            return []
        self.nodes.add(node_id)
        if not self.organized:
            if len(self.nodes) < self.config.node_num:
                return []
            return self._organize()
        # late joiner after initial organization: re-line immediately
        log.info("master: late joiner node %d -> reorganize", node_id)
        return self._organize()

    def member_unreachable(self, node_id: int) -> list[Envelope]:
        if node_id not in self.nodes:
            return []
        self.nodes.discard(node_id)
        if not self.organized:
            return []
        log.info("master: lost node %d -> reorganize", node_id)
        # degraded mode FIRST: in-flight rounds that already hold every
        # completion the surviving workers can deliver complete gracefully
        # (counted, flushed, watchdog retired) before the reorganization
        # abandons whatever genuinely cannot finish
        dims = self.config.dimensions
        gone = [dim_worker_id(node_id, d, dims) for d in range(dims)]
        for lm in self.line_masters.values():
            lm.member_unreachable(gone)
        if not self.nodes:
            # cluster emptied: fold the dying configuration's progress and
            # round high-water mark exactly as _organize would, so a later
            # repopulation neither undercounts nor reuses round numbers.
            # A promoted standby can reach here with ZERO live lines
            # (takeover marks the grid organized before any re-join lands,
            # then the detector expels the last known member) — its
            # digest-carried resume_round is already the high-water mark.
            if self.line_masters:
                self.resume_round = max(
                    lm.next_round for lm in self.line_masters.values()
                )
                self._completed_before_reorg += sum(
                    lm.total_completed for lm in self.line_masters.values()
                )
            self.organized = False
            for lm in self.line_masters.values():
                lm.abandon_open_spans()
            if self.on_reorganize is not None:
                self.on_reorganize()
            self.line_masters.clear()
            self._line_of_worker.clear()
            return []
        return self._organize()

    def reorganize(self) -> list[Envelope]:
        """Force a fresh line organization + Prepare handshake with the
        current member set (e.g. a node process restarted under the same
        identity and needs its workers re-configured)."""
        if not self.organized or not self.nodes:
            return []
        return self._organize()

    # -- line organization ---------------------------------------------------

    def _organize(self) -> list[Envelope]:
        """(Re)partition nodes into lines; handshake every line."""
        # Resume AFTER the highest round any previous line had begun, so a new
        # configuration never reuses in-flight round numbers.
        if self.line_masters:
            self.resume_round = max(
                lm.next_round for lm in self.line_masters.values()
            )
            self._completed_before_reorg += sum(
                lm.total_completed for lm in self.line_masters.values()
            )
        self.config_id += 1
        _REORGANIZATIONS.inc()
        self.organized = True
        # the replaced lines' in-flight rounds are abandoned BY DESIGN:
        # close their open trace spans (else the round roots vanish
        # unrecorded) and let any watchdog retire their deadlines (else
        # every re-mesh reads as a stall)
        for lm in self.line_masters.values():
            lm.abandon_open_spans()
        if self.on_reorganize is not None:
            self.on_reorganize()
        self.line_masters.clear()
        self._line_of_worker.clear()
        nodes = sorted(self.nodes)
        dims = self.config.dimensions
        lines: list[list[int]] = []  # each entry: worker ids of one line
        if dims == 1:
            # sharded round scheduling (RESILIENCE.md "Tier 6"): split the
            # membership into up to line_shards contiguous lines, each
            # owning a worker subset and running its own round sequence —
            # round fan-out stops being one LineMaster's job. Every
            # reorganization re-shards from the CURRENT view, so shards
            # track membership exactly like the 2D grid's rows/columns.
            shards = max(1, min(self.config.line_shards, len(nodes)))
            base, extra = divmod(len(nodes), shards)
            start = 0
            for s in range(shards):
                size = base + (1 if s < extra else 0)
                lines.append(
                    [
                        dim_worker_id(n, 0, 1)
                        for n in nodes[start : start + size]
                    ]
                )
                start += size
        elif dims == 2:
            rows, cols = grid_factors(len(nodes))
            grid = [nodes[r * cols : (r + 1) * cols] for r in range(rows)]
            # dim 0: one line per row; dim 1: one line per column
            for r in range(rows):
                lines.append([dim_worker_id(n, 0, 2) for n in grid[r]])
            for c in range(cols):
                lines.append([dim_worker_id(grid[r][c], 1, 2) for r in range(rows)])
        else:
            raise ValueError(f"dimensions must be 1 or 2, got {dims}")

        out: list[Envelope] = []
        # Completed-round budget carried into the new lines: prior configs'
        # completions, split evenly (line count/shape may have changed — the
        # run-level target is ~max_rounds useful rounds per current line).
        prior_per_line = self._completed_before_reorg // len(lines)
        for line_id, worker_ids in enumerate(lines):
            lm = LineMaster(
                self.threshold,
                self.line_master_config,
                line_id=line_id,
                on_round_complete=self.on_round_complete,
                on_round_start=self.on_round_start,
                epoch=self.epoch,
            )
            # the controller's current level survives a reorganization: a
            # re-mesh mid-incident must not silently reset to full fidelity
            lm.policy = self.policy
            self.line_masters[line_id] = lm
            for w in worker_ids:
                self._line_of_worker[w] = line_id
            out.extend(
                lm.prepare(
                    tuple(worker_ids),
                    self.config_id,
                    self.resume_round,
                    completed_so_far=prior_per_line,
                )
            )
        log.info(
            "master: organized %d nodes into %d line(s), config %d, resume at %d",
            len(nodes),
            len(lines),
            self.config_id,
            self.resume_round,
        )
        return out

    # -- message routing -----------------------------------------------------

    def handle_for_line(self, line_id: int, msg: Any) -> list[Envelope]:
        lm = self.line_masters.get(line_id)
        if lm is None:
            return []
        ctx = obs_trace.current()
        if ctx is not None and ctx.sampled and obs_trace.enabled():
            # the grid-master layer of the round trace: dispatch of a
            # worker's confirm/complete back into the owning line
            with obs_trace.span(
                "grid_master.dispatch", line=line_id, msg=type(msg).__name__
            ):
                return lm.handle(msg)
        return lm.handle(msg)

    def handle(self, msg: Any) -> list[Envelope]:
        """Route a worker->master message to the owning line master."""
        if isinstance(msg, (ConfirmPreparation, CompleteAllreduce)):
            wid = msg.worker_id if isinstance(msg, ConfirmPreparation) else msg.src_id
            line_id = self._line_of_worker.get(wid)
            if line_id is None:
                return []
            return self.handle_for_line(line_id, msg)
        raise TypeError(f"master cannot handle {type(msg).__name__}")

    # -- adaptive degradation (control/adapt.py) -------------------------------

    def set_policy(self, policy) -> None:
        """Adopt a new RoundPolicy: rounds started from now on (and any
        future line configuration) carry it; in-flight rounds keep the
        stamp they started under."""
        self.policy = policy
        for lm in self.line_masters.values():
            lm.policy = policy

    def worker_lags(self) -> dict[int, int]:
        """Per-worker contribution lag (rounds) across every line — the
        controller's straggler evidence (LineMaster.worker_lags)."""
        out: dict[int, int] = {}
        for lm in self.line_masters.values():
            for w, lag in lm.worker_lags().items():
                out[w] = max(out.get(w, 0), lag)
        return out

    @property
    def total_completed(self) -> int:
        """Line-rounds completed across every configuration this master ran."""
        return self._completed_before_reorg + sum(
            lm.total_completed for lm in self.line_masters.values()
        )

    @property
    def is_done(self) -> bool:
        return bool(self.line_masters) and all(
            lm.is_done for lm in self.line_masters.values()
        )
