"""Cluster-wide control plane — the reference's grid ``Master`` (SURVEY.md §3).

Discovers nodes via membership events, organizes them into lines (1D) or a 2D
grid of row/column lines (the butterfly topology, SURVEY.md §4.3), owns one
``LineMaster`` per line, and on any membership change bumps the config id and
re-runs the ``PrepareAllreduce`` -> ``ConfirmPreparation`` handshake so rounds
resume against the new peer set (SURVEY.md §4.5: within-round dropout needs NO
reconfiguration — thresholds absorb it; this path is for actual member loss or
late joiners).

Hierarchy (RESILIENCE.md "Scale — the pod-scale control plane"): this
class owns CROSS-SHARD structure only — membership, the shard layout
(control/pod.py's pure assignment functions), per-worker round-resume
floors, and the dims-2 start gates. Each shard's ``LineMaster`` owns its
own round sequence:

- **dims-1 shards free-run** — every line resumes past only what ITS
  OWN workers have seen (the per-worker floors), so a fast shard never
  drags a slow one's round numbers forward on a re-shard, and a re-shard
  that moves a worker between shards still never hands it a round id at
  or below one it already flushed;
- **dims-2 lines stay in lockstep** — the butterfly chains dim-0 output
  into dim-1 input BY ROUND NUMBER, so all lines share one resume point,
  and each COLUMN line carries a ``start_gate`` that holds round r until
  every ROW line has completed r (the one place a cross-shard barrier is
  load-bearing; everywhere else rounds free-run).

Worker addressing: each node runs one worker per grid dimension (the
reference's ``AllreduceDimensionNode``); worker id = ``node_id * dims + dim``.
"""

from __future__ import annotations

import logging
from typing import Any

from akka_allreduce_tpu.config import (
    LineMasterConfig,
    MasterConfig,
    ThresholdConfig,
)
from akka_allreduce_tpu.control.envelope import Envelope
from akka_allreduce_tpu.control.line_master import LineMaster
from akka_allreduce_tpu.control import pod
from akka_allreduce_tpu.obs import metrics as obs_metrics
from akka_allreduce_tpu.obs import trace as obs_trace
from akka_allreduce_tpu.parallel.mesh import grid_factors
from akka_allreduce_tpu.protocol import (
    DEFAULT_POLICY,
    CompleteAllreduce,
    ConfirmPreparation,
)

log = logging.getLogger(__name__)

_REORGANIZATIONS = obs_metrics.counter("master.reorganizations")
_SHARDS = obs_metrics.gauge("master.shards")


def dim_worker_id(node_id: int, dim: int, dims: int) -> int:
    return node_id * dims + dim


class GridMaster:
    """Membership + shard layout + reconfiguration handshake."""

    def __init__(
        self,
        threshold: ThresholdConfig,
        config: MasterConfig = MasterConfig(),
        line_master_config: LineMasterConfig = LineMasterConfig(),
        *,
        on_round_complete=None,  # LineMaster RoundObserver, fanned to all lines
        on_round_start=None,  # LineMaster RoundStartObserver, same fan-out
        on_reorganize=None,  # called when a reorganization replaces the lines
        epoch: int = -1,  # leadership epoch stamped onto Prepare/Start
    ) -> None:
        self.threshold = threshold
        self.config = config
        self.line_master_config = line_master_config
        self.on_round_complete = on_round_complete
        self.on_round_start = on_round_start
        self.on_reorganize = on_reorganize
        self.epoch = epoch
        # current RoundPolicy (control/adapt.py): new rounds AND new line
        # configurations start under it; set via set_policy
        self.policy = DEFAULT_POLICY
        self.nodes: set[int] = set()
        self.config_id = 0
        self.organized = False
        self.line_masters: dict[int, LineMaster] = {}
        self._line_of_worker: dict[int, int] = {}
        # line ids whose Starts are gated on the dim-0 lines (dims-2
        # columns) — the set handle_for_line refill()s on row completion
        self._gated_lines: set[int] = set()
        # global resume fallback: the highest round number ANY line of
        # any configuration began (dims-2 lines, whose numbering is
        # coupled by the chain, all resume from here)
        self.resume_round = 0
        # per-WORKER round high-water (dims-1 sharding): the highest
        # next_round of any replaced line that contained the worker.
        # A new shard resumes past only ITS members' floors — this is
        # what lets shards free-run — and the map rides the replicated
        # StateDigest so a standby takeover keeps every shard's sequence
        # instead of snapping all of them to the global max.
        self._resume_of_worker: dict[int, int] = {}
        self._completed_before_reorg = 0  # line-rounds of replaced configs

    # -- membership events (reference: Akka Cluster MemberUp/Unreachable) ----

    def member_up(self, node_id: int) -> list[Envelope]:
        if node_id in self.nodes:
            return []
        self.nodes.add(node_id)
        if not self.organized:
            if len(self.nodes) < self.config.node_num:
                return []
            return self._organize()
        # late joiner after initial organization: re-line immediately
        log.info("master: late joiner node %d -> reorganize", node_id)
        return self._organize()

    def member_unreachable(self, node_id: int) -> list[Envelope]:
        if node_id not in self.nodes:
            return []
        self.nodes.discard(node_id)
        if not self.organized:
            return []
        log.info("master: lost node %d -> reorganize", node_id)
        # degraded mode FIRST: in-flight rounds that already hold every
        # completion the surviving workers can deliver complete gracefully
        # (counted, flushed, watchdog retired) before the reorganization
        # abandons whatever genuinely cannot finish
        dims = self.config.dimensions
        gone = [dim_worker_id(node_id, d, dims) for d in range(dims)]
        for lm in self.line_masters.values():
            lm.member_unreachable(gone)
        if not self.nodes:
            # cluster emptied: fold the dying configuration's progress and
            # round high-water marks exactly as _organize would, so a later
            # repopulation neither undercounts nor reuses round numbers.
            # A promoted standby can reach here with ZERO live lines
            # (takeover marks the grid organized before any re-join lands,
            # then the detector expels the last known member) — its
            # digest-carried floors are already the high-water marks.
            self._fold_replaced_lines()
            self.organized = False
            for lm in self.line_masters.values():
                lm.abandon_open_spans()
            if self.on_reorganize is not None:
                self.on_reorganize()
            self.line_masters.clear()
            self._line_of_worker.clear()
            self._gated_lines.clear()
            return []
        return self._organize()

    def reorganize(self) -> list[Envelope]:
        """Force a fresh line organization + Prepare handshake with the
        current member set (e.g. a node process restarted under the same
        identity and needs its workers re-configured)."""
        if not self.organized or not self.nodes:
            return []
        return self._organize()

    # -- line organization ---------------------------------------------------

    def _fold_replaced_lines(self) -> None:
        """Roll the dying configuration's round high-waters into the
        per-worker floors (and the global fallback) and bank its
        completed-round budget — one definition for _organize and the
        cluster-emptied path."""
        if not self.line_masters:
            return
        for lm in self.line_masters.values():
            for w in lm.worker_ids:
                prev = self._resume_of_worker.get(w, 0)
                self._resume_of_worker[w] = max(prev, lm.next_round)
        self.resume_round = max(
            self.resume_round,
            max(lm.next_round for lm in self.line_masters.values()),
        )
        self._completed_before_reorg += sum(
            lm.total_completed for lm in self.line_masters.values()
        )

    def _shard_views(self, nodes: list[int]) -> list[list[int]]:
        """The dims-1 shard layout of a membership view — coordinate-
        anchored blocks when a pod grid is configured (boundaries never
        move, an expulsion only shrinks its own shard), else the
        balanced contiguous split. Both are PURE in the view."""
        cfg = self.config
        if cfg.grid_rows > 0:
            return pod.coordinate_shard_assignment(
                nodes, cfg.grid_rows, cfg.grid_cols, cfg.line_shards
            )
        return pod.shard_assignment(nodes, cfg.line_shards)

    def _grid_views(self, nodes: list[int]) -> tuple[list[list[int]], list[list[int]]]:
        """The dims-2 row and column membership of a view. With a pod
        grid configured the node id IS the coordinate (row-major over
        ``grid_rows x grid_cols`` — control/pod.py), so rows/columns are
        stable coordinate groups with holes where members died; without
        one, the historical most-square factorization of the live count."""
        cfg = self.config
        if cfg.grid_rows > 0:
            cols = cfg.grid_cols
            row_of: dict[int, list[int]] = {}
            col_of: dict[int, list[int]] = {}
            for n in nodes:
                row_of.setdefault(n // cols, []).append(n)
                col_of.setdefault(n % cols, []).append(n)
            rows_v = [row_of[r] for r in sorted(row_of)]
            cols_v = [col_of[c] for c in sorted(col_of)]
            return rows_v, cols_v
        rows, cols = grid_factors(len(nodes))
        grid = [nodes[r * cols : (r + 1) * cols] for r in range(rows)]
        rows_v = [grid[r] for r in range(rows)]
        cols_v = [[grid[r][c] for r in range(rows)] for c in range(cols)]
        return rows_v, cols_v

    def _organize(self) -> list[Envelope]:
        """(Re)partition nodes into lines; handshake every line."""
        # Fold the replaced lines' high-waters FIRST: a new configuration
        # never reuses an in-flight round number of any line that shared
        # a worker with it.
        self._fold_replaced_lines()
        self.config_id += 1
        _REORGANIZATIONS.inc()
        self.organized = True
        # the replaced lines' in-flight rounds are abandoned BY DESIGN:
        # close their open trace spans (else the round roots vanish
        # unrecorded) and let any watchdog retire their deadlines (else
        # every re-mesh reads as a stall)
        for lm in self.line_masters.values():
            lm.abandon_open_spans()
        if self.on_reorganize is not None:
            self.on_reorganize()
        self.line_masters.clear()
        self._line_of_worker.clear()
        self._gated_lines.clear()
        nodes = sorted(self.nodes)
        dims = self.config.dimensions
        lines: list[list[int]] = []  # each entry: worker ids of one line
        gated_from = None  # first line id whose Starts are dim-1 gated
        if dims == 1:
            # sharded round scheduling (RESILIENCE.md "Tier 6"/"Scale"):
            # split the membership into up to line_shards lines, each
            # owning a worker subset and running its own round sequence —
            # round fan-out stops being one LineMaster's job. Every
            # reorganization re-shards from the CURRENT view through the
            # pure assignment functions (control/pod.py), so the same
            # view yields the same shards on every rebuild.
            for shard in self._shard_views(nodes):
                lines.append([dim_worker_id(n, 0, 1) for n in shard])
        elif dims == 2:
            rows_v, cols_v = self._grid_views(nodes)
            # dim 0: one line per row; dim 1: one line per column
            for row in rows_v:
                lines.append([dim_worker_id(n, 0, 2) for n in row])
            gated_from = len(lines)
            for col in cols_v:
                lines.append([dim_worker_id(n, 1, 2) for n in col])
        else:
            raise ValueError(f"dimensions must be 1 or 2, got {dims}")

        out: list[Envelope] = []
        # Completed-round budget carried into the new lines: prior configs'
        # completions, split evenly (line count/shape may have changed — the
        # run-level target is ~max_rounds useful rounds per current line).
        prior_per_line = self._completed_before_reorg // len(lines)
        row_line_ids = list(range(gated_from)) if gated_from is not None else []
        _SHARDS.set(len(lines))
        for line_id, worker_ids in enumerate(lines):
            lm = LineMaster(
                self.threshold,
                self.line_master_config,
                line_id=line_id,
                on_round_complete=self.on_round_complete,
                on_round_start=self.on_round_start,
                epoch=self.epoch,
            )
            # the controller's current level survives a reorganization: a
            # re-mesh mid-incident must not silently reset to full fidelity
            lm.policy = self.policy
            self.line_masters[line_id] = lm
            for w in worker_ids:
                self._line_of_worker[w] = line_id
            if dims == 1:
                # per-shard resume: past everything THIS shard's workers
                # have seen, independent of the other shards' sequences
                from_round = max(
                    (self._resume_of_worker.get(w, 0) for w in worker_ids),
                    default=0,
                )
            else:
                # the butterfly's chain couples dim-0/dim-1 by round
                # number: every line shares the global resume point
                from_round = self.resume_round
            if gated_from is not None and line_id >= gated_from:
                # the dims-2 barrier: a column's round r starts only once
                # every row line has COMPLETED r — the Start then chases
                # chain data that exists (the node-side stash still
                # absorbs per-worker skew; this keeps the scheduler from
                # running column rounds that structurally cannot finish)
                lm.start_gate = self._row_gate(row_line_ids)
                self._gated_lines.add(line_id)
            out.extend(
                lm.prepare(
                    tuple(worker_ids),
                    self.config_id,
                    from_round,
                    completed_so_far=prior_per_line,
                )
            )
        log.info(
            "master: organized %d nodes into %d line(s), config %d, resume at %d",
            len(nodes),
            len(lines),
            self.config_id,
            self.resume_round,
        )
        return out

    def _row_gate(self, row_line_ids: list[int]):
        """Start gate for a column line: round r may start once every row
        line of THIS configuration has completed r. Bound to the line ids
        (not instances): the gate dies with the configuration, and ids
        index the current ``line_masters`` generation only."""

        def gate(r: int) -> bool:
            for lid in row_line_ids:
                lm = self.line_masters.get(lid)
                if lm is not None and lm.completed_up_to < r:
                    return False
            return True

        return gate

    # -- message routing -----------------------------------------------------

    def handle_for_line(self, line_id: int, msg: Any) -> list[Envelope]:
        lm = self.line_masters.get(line_id)
        if lm is None:
            return []
        ctx = obs_trace.current()
        watch_gates = self._gated_lines and line_id not in self._gated_lines
        horizon = lm.completed_up_to if watch_gates else -1
        if ctx is not None and ctx.sampled and obs_trace.enabled():
            # the grid-master layer of the round trace: dispatch of a
            # worker's confirm/complete back into the owning line
            with obs_trace.span(
                "grid_master.dispatch", line=line_id, msg=type(msg).__name__
            ):
                out = lm.handle(msg)
        else:
            out = lm.handle(msg)
        if watch_gates and lm.completed_up_to > horizon:
            # a row line's horizon MOVED: a column gate keyed on it may
            # have opened — refill the gated lines and carry their Starts
            # in the same dispatch (synchronous, no extra scheduling hop;
            # gated only on actual completion, not every row message —
            # the per-message gate sweep would be O(rows·cols) at pod
            # scale for dispatches that can never open anything)
            for gated_id in sorted(self._gated_lines):
                gated = self.line_masters.get(gated_id)
                if gated is not None:
                    out.extend(gated.refill())
        return out

    def handle(self, msg: Any) -> list[Envelope]:
        """Route a worker->master message to the owning line master."""
        if isinstance(msg, (ConfirmPreparation, CompleteAllreduce)):
            wid = msg.worker_id if isinstance(msg, ConfirmPreparation) else msg.src_id
            line_id = self._line_of_worker.get(wid)
            if line_id is None:
                return []
            return self.handle_for_line(line_id, msg)
        raise TypeError(f"master cannot handle {type(msg).__name__}")

    # -- replication (master HA, per-shard-aware) ----------------------------

    def lines_static_state(self) -> dict[str, list[int]]:
        """The slow half of the replicated shard state: each live line's
        worker set (changes only on reorganization — rides the digest's
        cached static half)."""
        return {
            str(lid): sorted(lm.worker_ids)
            for lid, lm in self.line_masters.items()
        }

    def resume_floor_state(self) -> dict[str, int]:
        """The per-worker resume floors (reorganization-paced too)."""
        return {str(w): r for w, r in sorted(self._resume_of_worker.items())}

    def lines_round_state(self) -> dict[str, int]:
        """The fast half: each live line's next round number — per tick,
        so a standby takeover resumes EVERY shard past its own sequence
        instead of snapping all of them to the global max."""
        return {
            str(lid): lm.next_round for lid, lm in self.line_masters.items()
        }

    def restore_shard_state(
        self,
        floors: dict | None,
        line_workers: dict | None,
        line_next: dict | None,
        *,
        fallback_round: int = 0,
        fallback_workers=(),
    ) -> None:
        """Adopt a replicated shard state (standby takeover): per-worker
        floors, raised by each replicated line's live next round over its
        worker set. The takeover's first reorganization then resumes
        every shard past ITS OWN high-water.

        A digest from a leader that predates the per-shard fields (no
        floors, no lines) falls back to flooring EVERY known worker at
        ``fallback_round`` (the digest's global next) — the legacy
        global-max takeover, never a round-number regression."""
        for w, r in (floors or {}).items():
            self._resume_of_worker[int(w)] = max(
                self._resume_of_worker.get(int(w), 0), int(r)
            )
        for lid, workers in (line_workers or {}).items():
            nxt = int((line_next or {}).get(lid, 0))
            for w in workers:
                self._resume_of_worker[int(w)] = max(
                    self._resume_of_worker.get(int(w), 0), nxt
                )
        if not floors and not line_workers:
            for w in fallback_workers:
                self._resume_of_worker[int(w)] = max(
                    self._resume_of_worker.get(int(w), 0), int(fallback_round)
                )

    # -- adaptive degradation (control/adapt.py) -------------------------------

    def set_policy(self, policy) -> None:
        """Adopt a new RoundPolicy: rounds started from now on (and any
        future line configuration) carry it; in-flight rounds keep the
        stamp they started under."""
        self.policy = policy
        for lm in self.line_masters.values():
            lm.policy = policy

    def worker_lags(self) -> dict[int, int]:
        """Per-worker contribution lag (rounds) across every line — the
        controller's straggler evidence (LineMaster.worker_lags). Shards
        are disjoint worker sets, so the merge is a union; the max guard
        covers the dims-2 case where a node's two dim workers would ever
        share an id (they cannot — belt and suspenders)."""
        out: dict[int, int] = {}
        for lm in self.line_masters.values():
            for w, lag in lm.worker_lags().items():
                out[w] = max(out.get(w, 0), lag)
        return out

    @property
    def total_completed(self) -> int:
        """Line-rounds completed across every configuration this master ran."""
        return self._completed_before_reorg + sum(
            lm.total_completed for lm in self.line_masters.values()
        )

    @property
    def is_done(self) -> bool:
        return bool(self.line_masters) and all(
            lm.is_done for lm in self.line_masters.values()
        )
