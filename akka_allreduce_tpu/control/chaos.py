"""Deterministic chaos layer for the control-plane transports.

The reference tests fault tolerance by omitting messages (SURVEY.md §5);
this module generalizes that into a seed-driven fault-injection layer that
interposes on BOTH transports through one shared hook point: a transport
carries an optional ``chaos`` attribute (a :class:`ChaosInjector`) and asks
it ``plan_send(env)`` for every envelope headed to the wire. The injector
answers with a :class:`ChaosAction` (or ``None`` — the fast path), and the
transport applies the mechanics it supports:

- ``RemoteTransport`` (control/remote.py): drop, fail (partition semantics
  — the drop fires ``on_send_error`` so failure counting sees it, exactly
  like a refused connection), delay/stall (the frame is held and sent
  later — later frames overtake it, so delay IS reordering pressure),
  duplicate, and payload corruption (a bit flip in the tag-2/3 payload
  bytes, which the wire checksum must reject on the receive side).
- ``LocalRouter`` (control/local.py): drop, duplicate, reorder
  (push-to-back), and corruption via a wire-codec round trip — the same
  checksum rejects the flip even though no socket is involved.

Faults are compiled from a spec string (see :func:`parse_spec`)::

    drop:p=0.05;delay:ms=20;corrupt:p=0.01
    partition:groups=m+0|1+2,at=round10,heal=5s
    partition:from=1+2,to=m,at=8s,heal=8s   (one-directional)
    stall:node=1,at=3s,for=2s;crash:node=2,at=round8

Determinism: every probabilistic decision draws from a per-fault
``random.Random`` seeded by ``(seed, role, fault index, fault name)``, and
the event log records NO wall-clock timestamps — only logical fields (seq,
fault, dest, message type, round). Two injectors with the same seed fed
the same traffic emit byte-identical logs (``event_log_jsonl``), which is
the tier-1 determinism ratchet in tests/test_chaos.py. Injected events are
also mirrored to the PR-4 flight recorder ring and the metrics registry
(``chaos.injected.<fault>``), so a post-mortem dump shows what the chaos
layer did alongside what the system did about it.

No new wire tags: chaos configuration travels inside ``Welcome``'s config
JSON (``config.ChaosConfig``), and every fault is applied to frames of the
EXISTING protocol — arlint's WIRE001 exhaustiveness surface is unchanged
by design (pinned in tests/test_chaos.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import random
import sys
import time
from typing import Any, Callable

from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics
from akka_allreduce_tpu.protocol import ReduceBlock, ScatterBlock

log = logging.getLogger(__name__)

__all__ = [
    "MASTER_ROLE",
    "CRASH_EXIT_CODE",
    "ChaosAction",
    "ChaosInjector",
    "FaultSpec",
    "parse_spec",
    "membership_schedule",
    "leader_kill_step",
]

#: role value of the master process (nodes use their node id >= 0)
MASTER_ROLE = -1

#: exit status of a chaos-injected crash (distinguishable from real crashes)
CRASH_EXIT_CODE = 23

_FAULTS = (
    "drop",
    "delay",
    "duplicate",
    "reorder",
    "corrupt",
    "partition",
    "stall",
    "crash",
)

_EVENTS_TOTAL = _metrics.counter("chaos.events")


@dataclasses.dataclass
class ChaosAction:
    """What a transport should do to ONE outgoing envelope."""

    drop: bool = False  # swallow silently (packet-loss semantics)
    fail: bool = False  # swallow AND fire on_send_error (partition semantics)
    delay_s: float = 0.0  # hold the frame; later sends overtake it
    duplicate: bool = False  # send the frame twice
    corrupt: bool = False  # flip one payload bit (checksum must reject)
    # corruption coordinates, decided at plan time so the decision stream
    # (and thus the event log) never depends on frame geometry
    corrupt_at: float = 0.0  # fraction into the payload bytes
    corrupt_bit: int = 0  # which bit of that byte flips


def _parse_when(text: str, what: str) -> tuple[str, float]:
    """``round10`` -> ("round", 10); ``5s``/``5`` -> ("time", 5.0)."""
    if text.startswith("round"):
        try:
            return "round", float(int(text[len("round"):]))
        except ValueError:
            raise ValueError(f"bad {what} {text!r}: expected roundN") from None
    try:
        return "time", float(text[:-1] if text.endswith("s") else text)
    except ValueError:
        raise ValueError(
            f"bad {what} {text!r}: expected roundN, <sec>s, or a number"
        ) from None


def _parse_role(text: str, what: str) -> int:
    if text == "m":
        return MASTER_ROLE
    if text.lstrip("-").isdigit():
        return int(text)
    raise ValueError(f"bad {what} {text!r}: expected a node id or 'm'")


@dataclasses.dataclass
class FaultSpec:
    """One compiled fault from the spec string."""

    name: str
    p: float = 1.0
    delay_ms: float = 0.0
    jitter_ms: float = 0.0
    groups: tuple[frozenset[int], ...] = ()
    # one-directional partition (`partition:from=m,to=1`): sends FROM a
    # member of `src` TO a member of `dst` fail; the reverse direction
    # flows — the asymmetric-loss case that makes a hub misjudge N nodes
    # from one congested link (gossip's indirect probes route around it)
    src: frozenset[int] = frozenset()
    dst: frozenset[int] = frozenset()
    node: int | None = None
    at: tuple[str, float] = ("time", 0.0)
    until: tuple[str, float] | None = None  # heal= / for= (absolute or span)
    # runtime window state (set by the injector)
    active_since_s: float | None = None
    done: bool = False
    # one-shot round triggers ARM only after this process observes a round
    # BELOW the trigger: a process that joins a cluster already past the
    # trigger round (the chaos-recover respawn) must not re-fire a crash
    # that belongs to the epoch that approached it — without this, a
    # crash:node=K,at=roundN kills node K again on every rejoin forever
    armed: bool = False


def parse_spec(spec: str) -> list[FaultSpec]:
    """Compile a chaos spec string into fault specs.

    Grammar: ``fault(;fault)*`` where ``fault := name[:k=v(,k=v)*]``.
    Group lists use ``+`` within a group and ``|`` between groups
    (``groups=m+0|1+2``; ``m`` is the master) because ``,`` separates
    parameters. Raises ``ValueError`` with the offending token on any
    malformed input — a bad spec must fail at startup, not mid-run.
    """
    faults: list[FaultSpec] = []
    for i, part in enumerate(p for p in spec.split(";") if p.strip()):
        name, _, rest = part.strip().partition(":")
        name = name.strip()
        if name not in _FAULTS:
            raise ValueError(
                f"unknown chaos fault {name!r}; expected one of {_FAULTS}"
            )
        f = FaultSpec(name=name)
        params: dict[str, str] = {}
        for kv in (x for x in rest.split(",") if x.strip()):
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"bad chaos param {kv!r} (expected k=v)")
            params[k.strip()] = v.strip()
        for k, v in params.items():
            if k == "p":
                f.p = float(v)
                if not 0.0 <= f.p <= 1.0:
                    raise ValueError(f"{name}: p must be in [0,1], got {v}")
            elif k == "ms" and name == "delay":
                f.delay_ms = float(v)
            elif k == "jitter_ms" and name == "delay":
                f.jitter_ms = float(v)
            elif k == "groups" and name == "partition":
                f.groups = tuple(
                    frozenset(
                        _parse_role(m, "partition group member")
                        for m in g.split("+")
                        if m
                    )
                    for g in v.split("|")
                )
                if len(f.groups) < 2:
                    raise ValueError(
                        f"partition needs >= 2 groups, got {v!r}"
                    )
            elif k in ("from", "to") and name == "partition":
                members = frozenset(
                    _parse_role(m, f"partition {k} member")
                    for m in v.split("+")
                    if m
                )
                if not members:
                    raise ValueError(f"partition: empty {k}= member list")
                if k == "from":
                    f.src = members
                else:
                    f.dst = members
            elif k == "node" and name in ("stall", "crash", "delay"):
                # delay:node=K is the STAGED STRAGGLER (RESILIENCE.md
                # "Tier 5"): one process's sends run late while its
                # heartbeats keep their cadence (a constant hold preserves
                # spacing) — slow-but-alive, the case the adaptive
                # controller exists for, distinct from stall's silence
                f.node = _parse_role(v, f"{name} node")
            elif k == "at":
                f.at = _parse_when(v, f"{name} at")
            elif k == "heal" and name == "partition":
                f.until = _parse_when(v, "partition heal")
            elif k == "for" and name in ("stall", "delay"):
                f.until = _parse_when(v, f"{name} for")
            else:
                raise ValueError(f"{name}: unknown param {k!r}")
        if name == "partition":
            if f.groups and (f.src or f.dst):
                raise ValueError(
                    "partition: groups= and from=/to= are mutually "
                    "exclusive (symmetric vs one-directional form)"
                )
            if bool(f.src) != bool(f.dst):
                raise ValueError(
                    "partition: from= and to= must be given together"
                )
            if not f.groups and not f.src:
                raise ValueError("partition requires groups= or from=/to=")
        if name in ("stall", "crash") and f.node is None:
            raise ValueError(f"{name} requires node=")
        # crash:node=m is allowed since the master-HA PR: a real
        # cluster-master process arms allow_crash, and the warm-standby
        # failover protocol is exactly what absorbs the kill (the
        # chaos-failover drill). In-process masters keep allow_crash off
        # and record a suppressed crash, like nodes always did.
        if name == "crash" and f.at == ("round", 0.0):
            # round triggers arm only after a round BELOW the trigger is
            # observed (so a rejoined process cannot re-fire a past crash);
            # round0 can never arm — reject it instead of silently never
            # firing
            raise ValueError(
                "crash:at=round0 cannot arm (round triggers fire when the "
                "round sequence crosses them from below); use at=round1+ "
                "or a time trigger"
            )
        if name == "stall" and f.until is None:
            raise ValueError("stall requires for=")
        if name == "delay" and f.delay_ms <= 0:
            raise ValueError("delay requires ms= > 0")
        faults.append(f)
    return faults


def _derive_seed(seed: int, role: int, index: int, name: str) -> int:
    digest = hashlib.blake2b(
        f"{seed}:{role}:{index}:{name}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "little")


class ChaosInjector:
    """Per-process chaos runtime: compiled faults + seeded decision streams.

    One injector per transport; ``role`` is the process's identity
    (:data:`MASTER_ROLE` or a node id) so partitions/stalls/crashes know
    which side of the spec this process is. ``t0`` anchors time-based
    triggers — pass the SAME anchor when rebuilding an injector after a
    rejoin, or the fault timeline would restart with the membership.
    """

    def __init__(
        self,
        seed: int,
        spec: str,
        *,
        role: int,
        dims: int = 1,
        clock: Callable[[], float] = time.monotonic,
        t0: float | None = None,
        allow_crash: bool = False,
        log_path: str | None = None,
    ) -> None:
        self.seed = seed
        self.role = role
        self.dims = max(1, dims)
        self.clock = clock
        self.t0 = clock() if t0 is None else t0
        self.allow_crash = allow_crash
        self.log_path = log_path
        self.spec = spec
        self.faults = parse_spec(spec)
        self._rngs = [
            random.Random(_derive_seed(seed, role, i, f.name))
            for i, f in enumerate(self.faults)
        ]
        self.events: list[dict[str, Any]] = []
        self.round = -1
        self.crashes_suppressed = 0
        self._counters = {
            name: _metrics.counter(f"chaos.injected.{name}")
            for name in _FAULTS
        }

    # -- bookkeeping ----------------------------------------------------------

    def _now(self) -> float:
        return self.clock() - self.t0

    def _log(self, fault: str, env, **extra: Any) -> None:
        """One injected event: logical fields only (NO timestamps), so the
        log is byte-identical across same-seed same-traffic runs."""
        rec = {
            "seq": len(self.events),
            "fault": fault,
            "role": self.role,
            "dest": env.dest if env is not None else None,
            "msg": type(env.msg).__name__ if env is not None else None,
            "round": self.round if self.round >= 0 else None,
            **extra,
        }
        self.events.append(rec)
        self._counters[fault].inc()
        _EVENTS_TOTAL.inc()
        _flight.note("chaos_inject", **rec)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.events:
            out[e["fault"]] = out.get(e["fault"], 0) + 1
        return out

    def event_log_jsonl(self) -> str:
        """The deterministic event log, one sorted-key JSON object per
        line — the byte-identity surface of the same-seed guarantee."""
        return "\n".join(json.dumps(e, sort_keys=True) for e in self.events)

    def write_log(self, path: str | None = None) -> str | None:
        path = path or self.log_path
        if not path:
            return None
        with open(path, "w") as f:
            text = self.event_log_jsonl()
            f.write(text + ("\n" if text else ""))
        return path

    # -- routing helpers ------------------------------------------------------

    def _dest_role(self, dest: str) -> int | None:
        """Which process an address lives on (None = unattributable, e.g.
        the pre-Welcome ``client`` reply — chaos leaves those alone)."""
        if dest == "master" or dest.startswith("line_master:"):
            return MASTER_ROLE
        prefix, _, suffix = dest.rpartition(":")
        if suffix.lstrip("-").isdigit():
            if prefix == "worker":
                return int(suffix) // self.dims
            if prefix in ("node", "gossip"):
                # gossip endpoints use the same role id space (the master
                # is gossip:-1 == MASTER_ROLE), so partitions/stalls cut
                # membership traffic exactly like round traffic
                return int(suffix)
        return None

    def _group_of(self, groups, role: int | None) -> int | None:
        if role is None:
            return None
        for i, g in enumerate(groups):
            if role in g:
                return i
        return None

    def _window_active(self, f: FaultSpec, now: float) -> bool:
        """Evaluate (and advance) a partition/stall window's state."""
        kind, value = f.at
        started = (
            self.round >= value if kind == "round" else now >= value
        )
        if not started:
            return False
        if f.active_since_s is None:
            f.active_since_s = now
        if f.until is None:
            return True
        ukind, uvalue = f.until
        if ukind == "round":
            return self.round < uvalue
        # time spans are relative to activation (heal=5s / for=2s)
        return now - f.active_since_s < uvalue

    def _fired(self, f: FaultSpec, now: float) -> bool:
        """One-shot trigger (crash)."""
        if f.done:
            return False
        kind, value = f.at
        if kind == "round":
            # arm only while approaching the trigger from below (see
            # FaultSpec.armed): a rejoined process observing round 122
            # must not re-fire an at=round30 crash
            if 0 <= self.round < value:
                f.armed = True
            if f.armed and self.round >= value:
                f.done = True
                return True
            return False
        if now >= value:
            f.done = True
            return True
        return False

    # -- the hook point -------------------------------------------------------

    def plan_send(self, env) -> ChaosAction | None:
        """Decide this envelope's fate. Called by the transport for every
        envelope headed to the wire; ``None`` means untouched (fast path).
        May not return at all: a fired ``crash`` fault ``os._exit``\\ s the
        process (only when ``allow_crash`` — cluster subprocesses; the
        in-process harness records a suppressed crash instead)."""
        r = getattr(env.msg, "round_num", None)
        if isinstance(r, int) and r > self.round:
            self.round = r
        now = self._now()
        act = ChaosAction()
        hit = False
        for f, rng in zip(self.faults, self._rngs):
            name = f.name
            if name == "crash":
                if f.node == self.role and self._fired(f, now):
                    if self.allow_crash:
                        self._log("crash", env, exit=CRASH_EXIT_CODE)
                        self.write_log()
                        sys.stderr.write(
                            f"chaos: injected crash (role {self.role}, "
                            f"round {self.round})\n"
                        )
                        sys.stderr.flush()
                        os._exit(CRASH_EXIT_CODE)
                    # in-process harness: the log must record what actually
                    # happened — a suppressed crash, not an exit
                    self._log("crash", env, suppressed=True)
                    self.crashes_suppressed += 1
                continue
            if name == "partition":
                if not self._window_active(f, now):
                    continue
                if f.src:
                    # one-directional form: only the src -> dst direction
                    # is cut (the acks/replies flow back fine — the
                    # asymmetric-loss case a hub detector cannot tell
                    # from death)
                    theirs = self._dest_role(env.dest)
                    if self.role not in f.src or theirs not in f.dst:
                        continue
                    self._log("partition", env, oneway=True, peer=theirs)
                    act.fail = True
                    hit = True
                    break  # the direction is down; nothing else applies
                mine = self._group_of(f.groups, self.role)
                theirs = self._group_of(f.groups, self._dest_role(env.dest))
                if mine is None or theirs is None or mine == theirs:
                    continue
                self._log("partition", env, group=mine, peer_group=theirs)
                act.fail = True
                hit = True
                break  # the link is down; nothing else applies
            if name == "stall":
                if f.node != self.role or not self._window_active(f, now):
                    continue
                assert f.until is not None and f.active_since_s is not None
                ukind, uvalue = f.until
                remain = (
                    max(uvalue - now + f.active_since_s, 0.0)
                    if ukind == "time"
                    else 0.05  # round-bounded stalls re-check per send
                )
                # log the CONFIGURED window, not the live remainder: the
                # remainder is wall-clock-derived and would break the
                # byte-identical same-seed log guarantee
                self._log("stall", env, window=f"{ukind}:{uvalue:g}")
                act.delay_s = max(act.delay_s, remain)
                hit = True
                continue
            if name == "delay" and (
                f.node is not None or f.at != ("time", 0.0) or f.until
            ):
                # the targeted/windowed delay form (the staged straggler):
                # role and window are checked BEFORE the rng draw, so an
                # un-targeted un-windowed spec's decision stream is
                # byte-identical to the historical one
                if f.node is not None and f.node != self.role:
                    continue
                if not self._window_active(f, now):
                    continue
            # probabilistic faults consume exactly one sample per send so
            # the decision stream depends only on (seed, traffic order)
            if rng.random() >= f.p:
                continue
            if name == "drop":
                self._log("drop", env)
                act.drop = True
                hit = True
                break  # dropped; later faults moot
            if name == "delay":
                extra = rng.random() * f.jitter_ms if f.jitter_ms else 0.0
                ms = f.delay_ms + extra
                self._log("delay", env, delay_ms=round(ms, 3))
                act.delay_s = max(act.delay_s, ms / 1e3)
                hit = True
            elif name == "duplicate":
                self._log("duplicate", env)
                act.duplicate = True
                hit = True
            elif name == "reorder":
                # mechanically a tiny hold: per-connection FIFO is violated
                # because later sends overtake the held frame
                self._log("reorder", env)
                act.delay_s = max(act.delay_s, 0.005)
                hit = True
            elif name == "corrupt":
                if not isinstance(env.msg, (ScatterBlock, ReduceBlock)):
                    continue  # only payload frames carry the checksum
                act.corrupt = True
                act.corrupt_at = rng.random()
                act.corrupt_bit = rng.randrange(8)
                self._log(
                    "corrupt", env,
                    at=round(act.corrupt_at, 6), bit=act.corrupt_bit,
                )
                hit = True
        return act if hit else None

    def corrupt_frame_parts(self, parts: list, act: ChaosAction) -> list:
        """Flip one bit of the frame's PAYLOAD segment — the float bytes
        the tag-2/3 checksum covers. The payload is the unique
        ``memoryview`` segment of ``encode_frame_parts`` (headers, dest and
        the trace trailer are ``bytes``); a frame may also END with the
        trace trailer, so "last part" would miss. The segment is COPIED
        first: the original is a zero-copy view of engine memory, and
        chaos must corrupt the wire, never the engine."""
        parts = list(parts)
        views = [
            i for i, p in enumerate(parts) if isinstance(p, memoryview)
        ]
        if len(views) == 1:
            target = views[0]
        else:  # fall back to the largest segment (the payload dominates)
            target = max(range(len(parts)), key=lambda i: len(parts[i]))
        buf = bytearray(parts[target])
        if buf:
            i = min(int(act.corrupt_at * len(buf)), len(buf) - 1)
            buf[i] ^= 1 << act.corrupt_bit
            parts[target] = bytes(buf)
        return parts


def membership_schedule(
    seed: int,
    nodes: int,
    steps: int,
    *,
    flap_p: float = 0.03,
    flap_len: tuple[int, int] = (3, 8),
) -> dict[int, frozenset[int]]:
    """Seeded membership chaos for the soak loop (``soak --chaos SEED``).

    Returns ``{step: frozenset(silent node ids)}`` — per step, which nodes
    withhold their heartbeat. Each node other than 0 independently enters
    silence windows (probability ``flap_p`` per step, uniform length in
    ``flap_len``); node 0 never flaps, so the cluster always has a
    survivor. A pure function of its arguments: the same seed replays the
    same churn.
    """
    rng = random.Random(_derive_seed(seed, MASTER_ROLE, 0, "membership"))
    silent: dict[int, set[int]] = {}
    lo, hi = flap_len
    for k in range(1, nodes):
        step = 0
        while step < steps:
            if rng.random() < flap_p:
                span = rng.randint(lo, hi)
                for s in range(step, min(step + span, steps)):
                    silent.setdefault(s, set()).add(k)
                step += span
            else:
                step += 1
    return {s: frozenset(v) for s, v in silent.items()}


def leader_kill_step(seed: int, steps: int) -> int | None:
    """Seeded step at which the soak's simulated control-plane leader dies
    (the leader-kill entry of ``soak --chaos SEED``'s schedule).

    A pure function of its arguments — the same seed replays the same
    kill. Lands in the middle 40-60% of the run so checkpoint and
    membership churn exist on both sides of the failover; ``None`` for
    runs too short to fit a leaderless window plus recovery."""
    if steps < 20:
        return None
    rng = random.Random(_derive_seed(seed, MASTER_ROLE, 1, "leader_kill"))
    return int(steps * (0.4 + 0.2 * rng.random()))
