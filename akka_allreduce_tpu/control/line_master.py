"""Per-line round scheduler — the reference's ``LineMaster`` (SURVEY.md §3).

Keeps a bounded number of rounds in flight; a round completes when
``ceil(th_allreduce * n_workers)`` workers report ``CompleteAllreduce``; each
completion advances the window (new rounds start immediately — never wait for
stragglers). Rounds older than a completed round are abandoned (their
completions are ignored), matching the worker's discipline.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

from akka_allreduce_tpu.config import LineMasterConfig, ThresholdConfig
from akka_allreduce_tpu.control.envelope import Envelope, peer_addr
from akka_allreduce_tpu.obs import metrics as obs_metrics
from akka_allreduce_tpu.obs import trace as obs_trace
from akka_allreduce_tpu.protocol import (
    DEFAULT_POLICY,
    CompleteAllreduce,
    ConfirmPreparation,
    PrepareAllreduce,
    RoundPolicy,
    StartAllreduce,
)

log = logging.getLogger(__name__)

# (line_id, round_num, latency_s, completions at threshold, n_workers)
RoundObserver = Callable[[int, int, float, int, int], None]
# (line_id, round_num) at the moment StartAllreduce envelopes are built
RoundStartObserver = Callable[[int, int], None]

_ROUNDS_COMPLETED = obs_metrics.counter("master.rounds_completed")
_ROUND_LATENCY = obs_metrics.histogram("master.round_latency_s")
# per-wire-mode round accounting (OBSERVABILITY.md adapt.*), held as
# objects so the per-completion hot path is an attribute read, not a
# registry name lookup (bootstrap.py's convention)
_MODE_ROUNDS = {
    wire: obs_metrics.counter(f"adapt.mode_rounds.{wire or 'full'}")
    for wire in RoundPolicy.WIRE_MODES
}
_ROUNDS_ABANDONED = obs_metrics.counter("master.rounds_abandoned")
_ROUNDS_DEGRADED = obs_metrics.counter("master.rounds_degraded")
_ROUNDS_RESTARTED = obs_metrics.counter("master.rounds_restarted")


class LineMaster:
    """Drives rounds for one line (worker group) of the grid."""

    def __init__(
        self,
        threshold: ThresholdConfig,
        config: LineMasterConfig = LineMasterConfig(),
        line_id: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_round_complete: RoundObserver | None = None,
        on_round_start: RoundStartObserver | None = None,
        epoch: int = -1,
    ) -> None:
        self.threshold = threshold
        self.config = config
        self.line_id = line_id
        # stamped onto every Prepare/Start so nodes can fence a zombie
        # master's round triggers after a failover (-1 = unfenced)
        self.epoch = epoch
        # cross-shard barrier (RESILIENCE.md "Scale"): when set, round r
        # may only START once gate(r) answers True. The ONLY user is the
        # butterfly's dims-2 exchange — the grid master gates each
        # column line on every row line having completed the round, so a
        # column Start never outruns the chain data it consumes; dims-1
        # shards carry no gate and free-run their own sequences.
        self.start_gate: Callable[[int], bool] | None = None
        # the CURRENT RoundPolicy (control/adapt.py): stamped onto each
        # round's StartAllreduce AT START — the per-round record below is
        # what re-Starts re-send, so a re-issued Start can never disagree
        # with buffers already reduced under the round's original policy
        self.policy: RoundPolicy = DEFAULT_POLICY
        self._round_policies: dict[int, RoundPolicy] = {}
        self._prepare_policy: RoundPolicy = DEFAULT_POLICY
        self.clock = clock
        self.on_round_complete = on_round_complete
        self.on_round_start = on_round_start
        self._started_at: dict[int, float] = {}
        self._restarted_at: dict[int, float] = {}  # restart_stalled rate limit
        # round -> open root span: this line master is where a round's
        # trace is BORN — the id stamped onto the StartAllreduce envelopes
        # is the one every downstream hop inherits
        self._round_spans: dict[int, obs_trace.Span] = {}
        self.worker_ids: tuple[int, ...] = ()
        self.config_id: int = -1
        self.next_round = 0  # next round number to start
        self.completed_up_to = -1
        self.started_rounds: set[int] = set()
        self.completions: dict[int, set[int]] = {}  # round -> worker ids
        self.total_completed = 0
        # line-rounds completed by this line's predecessors (earlier configs);
        # max_rounds budgets COMPLETED rounds across the lineage, not round
        # numbers — reorganization churn burns round numbers (they are never
        # reused, stale messages must not collide) but must not burn budget
        self.completed_so_far = 0
        self._confirmed: set[int] = set()
        self._preparing = False
        self._prepared_at = 0.0
        # workers the detector marked unreachable mid-config: the effective
        # completion trigger degrades to what the REACHABLE set can deliver,
        # so in-flight rounds at th=1.0 complete gracefully at detection
        # instead of wedging until the watchdog trips (degraded mode)
        self.unreachable: set[int] = set()
        # highest round each worker EVER asserted complete — updated even
        # for stale/late completions (which _on_complete otherwise drops),
        # because the gap between this watermark and completed_up_to IS the
        # straggler evidence the AdaptiveController consumes: a worker
        # whose completions chronically arrive after the round retired is
        # lagging by that many rounds, in round units, no wall clock
        self.worker_last_complete: dict[int, int] = {}

    # -- configuration / handshake ------------------------------------------

    def abandon_open_spans(self) -> None:
        """End every still-open round span as abandoned — called when this
        line master is superseded by a grid reorganization, so in-flight
        rounds' root spans reach the trace buffer (and the abandoned
        counter) instead of being silently GC'd with the instance."""
        for span in self._round_spans.values():
            _ROUNDS_ABANDONED.inc()
            span.set(abandoned=True, reorganized=True)
            span.end()
        self._round_spans.clear()

    def prepare(
        self,
        worker_ids: tuple[int, ...],
        config_id: int,
        from_round: int,
        completed_so_far: int = 0,
    ) -> list[Envelope]:
        """Begin the PrepareAllreduce handshake with a (new) worker set."""
        self.worker_ids = tuple(worker_ids)
        self.config_id = config_id
        self.next_round = from_round
        self.completed_so_far = completed_so_far
        # every worker starts the config with zero lag: from_round - 1 is
        # the shared watermark (nothing of this config completed yet)
        self.worker_last_complete = {w: from_round - 1 for w in worker_ids}
        self.started_rounds.clear()
        self.completions.clear()
        self.completed_up_to = from_round - 1
        self._confirmed.clear()
        self.unreachable.clear()  # a new config is built from live members
        self._round_policies.clear()
        # the policy in force when this configuration was prepared:
        # re-sent Prepares (reprepare_pending) carry the SAME stamp, so a
        # retried handshake cannot smuggle a newer level in
        self._prepare_policy = self.policy
        self._preparing = True
        self._prepared_at = self.clock()
        return self._prepare_envelopes(self.worker_ids)

    def _prepare_envelopes(self, workers) -> list[Envelope]:
        return [
            Envelope(
                peer_addr(w),
                PrepareAllreduce(
                    self.config_id, self.worker_ids, w, self.next_round,
                    self.line_id, self.epoch, self._prepare_policy,
                ),
            )
            for w in workers
        ]

    def restart_stalled(self, min_age_s: float) -> list[Envelope]:
        """Re-send ``StartAllreduce`` for in-flight rounds that made no
        completion progress for ``min_age_s`` — only to workers missing
        from the round's completion set.

        Delivery is at-most-once: under sustained loss a dropped Start
        starves a worker out of the round and a dropped Complete starves
        the round out of its trigger — with a bounded window both in-flight
        rounds can wedge PERMANENTLY (the chaos harness exposes this within
        seconds at drop:p=0.05). The retry is idempotent on every path: a
        worker mid-round re-scatters into dedup'd buffers, a worker that
        already finished re-asserts its lost CompleteAllreduce, a worker
        that never started simply starts."""
        if self._preparing:
            return []
        now = self.clock()
        out: list[Envelope] = []
        for r in sorted(self.started_rounds):
            if r <= self.completed_up_to:
                continue
            last = max(
                self._started_at.get(r, 0.0), self._restarted_at.get(r, 0.0)
            )
            if now - last < min_age_s:
                continue
            done = self.completions.get(r, set())
            pending = [w for w in self.worker_ids if w not in done]
            if not pending:
                continue
            self._restarted_at[r] = now  # rate limit; latency stays honest
            _ROUNDS_RESTARTED.inc()
            log.info(
                "line %d: round %d stalled %.2fs at %d/%d completions; "
                "re-starting %s",
                self.line_id, r, now - last, len(done),
                self.completion_trigger, pending,
            )
            span = self._round_spans.get(r)
            ctx = span.context if span is not None else None
            # the round's ORIGINAL policy, never the controller's current
            # one: workers that already reduced buffers for r did so under
            # the stamp the first Start carried, and a re-issued Start
            # that disagreed would split the round's threshold semantics
            pol = self._round_policies.get(r, DEFAULT_POLICY)
            out.extend(
                Envelope(
                    peer_addr(w), StartAllreduce(r, self.epoch, pol), trace=ctx
                )
                for w in pending
            )
        return out

    def reprepare_pending(self, min_age_s: float) -> list[Envelope]:
        """Re-send PrepareAllreduce to workers that have not confirmed within
        ``min_age_s`` — delivery is at-most-once (a send can vanish into a
        connection whose peer just restarted), so the handshake must retry
        rather than wedge the line (SURVEY.md §4.5)."""
        if not self._preparing or self.clock() - self._prepared_at < min_age_s:
            return []
        pending = [w for w in self.worker_ids if w not in self._confirmed]
        self._prepared_at = self.clock()
        log.info(
            "line %d: re-sending Prepare(config %d) to unconfirmed %s",
            self.line_id,
            self.config_id,
            pending,
        )
        return self._prepare_envelopes(pending)

    @property
    def n_workers(self) -> int:
        return len(self.worker_ids)

    @property
    def completion_trigger(self) -> int:
        """Completions required for a round — the configured threshold,
        DEGRADED to the reachable-worker count when the detector has marked
        members unreachable mid-config: the dead cannot report, so waiting
        for them is a wedge, not a guarantee (never below 1)."""
        base = self.threshold.allreduce_count(self.n_workers)
        reachable = self.n_workers - len(self.unreachable)
        return max(1, min(base, reachable))

    def member_unreachable(self, worker_ids) -> list[Envelope]:
        """Degraded mode: the detector marked these workers unreachable.

        Lowers the effective completion trigger and immediately re-checks
        every in-flight round against it — a round that already has every
        completion the REACHABLE set can deliver completes NOW (graceful
        degradation) instead of wedging until the watchdog dumps a stall
        or a reorganization abandons it. No new rounds are started here:
        the grid master reorganizes right after, and feeding the window of
        a dying config would only burn round numbers.
        """
        affected = set(worker_ids) & set(self.worker_ids)
        new = affected - self.unreachable
        if not new:
            return []
        self.unreachable |= new
        trigger = self.completion_trigger
        for r in sorted(self.started_rounds):
            if r <= self.completed_up_to or r not in self.started_rounds:
                continue  # retired by an earlier completion this loop
            if len(self.completions.get(r, ())) >= trigger:
                log.info(
                    "line %d: round %d completes DEGRADED (%d/%d workers "
                    "unreachable, trigger %d)",
                    self.line_id, r, len(self.unreachable),
                    self.n_workers, trigger,
                )
                _ROUNDS_DEGRADED.inc()
                self._complete_round(r, degraded=True)
        return []

    # -- message dispatch ----------------------------------------------------

    def handle(self, msg: Any) -> list[Envelope]:
        if isinstance(msg, ConfirmPreparation):
            return self._on_confirm(msg)
        if isinstance(msg, CompleteAllreduce):
            return self._on_complete(msg)
        raise TypeError(f"line master cannot handle {type(msg).__name__}")

    def _on_confirm(self, msg: ConfirmPreparation) -> list[Envelope]:
        if msg.config_id != self.config_id or not self._preparing:
            return []
        self._confirmed.add(msg.worker_id)
        if self._confirmed != set(self.worker_ids):
            return []
        # all workers rebuilt their buffers: open the round window
        self._preparing = False
        log.info(
            "line %d: config %d confirmed by all %d workers; starting at round %d",
            self.line_id,
            self.config_id,
            self.n_workers,
            self.next_round,
        )
        return self._fill_window()

    def worker_lags(self) -> dict[int, int]:
        """Per-worker contribution lag in ROUNDS: how far each worker's
        newest completion assertion trails the line's completed horizon.
        Reachable workers only — the detector owns the unreachable story
        (degraded mode), the controller owns the slow-but-alive one."""
        return {
            w: max(0, self.completed_up_to - self.worker_last_complete.get(w, -1))
            for w in self.worker_ids
            if w not in self.unreachable
        }

    def _on_complete(self, msg: CompleteAllreduce) -> list[Envelope]:
        r = msg.round_num
        if msg.src_id in self.worker_last_complete:
            # the lag watermark advances on EVERY assertion, stale ones
            # included: a late completion is exactly the straggler signal
            prev = self.worker_last_complete.get(msg.src_id, -1)
            self.worker_last_complete[msg.src_id] = max(prev, r)
        if self._preparing or r <= self.completed_up_to or r not in self.started_rounds:
            return []  # stale or unknown round
        done = self.completions.setdefault(r, set())
        if msg.src_id in done:
            return []
        done.add(msg.src_id)
        if len(done) < self.completion_trigger:
            return []
        self._complete_round(r)
        return self._fill_window()

    def _complete_round(self, r: int, *, degraded: bool = False) -> None:
        """The completion body: advance the watermark, account the round,
        close its span, and abandon older in-flight rounds (the workers'
        own discipline). Callers decide whether to refill the window —
        threshold completions do, degraded completions don't (the config
        is about to be replaced)."""
        done = self.completions.get(r, set())
        self.completed_up_to = max(self.completed_up_to, r)
        self.total_completed += 1
        _ROUNDS_COMPLETED.inc()
        # per-mode round accounting (OBSERVABILITY.md adapt.*): which wire
        # mode this round actually ran under — the A/B attribution signal
        # soak/bench reports carry
        pol = self._round_policies.get(r, DEFAULT_POLICY)
        _MODE_ROUNDS[pol.wire].inc()
        started = self._started_at.get(r)
        latency = self.clock() - started if started is not None else -1.0
        if latency >= 0:
            _ROUND_LATENCY.observe(latency)
        if self.on_round_complete is not None:
            self.on_round_complete(
                self.line_id, r, latency, len(done), self.n_workers
            )
        span = self._round_spans.pop(r, None)
        if span is not None:
            span.set(completions=len(done))
            if degraded:
                span.set(degraded=True)
            span.end()
        for stale in [x for x in self.started_rounds if x <= r]:
            self.started_rounds.discard(stale)
            self.completions.pop(stale, None)
            self._started_at.pop(stale, None)
            self._restarted_at.pop(stale, None)
            self._round_policies.pop(stale, None)
            stale_span = self._round_spans.pop(stale, None)
            if stale_span is not None:
                _ROUNDS_ABANDONED.inc()
                stale_span.set(abandoned=True)
                stale_span.end()

    # -- round window --------------------------------------------------------

    def refill(self) -> list[Envelope]:
        """Re-check the window after an EXTERNAL event opened a start
        gate (a row line completing the round a column line waits on) —
        a no-op while the Prepare handshake is still in flight."""
        if self._preparing:
            return []
        return self._fill_window()

    def _fill_window(self) -> list[Envelope]:
        out: list[Envelope] = []
        while len(self.started_rounds) < self.config.round_window:
            if (
                self.config.max_rounds >= 0
                and self.completed_so_far
                + self.total_completed
                + len(self.started_rounds)
                >= self.config.max_rounds
            ):
                break
            if self.start_gate is not None and not self.start_gate(
                self.next_round
            ):
                # gated: the window stops filling HERE (rounds start in
                # order); the grid master refill()s us when the gate's
                # upstream round completes
                break
            r = self.next_round
            self.next_round += 1
            self.started_rounds.add(r)
            self._started_at[r] = self.clock()
            # the policy is FROZEN per round at start (the stamp every
            # worker and every re-Start of r must agree on) — recorded
            # unconditionally, so a round started under the DEFAULT policy
            # can never inherit a later level through a re-Start fallback
            pol = self.policy
            self._round_policies[r] = pol
            # the round's trace is minted HERE: one fresh trace id, a
            # line_master.round root span that stays open until the
            # threshold completion, and the context stamped onto every
            # StartAllreduce so workers/transports continue the same trace
            span = obs_trace.start_span(
                "line_master.round",
                root=True,  # fresh trace id per round, never a child of the
                # completion handler's ambient context
                line=self.line_id,
                round=r,
                config=self.config_id,
            )
            if not pol.is_default:
                span.set(policy=pol.describe())
            self._round_spans[r] = span
            if self.on_round_start is not None:
                self.on_round_start(self.line_id, r)
            out.extend(
                Envelope(
                    peer_addr(w),
                    StartAllreduce(r, self.epoch, pol),
                    trace=span.context,
                )
                for w in self.worker_ids
            )
        return out

    @property
    def is_done(self) -> bool:
        """max_rounds line-rounds COMPLETED across the line's lineage (only
        meaningful with max_rounds >= 0). Budgeting completions, not round
        numbers, means reorganization churn can never satisfy the budget
        without actual work."""
        return (
            self.config.max_rounds >= 0
            and not self._preparing
            and self.completed_so_far + self.total_completed
            >= self.config.max_rounds
        )
