"""Closed-loop adaptive degradation (RESILIENCE.md "Tier 5 — adaptation").

The paper's core idea — threshold (partial) completion — was statically
configured at cluster start. This module closes the loop: the LEADER
master owns one :class:`AdaptiveController` that, once per round window,
reads straggler evidence and emits a per-round
:class:`~akka_allreduce_tpu.protocol.RoundPolicy` — an effective
``th_reduce`` (bounded by a configured floor) plus a wire compression
mode (``f32 → f16 → int8``) — and a restore path back to full fidelity
when the tail recovers. Fault *tolerance* becomes fault *adaptation*.

Evidence (gathered by the master FROM the PR-4 metrics registry and the
grid, then handed in — the controller itself is a pure state machine over
its inputs, which is what makes its decisions replayable):

- **contribution lag** (rounds): how far each worker's newest
  ``CompleteAllreduce`` assertion trails the completed horizon
  (``LineMaster.worker_lags`` — stale/late assertions move the watermark,
  so a chronically-late worker shows its lag in round units, no clock);
- **round latency** vs a learned healthy baseline (the registry's
  ``master.round_latency_s`` observations, folded in per round) — catches
  the straggler everyone must wait for (``th == 1.0``), which produces no
  lag because no round completes without it;
- **registry counter deltas**: ``master.rounds_restarted`` (loss so bad
  rounds had to be re-Started), ``remote.endpoint_reconnects``,
  ``chaos.injected.drop`` (when the chaos layer is armed, its own count
  is the ground-truth drop rate), and ``master.reorganizations``
  (membership churn in the window BLOCKS restores — a heal is not proven
  while the grid is still re-meshing).

Hysteresis: degrade and restore use DISTINCT thresholds
(``lag_degrade``/``lag_restore``, ``slow_factor``/recovered-mean) and
every transition requires ``min_dwell`` rounds at the current level — a
noisy tail cannot flap the mode. Decisions are paced by ROUND
COMPLETIONS (one evaluation per ``window`` observed rounds), never by a
wall-clock timer; the decision log records logical fields only, so the
same evidence sequence replays the same log byte for byte (pinned in
tests/test_adapt.py).

Failover: the controller's compact state rides the PR-7 ``StateDigest``
(``digest()``/``restore()``), so a promoted standby inherits the current
level mid-incident instead of resetting to full fidelity — the promoted
master's FIRST Prepare already carries the inherited policy (pinned in
tests/test_failover.py).
"""

from __future__ import annotations

import json
import logging
from typing import Any

from akka_allreduce_tpu.config import AdaptConfig, ThresholdConfig
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics
from akka_allreduce_tpu.protocol import DEFAULT_POLICY, RoundPolicy

log = logging.getLogger(__name__)

__all__ = ["AdaptiveController"]

# adapt.* observability (OBSERVABILITY.md): the current ladder level, the
# transition counters the soak/bench A/B reports carry, and a decisions
# counter so "the controller ran and chose to hold" is visible too
_LEVEL = _metrics.gauge("adapt.level")
_DEGRADES = _metrics.counter("adapt.degrades")
_RESTORES = _metrics.counter("adapt.restores")
_DECISIONS = _metrics.counter("adapt.decisions")

#: wire modes per degrade level past full fidelity (level 1, level 2)
_WIRE_LADDER = ("f16", "int8")

#: RoundPolicy wire stamp -> XLA-side trainer ``compress`` mode: the ONE
#: mapping that closes the ICI half of the loop (train/elastic.py's
#: ``apply_policy_wire``). The host wire's half-width float is f16; the
#: ICI collectives' is bf16 (the MXU-native half) — same ladder step,
#: per-plane dtype. "" (the default stamp) means inherit, i.e. the
#: trainer's construction-time mode, NOT necessarily full fidelity.
WIRE_TO_COMPRESS = {"f32": None, "f16": "bf16", "int8": "int8"}

#: registry counters whose WINDOW DELTAS are degrade pressure / restore
#: blockers — the master snapshots these and hands them to observe_round
COUNTER_EVIDENCE = ("restarts", "reconnects", "drops", "reorgs")


class AdaptiveController:
    """Per-round threshold + wire-precision controller (leader-owned).

    Feed it one :meth:`observe_round` per completed line-round; every
    ``config.window`` observations it evaluates the evidence and returns a
    NEW :class:`RoundPolicy` when the level changes (None = hold). The
    caller (``MasterProcess``) pushes a returned policy into the grid so
    rounds started from then on carry the stamp.
    """

    def __init__(
        self, config: AdaptConfig, threshold: ThresholdConfig
    ) -> None:
        self.config = config
        self.threshold = threshold
        self.level = 0
        # decision pacing + per-window evidence accumulators (reset each
        # evaluation) — all in round units or plain counts
        self._observed = 0  # rounds observed since the last evaluation
        self._rounds_at_level = 0  # dwell, in observed rounds
        self._window_latency_s = 0.0  # sum of this window's round latencies
        self._window_rounds = 0
        self._last_counters: dict[str, int] = {}
        # per-endpoint cumulative byte watermarks (PR-9's bandwidth
        # gauges): the controller diffs them per window, like counters
        self._last_bw: dict[str, float] = {}
        # healthy-latency baseline: learned from the FIRST full window
        # observed at level 0 with no pressure, then frozen — the yardstick
        # "slow" is measured against (0 until learned; latency evidence is
        # inert until then, lag/restart evidence never is)
        self.baseline_latency_s = 0.0
        # bounded decision log: logical fields only (NO timestamps), so
        # the same evidence sequence replays the same log byte for byte
        self.decisions: list[dict[str, Any]] = []
        # the most recent transition's record, even past the log cap —
        # what per-event consumers (metrics JSONL) must read, NOT
        # decisions[-1], which freezes once the bounded log fills
        self.last_decision: dict[str, Any] | None = None
        self.transitions = 0
        _LEVEL.set(0)

    # -- the ladder ----------------------------------------------------------

    def policy_for_level(self, level: int) -> RoundPolicy:
        """The RoundPolicy of ladder step ``level`` (0 = full fidelity =
        the default inherit-everything policy). th_reduce interpolates
        from the configured value down to ``floor_th_reduce`` across the
        ladder; the wire mode walks f16 then int8."""
        if level <= 0:
            return DEFAULT_POLICY
        levels = self.config.levels
        level = min(level, levels)
        base = self.threshold.th_reduce
        floor = min(self.config.floor_th_reduce, base)
        th = base - (base - floor) * (level / levels)
        return RoundPolicy(
            th_reduce=round(max(floor, th), 6),
            wire=_WIRE_LADDER[min(level, len(_WIRE_LADDER)) - 1],
        )

    def policy(self) -> RoundPolicy:
        return self.policy_for_level(self.level)

    # -- evidence intake -----------------------------------------------------

    @property
    def deciding_next(self) -> bool:
        """True when the NEXT :meth:`observe_round` call evaluates the
        window — callers can skip gathering the lag map and counter
        snapshot for the calls that would discard them."""
        return self._observed + 1 >= self.config.window

    def observe_round(
        self,
        round_num: int,
        worker_lags: dict[int, int],
        counters: dict[str, int],
        latency_s: float | None = None,
        bandwidth: dict[str, float] | None = None,
    ) -> RoundPolicy | None:
        """One completed line-round of evidence; returns the new policy on
        a level transition, else None.

        ``worker_lags`` is the grid's per-worker contribution lag in
        rounds; ``counters`` holds the CUMULATIVE registry counters named
        in :data:`COUNTER_EVIDENCE` (the controller diffs them against the
        previous window); ``latency_s`` is the round's latency observation
        (the same number the registry histogram absorbed) — optional, for
        callers without a clock (the soak simulation). ``bandwidth`` maps
        peer endpoints to CUMULATIVE bytes moved (PR-9's
        ``transport.endpoint.<host:port>.tx_bytes + rx_bytes`` gauges, as
        visible to the gathering process) — the bandwidth-imbalance arm
        (``AdaptConfig.bw_degrade_ratio``) diffs them per window and
        reads one endpoint moving far less than the median as straggler
        pressure, with its own hysteresis bar on the restore side.
        """
        self._observed += 1
        self._rounds_at_level += 1
        self._window_rounds += 1
        if latency_s is not None and latency_s >= 0:
            self._window_latency_s += latency_s
        if self._observed < self.config.window:
            return None
        return self._decide(round_num, worker_lags, counters, bandwidth)

    # -- the decision --------------------------------------------------------

    def _bw_ratio(self, bandwidth: dict[str, float] | None) -> float | None:
        """slowest-endpoint / median-endpoint byte delta for the window,
        or None when the arm is disabled or the evidence is too thin
        (fewer than 3 endpoints that moved anything: no median to stand
        out against)."""
        if self.config.bw_degrade_ratio <= 0 or bandwidth is None:
            return None
        known = self._last_bw
        deltas = sorted(
            d
            for k, v in bandwidth.items()
            if k in known and (d := max(0.0, float(v) - known[k])) > 0.0
            # zero-delta endpoints are excluded for the QUIET-WINDOW case
            # only: a link that moved nothing this window indicts nobody
            # (membership — tiers 3/6 — owns silent peers, and the
            # transport now EVICTS an expelled peer's rows outright via
            # forget_endpoint, so a dead peer's frozen row can no longer
            # masquerade as one); this arm judges links that are MOVING
            # data, just too little. First-seen endpoints (no watermark
            # yet) are excluded too: a peer that joined mid-window
            # carries only partial-window bytes and would read as a
            # spurious straggler — it gets its watermark seeded now and
            # is judged from the next window
        )
        self._last_bw = {k: float(v) for k, v in bandwidth.items()}
        if len(deltas) < 3:
            return None
        median = deltas[len(deltas) // 2]
        if median <= 0.0:
            return None  # a quiet window indicts nobody
        return deltas[0] / median

    def _decide(
        self,
        round_num: int,
        worker_lags: dict[int, int],
        counters: dict[str, int],
        bandwidth: dict[str, float] | None = None,
    ) -> RoundPolicy | None:
        cfg = self.config
        deltas = {
            k: max(0, int(counters.get(k, 0)) - self._last_counters.get(k, 0))
            for k in COUNTER_EVIDENCE
        }
        self._last_counters = {
            k: int(counters.get(k, 0)) for k in COUNTER_EVIDENCE
        }
        mean_latency = (
            self._window_latency_s / self._window_rounds
            if self._window_rounds
            else 0.0
        )
        max_lag = max(worker_lags.values(), default=0)
        slow = (
            self.baseline_latency_s > 0.0
            and mean_latency > cfg.slow_factor * self.baseline_latency_s
        )
        lagging = max_lag >= cfg.lag_degrade
        # connectivity noise: endpoint reconnects + (chaos-armed) dropped
        # sends this window — retried/absorbed loss that never forces a
        # re-Start still reads as pressure once it reaches the threshold
        noise = deltas["reconnects"] + deltas["drops"]
        noisy = cfg.noise_degrade > 0 and noise >= cfg.noise_degrade
        # bandwidth-imbalance arm (PR-9 gauges): one endpoint moving far
        # below the median endpoint's bytes this window is a straggling
        # link even when completions still arrive in time
        bw_ratio = self._bw_ratio(bandwidth)
        bw_lagging = bw_ratio is not None and bw_ratio < cfg.bw_degrade_ratio
        pressed = (
            lagging or slow or deltas["restarts"] > 0 or noisy or bw_lagging
        )
        # the healthy baseline is learned from the first quiet full window
        # at full fidelity, then frozen — degraded rounds are FASTER by
        # design and must not drag the yardstick down with them
        if (
            self.baseline_latency_s == 0.0
            and self.level == 0
            and not pressed
            and mean_latency > 0.0
        ):
            self.baseline_latency_s = mean_latency
        self._observed = 0
        self._window_latency_s = 0.0
        self._window_rounds = 0
        _DECISIONS.inc()
        dwelt = self._rounds_at_level >= cfg.min_dwell
        if pressed and self.level < cfg.levels and dwelt:
            return self._transition(
                round_num, self.level + 1, max_lag, deltas,
                [
                    name
                    for name, hit in (
                        ("lag", lagging), ("latency", slow),
                        ("restarts", deltas["restarts"] > 0),
                        ("noise", noisy),
                        ("bandwidth", bw_lagging),
                    )
                    if hit
                ],
            )
        recovered = (
            max_lag <= cfg.lag_restore
            and not slow
            and deltas["restarts"] == 0
            # a reorganization in the window means membership is still
            # churning (an expelled straggler re-joining reads as healed
            # for a moment): never restore on churn evidence
            and deltas["reorgs"] == 0
            # hysteresis gap on the noise arm: restore only when the
            # window's reconnects+drops fell below HALF the degrade bar
            and (cfg.noise_degrade <= 0 or noise * 2 < cfg.noise_degrade)
            # the bandwidth arm's own hysteresis bar: the slow endpoint
            # must be back above DOUBLE the degrade ratio (thin evidence
            # — too few endpoints, a quiet window — never blocks)
            and (bw_ratio is None or bw_ratio >= 2.0 * cfg.bw_degrade_ratio)
        )
        if recovered and self.level > 0 and dwelt:
            return self._transition(
                round_num, self.level - 1, max_lag, deltas, ["recovered"]
            )
        return None

    def _transition(
        self,
        round_num: int,
        to_level: int,
        max_lag: int,
        deltas: dict[str, int],
        why: list[str],
    ) -> RoundPolicy:
        frm = self.level
        self.level = to_level
        self._rounds_at_level = 0
        self.transitions += 1
        pol = self.policy()
        _LEVEL.set(to_level)
        (_DEGRADES if to_level > frm else _RESTORES).inc()
        rec = {
            "seq": self.transitions - 1,
            "round": round_num,
            "from": frm,
            "to": to_level,
            "policy": pol.describe(),
            "why": why,
            "lag": max_lag,
            **deltas,
        }
        self.last_decision = rec
        if len(self.decisions) < 4096:  # bounded, like the chaos log
            self.decisions.append(rec)
        _flight.note("adapt", **rec)
        log.warning(
            "adapt: level %d -> %d at round %d (%s): policy %s "
            "(lag=%d rounds, restarts=%d, reconnects=%d, drops=%d)",
            frm, to_level, round_num, "+".join(why), pol.describe(),
            max_lag, deltas["restarts"], deltas["reconnects"], deltas["drops"],
        )
        return pol

    # -- logs / replication --------------------------------------------------

    def decision_log_jsonl(self) -> str:
        """The decision log, one sorted-key JSON object per line — logical
        fields only, so same evidence => byte-identical log (the chaos
        event log's determinism contract, applied to decisions)."""
        return "\n".join(json.dumps(d, sort_keys=True) for d in self.decisions)

    def write_log(self, path: str) -> str:
        with open(path, "w") as f:
            text = self.decision_log_jsonl()
            f.write(text + ("\n" if text else ""))
        return path

    def digest(self) -> dict[str, Any]:
        """The compact state a warm standby needs to CONTINUE the loop
        mid-incident (rides the PR-7 StateDigest): the level (so the
        promoted master's first Prepare carries the inherited policy), the
        dwell so a takeover cannot reset the hysteresis clock, the learned
        baseline, and the counter watermarks so the first post-takeover
        window does not read the whole run's counters as one spike."""
        return {
            "level": self.level,
            "dwell": self._rounds_at_level,
            "baseline_s": self.baseline_latency_s,
            "counters": dict(self._last_counters),
            "bw": dict(self._last_bw),
            "transitions": self.transitions,
        }

    def restore(self, state: dict[str, Any] | None) -> None:
        """Adopt a replicated :meth:`digest` (standby takeover)."""
        if not state:
            return
        self.level = int(state.get("level", 0))
        self._rounds_at_level = int(state.get("dwell", 0))
        self.baseline_latency_s = float(state.get("baseline_s", 0.0))
        self._last_counters = {
            k: int(v) for k, v in dict(state.get("counters", {})).items()
        }
        self._last_bw = {
            k: float(v) for k, v in dict(state.get("bw", {})).items()
        }
        self.transitions = int(state.get("transitions", 0))
        _LEVEL.set(self.level)
