"""Binary wire codec for the control-plane protocol.

The reference configures Akka serializers for its ``Array[Float]``-carrying
actor messages (SURVEY.md §2 L0 "serializer config for Array[Float] messages").
This is the same layer, purpose-built: each message encodes to
``[u8 tag][fixed struct fields][u32 count][u32 checksum][raw little-endian
float payload]`` and a framed envelope is ``[u32 frame_len][u16 dest_len]
[dest utf8][encoded msg]``. No pickle — the format is versioned by tag,
language-neutral, and float payloads are zero-copy BOTH ways:
``encode_frame_parts`` returns scatter-gather segments whose payload segment
is a ``memoryview`` of the caller's array (the transport hands the segments
to ``sendmsg`` — no concatenation copy ever happens), and decode yields
``np.frombuffer`` views into the receive buffer. Payload frames carry an
additive byte checksum, computed/verified in the native wire hot loop
(``native/wire.cpp``) when built, with an exact struct/numpy fallback.
"""

from __future__ import annotations

import logging
import struct
import threading
from typing import Any

import numpy as np

_log = logging.getLogger(__name__)

from akka_allreduce_tpu import native
from akka_allreduce_tpu.control import cluster as cl
from akka_allreduce_tpu.control import gossip as gp
from akka_allreduce_tpu.control import statetransfer as st
from akka_allreduce_tpu.obs import metrics as _obs_metrics
from akka_allreduce_tpu.protocol import (
    DEFAULT_POLICY,
    CompleteAllreduce,
    ConfirmPreparation,
    PrepareAllreduce,
    ReduceBlock,
    RoundPolicy,
    ScatterBlock,
    StartAllreduce,
)

# one tag per message type; payload-carrying tags end the body with raw f32
# (tags 2/3) or raw checksummed bytes (tag 18 — peer chunk transfer)
_TAGS: dict[type, int] = {
    StartAllreduce: 1,
    ScatterBlock: 2,
    ReduceBlock: 3,
    CompleteAllreduce: 4,
    PrepareAllreduce: 5,
    ConfirmPreparation: 6,
    cl.JoinCluster: 7,
    cl.Welcome: 8,
    cl.Heartbeat: 9,
    cl.LeaveCluster: 10,
    cl.AddressBook: 11,
    cl.Shutdown: 12,
    cl.Rejoin: 13,
    # peer state transfer (control/statetransfer.py, RESILIENCE.md "Recovery")
    st.CheckpointAdvert: 14,
    st.ManifestRequest: 15,
    st.ManifestReply: 16,
    st.ChunkFetch: 17,
    st.ChunkData: 18,
    st.ChunkMissing: 19,
    st.ReplicaManifest: 20,
    # master high availability (RESILIENCE.md "Tier 4 — control-plane
    # failover"): standby registration, the leader's replicated state
    # digest (doubles as its lease heartbeat), and the replacement
    # master's checkpoint-advert solicitation
    cl.StandbyRegister: 21,
    cl.StateDigest: 22,
    st.AdvertSolicit: 23,
    # SWIM gossip membership (control/gossip.py, RESILIENCE.md "Tier 6"):
    # direct probe, indirect-probe request, and the (possibly relayed)
    # acknowledgement — each piggybacking a bounded membership digest
    gp.Ping: 24,
    gp.PingReq: 25,
    gp.Ack: 26,
}

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


def _pack_str(text: str) -> bytes:
    raw = text.encode("utf-8")
    return _U16.pack(len(raw)) + raw


def _unpack_str(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


def _pack_str32(text: str) -> bytes:
    """u32-length string — manifest JSON routinely exceeds the u16 bound
    (one entry per checkpoint leaf)."""
    raw = text.encode("utf-8")
    return _U32.pack(len(raw)) + raw


def _unpack_str32(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


_DIGEST_ENTRY = struct.Struct("<iqB")


def _pack_gossip_digest(digest) -> bytes:
    """``[u16 n]`` + per entry ``[i32 node_id][i64 incarnation][u8 status]``
    — the bounded membership digest on tags 24-26."""
    parts = [_U16.pack(len(digest))]
    for nid, inc, status in digest:
        parts.append(_DIGEST_ENTRY.pack(nid, inc, status))
    return b"".join(parts)


def _unpack_gossip_digest(
    buf: memoryview, off: int
) -> tuple[tuple[tuple[int, int, int], ...], int]:
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    out = []
    for _ in range(n):
        out.append(_DIGEST_ENTRY.unpack_from(buf, off))
        off += _DIGEST_ENTRY.size
    return tuple(out), off


def _unpack_endpoints(
    buf: memoryview, off: int, n: int
) -> tuple[tuple[tuple[str, int], ...], int]:
    """``n`` consecutive ``[str host][u16 port]`` pairs (standby lists)."""
    out = []
    for _ in range(n):
        host, off = _unpack_str(buf, off)
        (port,) = _U16.unpack_from(buf, off)
        off += 2
        out.append((host, port))
    return tuple(out), off


def _chunk_payload_view(payload) -> memoryview:
    """Raw byte view of a ChunkData payload (bytes / bytearray / memoryview
    / u8 ndarray) — stays a view, so the transport's vectored send moves
    the chunk bytes zero-copy exactly like a float payload segment."""
    if isinstance(payload, np.ndarray):
        return memoryview(np.ascontiguousarray(payload)).cast("B")
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    return mv if mv.format == "B" and mv.contiguous else mv.cast("B")


# Top bit of the u32 element count flags a float16 payload (the wire-
# compression mode, MetaDataConfig.wire_dtype): the f32 format is unchanged
# byte for byte, and the flag costs nothing. Bit 30 flags an int8 payload
# (``[f32 scale][i8 x n]`` — the adaptive controller's deepest degrade
# mode, control/adapt.py); the two flags are mutually exclusive. Decode
# always hands the engine float32 — compression lives entirely on the wire.
_F16_FLAG = 0x8000_0000
_I8_FLAG = 0x4000_0000


_F16_MAX = np.float32(65504.0)  # float16's finite range

#: total payload elements saturated at ±65504 by f16 wire casts in this
#: process — saturation silently alters out-of-range values, so operators
#: need a signal (ADVICE r2); read it via ``f16_clip_count()``. Mirrored
#: into the obs registry (``wire.f16_clipped``) so clipping shows up in
#: metrics_snapshot JSONL, not only this module global + a one-shot warn.
_f16_clipped = 0
_f16_clip_warned = False
_F16_CLIPPED = _obs_metrics.counter("wire.f16_clipped")

#: encode runs on the event loop (control frames) AND on payload sender
#: threads (deferred stream encode), so the module-global accounting above
#: is cross-context shared state: every read-modify-write holds this lock
#: (the arlint THRD001 contract; the obs-registry counters beside them are
#: GIL-atomic ``.inc()`` and need none)
_telemetry_lock = threading.Lock()

#: int8 wire-mode error accounting, mirroring the f16 counter pair: the
#: accumulated L1 magnitude of quantization residuals this process put on
#: the wire (``wire.int8_residual_l1`` — what the send-side EF carries
#: forward, see ``int8_roundtrip``), payload count, and non-finite inputs
#: saturated to finite values before scaling
_int8_residual_l1 = 0.0
_INT8_RESIDUAL = _obs_metrics.counter("wire.int8_residual_l1")
_INT8_PAYLOADS = _obs_metrics.counter("wire.int8_payloads")
_INT8_SATURATED = _obs_metrics.counter("wire.int8_saturated")


def f16_clip_count() -> int:
    """Elements the f16 wire mode has saturated since process start."""
    return _f16_clipped


def int8_residual_l1() -> float:
    """Accumulated |residual| the int8 wire mode has injected since
    process start (the error the worker-side EF loop feeds back)."""
    return _int8_residual_l1


def _note_clipped(n: int) -> None:
    global _f16_clipped, _f16_clip_warned
    with _telemetry_lock:
        _f16_clipped += n
        first = not _f16_clip_warned
        _f16_clip_warned = True
    _F16_CLIPPED.inc(n)
    if first:
        _log.warning(
            "f16 wire mode saturated %d out-of-range payload element(s) at "
            "+-65504; values were altered on the wire (further saturation "
            "is counted, not logged — wire.f16_clip_count())",
            n,
        )


#: non-finite int8 inputs saturate here: far past any sane payload, yet
#: ``127 * (_I8_SAT_MAX / 127)`` stays comfortably inside float32, so a
#: saturated chunk dequantizes FINITE (saturating at f32 max would round
#: the corner value back to inf)
_I8_SAT_MAX = np.float32(1e30)


def quantize_int8(value: np.ndarray) -> tuple[float, np.ndarray, np.ndarray]:
    """``(scale, int8 array, sanitized f32 array)`` with a shared
    per-chunk scale (``max|x| / 127``) — ONE definition used by the
    encode path and by the worker's error-feedback loop, so the residual
    the worker carries forward is exactly the error the wire injected.
    Non-finite inputs are saturated first (counted,
    ``wire.int8_saturated``) — a silent inf would zero the whole chunk —
    and the sanitized array is what residuals must be computed against."""
    arr = np.ascontiguousarray(value, dtype=np.float32)
    m = float(np.max(np.abs(arr), initial=0.0))
    if not np.isfinite(m):
        bad = int(np.count_nonzero(~np.isfinite(arr)))
        _INT8_SATURATED.inc(bad)
        arr = np.nan_to_num(arr, posinf=_I8_SAT_MAX, neginf=-_I8_SAT_MAX)
        m = float(np.max(np.abs(arr), initial=0.0))
    scale = m / 127.0 if m > 0.0 else 1.0
    q = np.rint(arr / np.float32(scale)).astype(np.int8)
    return scale, q, arr


def int8_roundtrip(value: np.ndarray) -> np.ndarray:
    """What the receiver will see after an int8 wire round trip — the
    worker's EF loop computes ``residual = value - int8_roundtrip(value)``
    and adds it into the next round's chunk (the same identity as
    ``comm.allreduce.ring_ef_residual`` with v=1: the whole hop error
    carries forward)."""
    scale, q, _ = quantize_int8(value)
    return q.astype(np.float32) * np.float32(scale)


def _pack_floats(value: np.ndarray, mode: str = "f32") -> tuple[memoryview, int]:
    """(payload byte view, count word) — the view aliases the caller's array
    (or the one cast copy), so the send path never copies the payload; the
    transport's vectored write is the only consumer.

    ``mode`` selects the wire precision: ``"f16"`` casts to float16,
    SATURATING at ±65504 (a silent cast would turn out-of-range elements
    into inf and poison every downstream f32 accumulation — unlike bf16,
    float16 trades range for mantissa; saturation is counted and warned
    once, ``f16_clip_count``). ``"int8"`` quantizes with a shared
    per-chunk scale (``[f32 scale][i8 x n]``, quantize_int8) and accounts
    the injected residual (``wire.int8_residual_l1``) — senders that want
    the error back must run the worker's EF loop."""
    if mode == "f16":
        arr32 = np.asarray(value, dtype=np.float32)
        clipped = int(np.count_nonzero(np.abs(arr32) > _F16_MAX))
        if clipped:
            _note_clipped(clipped)
        arr = np.clip(arr32, -_F16_MAX, _F16_MAX).astype("<f2")
        return memoryview(arr).cast("B"), arr.size | _F16_FLAG
    if mode == "int8":
        global _int8_residual_l1
        scale, q, arr32 = quantize_int8(value)
        resid = float(
            np.abs(arr32 - q.astype(np.float32) * np.float32(scale)).sum()
        )
        with _telemetry_lock:
            _int8_residual_l1 += resid
        _INT8_RESIDUAL.inc(resid)
        _INT8_PAYLOADS.inc()
        payload = struct.pack("<f", scale) + q.tobytes()
        return memoryview(payload), q.size | _I8_FLAG
    arr = np.ascontiguousarray(value, dtype="<f4")
    return memoryview(arr).cast("B"), arr.size


def _decode_block(buf: memoryview):
    """Payload-frame body -> (value view, src, dest, chunk, round, count).

    One native call parses the header AND verifies the payload checksum
    (``native.unpack_block``); the returned array is a zero-copy
    ``np.frombuffer`` view into ``buf`` (f16 payloads decompress — the
    astype is the one necessary copy). int8 frames (count-word bit 30)
    predate the native parser's vocabulary, so they take an exact Python
    path here: header struct reads + the generic native checksum — int8
    is the DEGRADED wire mode, so the hot path stays the native call."""
    tag = buf[0]
    cw_off = 21 if tag == 2 else 25 if tag == 3 else None
    if cw_off is not None and len(buf) >= cw_off + 8:
        (count_word,) = _U32.unpack_from(buf, cw_off)
        if count_word & _I8_FLAG:
            return _decode_block_i8(buf, tag, cw_off, count_word)
    src, dest, chunk, rnd, count, n, is_f16, off = native.unpack_block(buf)
    if is_f16:
        value = np.frombuffer(buf, dtype="<f2", count=n, offset=off).astype(
            np.float32
        )
    else:
        value = np.frombuffer(buf, dtype="<f4", count=n, offset=off)
    return value, src, dest, chunk, rnd, count


def _decode_block_i8(buf: memoryview, tag: int, cw_off: int, count_word: int):
    """The int8 arm of the payload decode: ``[f32 scale][i8 x n]`` behind
    the ordinary ``[count_word][checksum]`` header, checksum over the whole
    scale+data payload. Same contracts as the native path: ValueError on
    truncation/corruption, trailing bytes tolerated (``<=`` bound)."""
    if tag == 2:
        src, dest, chunk, rnd = struct.unpack_from("<iiiq", buf, 1)
        count = 0
    else:
        src, dest, chunk, rnd, count = struct.unpack_from("<iiiqi", buf, 1)
    (ck,) = _U32.unpack_from(buf, cw_off + 4)
    off = cw_off + 8
    n = count_word & ~_I8_FLAG
    nbytes = 4 + n  # f32 scale + n int8 elements
    if off + nbytes > len(buf):
        raise ValueError("truncated payload")
    payload = buf[off : off + nbytes]
    if native.wire_checksum(payload) != ck:
        raise ValueError("payload checksum mismatch")
    (scale,) = struct.unpack_from("<f", buf, off)
    q = np.frombuffer(buf, dtype=np.int8, count=n, offset=off + 4)
    value = q.astype(np.float32) * np.float32(scale)
    return value, src, dest, chunk, rnd, count


def _wire_mode(f16: bool, wire: str | None) -> str:
    """Normalize the two wire-precision spellings: an explicit per-frame
    ``wire`` mode (the RoundPolicy path) wins over the transport-default
    ``f16`` bool."""
    if wire:
        return wire
    return "f16" if f16 else "f32"


def encode(msg: Any, *, f16: bool = False, wire: str | None = None) -> bytes:
    """Message -> ``[tag][body]`` bytes."""
    return b"".join(_encode_parts(msg, _wire_mode(f16, wire)))


def _encode_policy(policy: RoundPolicy) -> bytes:
    """``[f32 th_reduce][u8 wire_mode]`` — the RoundPolicy trailing field
    on tags 1/5. Appended AFTER every previously-last field, so an old
    decoder (which reads exactly the bytes it knows) ignores it — the same
    version-skew contract as the trace trailer, ratcheted per tag in
    tests/test_wire_roundtrip.py."""
    return struct.pack(
        "<fB", policy.th_reduce, RoundPolicy.WIRE_MODES.index(policy.wire)
    )


_POLICY_LEN = 5


def _decode_policy(buf: memoryview, off: int) -> RoundPolicy:
    """Inverse of ``_encode_policy`` — a frame too short to carry the
    field is an old encoder's: default policy. Unknown future wire-mode
    bytes degrade to "inherit" rather than refusing the frame."""
    if len(buf) < off + _POLICY_LEN:
        return DEFAULT_POLICY
    th, mode = struct.unpack_from("<fB", buf, off)
    wire_mode = (
        RoundPolicy.WIRE_MODES[mode]
        if mode < len(RoundPolicy.WIRE_MODES)
        else ""
    )
    if not th and not wire_mode:
        return DEFAULT_POLICY
    return RoundPolicy(float(th), wire_mode)


def _encode_parts(msg: Any, mode: str = "f32") -> list:
    """Message -> list of buffer segments (bytes / memoryviews).

    Payload-carrying messages keep the float array as a zero-copy view so the
    caller's single ``join`` is the only copy on the send path. ``mode`` is
    the wire precision for float payloads ("f32"/"f16"/"int8").
    """
    tag = _TAGS.get(type(msg))
    if tag is None:
        raise TypeError(f"no wire tag for {type(msg).__name__}")
    head = bytes([tag])
    if tag == 1:
        return [
            head,
            struct.pack("<qq", msg.round_num, msg.epoch),
            _encode_policy(msg.policy),
        ]
    if tag == 2:
        payload, count_word = _pack_floats(msg.value, mode)
        head = native.pack_block_header(
            2, msg.src_id, msg.dest_id, msg.chunk_id, msg.round_num, 0,
            payload, count_word,
        )
        return [head, payload]
    if tag == 3:
        payload, count_word = _pack_floats(msg.value, mode)
        head = native.pack_block_header(
            3, msg.src_id, msg.dest_id, msg.chunk_id, msg.round_num,
            msg.count, payload, count_word,
        )
        return [head, payload]
    if tag == 4:
        return [head, struct.pack("<iq", msg.src_id, msg.round_num)]
    if tag == 5:
        peers = msg.peer_ids
        # epoch rides AFTER the peer list so the variable-length tail stays
        # where every decoder expects it; the policy stamp is the trailing
        # field after THAT (old decoders stop at the epoch)
        return [
            head,
            struct.pack(
                f"<qiqiH{len(peers)}iq",
                msg.config_id,
                msg.worker_id,
                msg.round_num,
                msg.line_id,
                len(peers),
                *peers,
                msg.epoch,
            ),
            _encode_policy(msg.policy),
        ]
    if tag == 6:
        return [head, struct.pack("<qi", msg.config_id, msg.worker_id)]
    if tag == 7:
        return [
            head,
            _pack_str(msg.host),
            struct.pack("<Hiq", msg.port, msg.preferred_node_id, msg.incarnation),
        ]
    if tag == 8:
        parts = [
            head,
            struct.pack("<i", msg.node_id),
            _pack_str(msg.config_json),
            struct.pack("<qH", msg.epoch, len(msg.standbys)),
        ]
        for h, p in msg.standbys:
            parts.append(_pack_str(h) + _U16.pack(p))
        return parts
    if tag == 9:
        return [
            head,
            struct.pack("<iq", msg.node_id, msg.incarnation),
            _pack_str(msg.host),
            _U16.pack(msg.port),
        ]
    if tag == 10:
        return [head, struct.pack("<i", msg.node_id)]
    if tag == 11:
        parts = [head, _U16.pack(len(msg.entries))]
        for nid, host, port in msg.entries:
            parts.append(struct.pack("<i", nid) + _pack_str(host) + _U16.pack(port))
        parts.append(struct.pack("<qH", msg.epoch, len(msg.standbys)))
        for h, p in msg.standbys:
            parts.append(_pack_str(h) + _U16.pack(p))
        return parts
    if tag == 12:
        return [head, _pack_str(msg.reason), struct.pack("<q", msg.epoch)]
    if tag == 13:
        return [head, _pack_str(msg.reason), struct.pack("<q", msg.epoch)]
    if tag == 14:
        return [
            head,
            struct.pack("<iiq", msg.node_id, msg.origin, msg.step),
            _pack_str32(msg.manifest_json),
        ]
    if tag == 15:
        return [head, struct.pack("<i", msg.node_id)]
    if tag == 16:
        holders = msg.holders
        return [
            head,
            struct.pack("<q", msg.step),
            _pack_str32(msg.manifest_json),
            struct.pack(f"<H{len(holders)}i", len(holders), *holders),
        ]
    if tag == 17:
        return [head, _pack_str(msg.sha), struct.pack("<i", msg.requester)]
    if tag == 18:
        # chunk payload: raw checksummed bytes, zero-copy like tags 2/3 —
        # the payload segment is a memoryview the vectored send gathers
        payload = _chunk_payload_view(msg.payload)
        return [
            head,
            struct.pack("<Biq", 1 if msg.push else 0, msg.origin, msg.step),
            _pack_str(msg.sha),
            struct.pack(
                "<II", payload.nbytes, native.wire_checksum(payload)
            ),
            payload,
        ]
    if tag == 19:
        return [head, _pack_str(msg.sha), struct.pack("<i", msg.holder)]
    if tag == 20:
        return [
            head,
            struct.pack("<qi", msg.step, msg.origin),
            _pack_str32(msg.manifest_json),
        ]
    if tag == 21:
        return [head, _pack_str(msg.host), _U16.pack(msg.port)]
    if tag == 22:
        return [
            head,
            struct.pack("<qq", msg.epoch, msg.seq),
            _pack_str(msg.host),
            _U16.pack(msg.port),
            # the digest body routinely exceeds the u16 string bound (it
            # embeds the full config plus the ckpt manifest registry)
            _pack_str32(msg.state_json),
        ]
    if tag == 23:
        return [head, _pack_str(msg.reason)]
    if tag == 24:
        return [
            head,
            struct.pack("<iqI", msg.sender, msg.incarnation, msg.seq),
            _pack_str(msg.host),
            _U16.pack(msg.port),
            _pack_gossip_digest(msg.digest),
        ]
    if tag == 25:
        return [
            head,
            struct.pack("<iiI", msg.sender, msg.target, msg.seq),
            _pack_gossip_digest(msg.digest),
        ]
    if tag == 26:
        return [
            head,
            struct.pack("<iqI", msg.sender, msg.incarnation, msg.seq),
            _pack_gossip_digest(msg.digest),
        ]
    raise AssertionError(f"unhandled tag {tag}")


def decode(data: bytes | memoryview) -> Any:
    """``[tag][body]`` bytes -> message (float payloads are zero-copy views)."""
    buf = memoryview(data)
    tag = buf[0]
    off = 1
    if tag == 1:
        rnd, epoch = struct.unpack_from("<qq", buf, off)
        return StartAllreduce(rnd, epoch, _decode_policy(buf, off + 16))
    if tag == 2:
        value, src, dest, chunk, rnd, _ = _decode_block(buf)
        return ScatterBlock(value, src, dest, chunk, rnd)
    if tag == 3:
        value, src, dest, chunk, rnd, count = _decode_block(buf)
        return ReduceBlock(value, src, dest, chunk, rnd, count)
    if tag == 4:
        return CompleteAllreduce(*struct.unpack_from("<iq", buf, off))
    if tag == 5:
        config_id, worker_id, round_num, line_id, n = struct.unpack_from(
            "<qiqiH", buf, off
        )
        peers = struct.unpack_from(f"<{n}i", buf, off + 26)
        (epoch,) = struct.unpack_from("<q", buf, off + 26 + 4 * n)
        policy = _decode_policy(buf, off + 34 + 4 * n)
        return PrepareAllreduce(
            config_id, peers, worker_id, round_num, line_id, epoch, policy
        )
    if tag == 6:
        return ConfirmPreparation(*struct.unpack_from("<qi", buf, off))
    if tag == 7:
        host, off = _unpack_str(buf, off)
        port, preferred, incarnation = struct.unpack_from("<Hiq", buf, off)
        return cl.JoinCluster(host, port, preferred, incarnation)
    if tag == 8:
        (node_id,) = struct.unpack_from("<i", buf, off)
        config_json, off = _unpack_str(buf, off + 4)
        epoch, n = struct.unpack_from("<qH", buf, off)
        standbys, off = _unpack_endpoints(buf, off + 10, n)
        return cl.Welcome(node_id, config_json, epoch, standbys)
    if tag == 9:
        node_id, incarnation = struct.unpack_from("<iq", buf, off)
        host, off = _unpack_str(buf, off + 12)
        (port,) = _U16.unpack_from(buf, off)
        return cl.Heartbeat(node_id, incarnation, host, port)
    if tag == 10:
        return cl.LeaveCluster(*struct.unpack_from("<i", buf, off))
    if tag == 11:
        (n,) = _U16.unpack_from(buf, off)
        off += 2
        entries = []
        for _ in range(n):
            (nid,) = struct.unpack_from("<i", buf, off)
            host, off = _unpack_str(buf, off + 4)
            (port,) = _U16.unpack_from(buf, off)
            off += 2
            entries.append((nid, host, port))
        epoch, n_standby = struct.unpack_from("<qH", buf, off)
        standbys, off = _unpack_endpoints(buf, off + 10, n_standby)
        return cl.AddressBook(tuple(entries), epoch, standbys)
    if tag == 12:
        reason, off = _unpack_str(buf, off)
        return cl.Shutdown(reason, *struct.unpack_from("<q", buf, off))
    if tag == 13:
        reason, off = _unpack_str(buf, off)
        return cl.Rejoin(reason, *struct.unpack_from("<q", buf, off))
    if tag == 14:
        node_id, origin, step = struct.unpack_from("<iiq", buf, off)
        manifest, _ = _unpack_str32(buf, off + 16)
        return st.CheckpointAdvert(node_id, origin, step, manifest)
    if tag == 15:
        return st.ManifestRequest(*struct.unpack_from("<i", buf, off))
    if tag == 16:
        (step,) = struct.unpack_from("<q", buf, off)
        manifest, off = _unpack_str32(buf, off + 8)
        (n,) = _U16.unpack_from(buf, off)
        holders = struct.unpack_from(f"<{n}i", buf, off + 2)
        return st.ManifestReply(step, manifest, holders)
    if tag == 17:
        sha, off = _unpack_str(buf, off)
        return st.ChunkFetch(sha, *struct.unpack_from("<i", buf, off))
    if tag == 18:
        push, origin, step = struct.unpack_from("<Biq", buf, off)
        sha, off = _unpack_str(buf, off + 13)
        nbytes, ck = struct.unpack_from("<II", buf, off)
        off += 8
        # bound with <=, never ==: trailing bytes (e.g. the trace trailer)
        # must be tolerated, exactly like the tag-2/3 payload decode
        if off + nbytes > len(buf):
            raise ValueError("truncated chunk payload")
        payload = buf[off : off + nbytes]
        if native.wire_checksum(payload) != ck:
            raise ValueError("chunk payload checksum mismatch")
        # zero-copy u8 view into the receive buffer, like the float tags —
        # the recv-pool export check keeps recycling safe
        value = np.frombuffer(payload, dtype=np.uint8)
        return st.ChunkData(sha, value, origin, step, bool(push))
    if tag == 19:
        sha, off = _unpack_str(buf, off)
        return st.ChunkMissing(sha, *struct.unpack_from("<i", buf, off))
    if tag == 20:
        step, origin = struct.unpack_from("<qi", buf, off)
        manifest, _ = _unpack_str32(buf, off + 12)
        return st.ReplicaManifest(step, manifest, origin)
    if tag == 21:
        host, off = _unpack_str(buf, off)
        return cl.StandbyRegister(host, *_U16.unpack_from(buf, off))
    if tag == 22:
        epoch, seq = struct.unpack_from("<qq", buf, off)
        host, off = _unpack_str(buf, off + 16)
        (port,) = _U16.unpack_from(buf, off)
        state_json, _ = _unpack_str32(buf, off + 2)
        return cl.StateDigest(epoch, seq, host, port, state_json)
    if tag == 23:
        reason, _ = _unpack_str(buf, off)
        return st.AdvertSolicit(reason)
    if tag == 24:
        sender, incarnation, seq = struct.unpack_from("<iqI", buf, off)
        host, off = _unpack_str(buf, off + 16)
        (port,) = _U16.unpack_from(buf, off)
        digest, _ = _unpack_gossip_digest(buf, off + 2)
        return gp.Ping(sender, incarnation, seq, host, port, digest)
    if tag == 25:
        sender, target, seq = struct.unpack_from("<iiI", buf, off)
        digest, _ = _unpack_gossip_digest(buf, off + 12)
        return gp.PingReq(sender, target, seq, digest)
    if tag == 26:
        sender, incarnation, seq = struct.unpack_from("<iqI", buf, off)
        digest, _ = _unpack_gossip_digest(buf, off + 16)
        return gp.Ack(sender, incarnation, seq, digest)
    raise ValueError(f"unknown wire tag {tag}")


# -- trace-context trailer -----------------------------------------------------
#
# Version-skew-compatible by construction (reserved-BYTES encoding, not a new
# tag): a frame carrying trace context appends
#   [u64 trace_id][u64 span_id][u8 flags][8-byte magic]
# AFTER the message body. Every per-tag decode arm reads exactly the bytes it
# needs and ignores anything after them (the payload tags bound-check
# `offset + payload <= len`, never `==` — native and fallback paths alike),
# so a decoder built BEFORE this trailer existed accepts trailered frames
# unchanged, and this decoder accepts trailer-less frames (no magic -> no
# context). tests/test_wire_roundtrip.py ratchets both directions over every
# tag. The magic ends the frame (constant offset from the end — no length
# field to trust) and an accidental 8-byte collision in payload data is a
# 2^-64 event whose worst case is one dropped frame (at-most-once absorbs it).

_TRACE_STRUCT = struct.Struct("<QQB")
_TRACE_MAGIC = b"\x00\xf7aRtC\x9e\x01"
_TRACE_LEN = _TRACE_STRUCT.size + len(_TRACE_MAGIC)
_TRACE_SAMPLED = 0x01


def encode_trace(trace) -> bytes:
    """Trace context (``obs.trace.TraceContext`` or (trace_id, span_id,
    sampled) triple) -> wire trailer bytes."""
    trace_id, span_id, sampled = trace
    return (
        _TRACE_STRUCT.pack(
            trace_id & 0xFFFF_FFFF_FFFF_FFFF,
            span_id & 0xFFFF_FFFF_FFFF_FFFF,
            _TRACE_SAMPLED if sampled else 0,
        )
        + _TRACE_MAGIC
    )


def split_trace(buf: memoryview):
    """``(message bytes view, trace context | None)`` for a frame body whose
    dest prefix is already consumed."""
    n = len(buf)
    if n >= _TRACE_LEN + 1 and bytes(buf[n - 8 : n]) == _TRACE_MAGIC:
        trace_id, span_id, flags = _TRACE_STRUCT.unpack_from(
            buf, n - _TRACE_LEN
        )
        from akka_allreduce_tpu.obs.trace import TraceContext

        return buf[: n - _TRACE_LEN], TraceContext(
            trace_id, span_id, bool(flags & _TRACE_SAMPLED)
        )
    return buf, None


def encode_frame_parts(
    dest: str, msg: Any, *, f16: bool = False, wire: str | None = None,
    trace=None,
) -> list[bytes | memoryview]:
    """Framed envelope as scatter-gather segments:
    ``[u32 len][u16 dest_len][dest][tag][body...][trace trailer?]``.

    The float payload stays a ``memoryview`` of the caller's array — NO
    payload-sized copy happens here or anywhere on the send path: the
    transport passes the segments straight to ``socket.sendmsg`` (writev),
    so the kernel gathers them. The payload memory must stay unmodified
    until the send completes (the engine's frozen-after-reduce buffers and
    snapshot-publishing sources guarantee this). ``f16`` sends float
    payloads at half width; ``wire`` overrides it per frame with an
    explicit mode ("f32"/"f16"/"int8" — the RoundPolicy path; decode is
    stateless, the mode travels in the count-word flags). ``trace``
    appends the 25-byte trace-context trailer (see above — old decoders
    ignore it)."""
    parts: list[Any] = [
        b"", _pack_str(dest), *_encode_parts(msg, _wire_mode(f16, wire))
    ]
    if trace is not None:
        parts.append(encode_trace(trace))
    body_len = sum(len(p) for p in parts)
    parts[0] = _U32.pack(body_len)
    return parts


def encode_frame(
    dest: str, msg: Any, *, f16: bool = False, wire: str | None = None,
    trace=None,
) -> bytes:
    """``encode_frame_parts`` joined to one buffer (compat / tests — the
    transport itself sends the segments unjoined)."""
    return b"".join(
        encode_frame_parts(dest, msg, f16=f16, wire=wire, trace=trace)
    )


# -- multi-stream preamble and frame sizing ------------------------------------
#
# With DataPlaneConfig.streams > 1 a transport opens N sockets per peer
# endpoint. Every such connection opens with a PREAMBLE so the receive side
# knows (a) this is a stream connection, (b) which stream it is, and (c) the
# sender's canonical endpoint (for per-endpoint rx telemetry — the TCP
# peername carries an ephemeral port). The magic's first four bytes are
# 0xFFFFFFFF — as a legacy length prefix that is ~16x over
# ``RemoteTransport.max_frame_bytes``, so no valid legacy frame can ever
# start with it: one 4-byte peek disambiguates the two framings, and a
# legacy (streams=1 / pre-streams) connection is byte-identical to PR-8.
#
# Frames on payload streams (stream_id >= 1) are framed
# ``[u32 body_len][u32 seq][body]`` — the per-stream sequence number is
# framing, not message bytes, so the message wire format (tags, checksums,
# trace trailer) is untouched. Stream 0 keeps legacy ``[u32 len][body]``
# framing after its preamble: control ordering rides one FIFO socket.

STREAM_MAGIC = b"\xff\xff\xff\xffAWS1"
_PREAMBLE_FIXED = struct.Struct("<HHHH")  # stream_id, total, port, host_len


def encode_stream_preamble(
    stream_id: int, total_streams: int, host: str, port: int
) -> bytes:
    """``[magic 8][u16 stream_id][u16 total][u16 port][u16 host_len][host]``."""
    raw = host.encode("utf-8")
    return (
        STREAM_MAGIC
        + _PREAMBLE_FIXED.pack(stream_id, total_streams, port, len(raw))
        + raw
    )


def parse_stream_preamble(buf: memoryview):
    """``(stream_id, total, host, port, consumed) | None`` (need more bytes).

    The caller has already matched :data:`STREAM_MAGIC`'s first 4 bytes;
    a full-magic mismatch raises ``ValueError`` (protocol error — close)."""
    if len(buf) < 8:
        return None
    if bytes(buf[:8]) != STREAM_MAGIC:
        raise ValueError("bad stream preamble magic")
    if len(buf) < 16:
        return None
    stream_id, total, port, host_len = _PREAMBLE_FIXED.unpack_from(buf, 8)
    if host_len > 1024:
        # no real hostname; also keeps the preamble well under the receive
        # ring so an incomplete one can always finish buffering
        raise ValueError(f"stream preamble host_len {host_len} implausible")
    if len(buf) < 16 + host_len:
        return None
    host = bytes(buf[16 : 16 + host_len]).decode("utf-8")
    return stream_id, total, host, port, 16 + host_len


def payload_frame_nbytes(
    dest: str, msg: Any, mode: str, has_trace: bool
) -> int:
    """Exact byte size of ``encode_frame_parts(dest, msg, ...)`` for a
    payload message (ScatterBlock / ReduceBlock) WITHOUT encoding it — the
    deferred-encode senders charge backpressure accounting at enqueue time,
    before the sender thread runs the actual encode + checksum pass.

    NB this is the size of the ENCODED PARTS (length prefix + body); the
    4-byte per-stream seq header is connection framing stamped by the
    sender thread, and the caller accounts for it (+4 per frame)."""
    tag = _TAGS[type(msg)]
    if tag == 2:
        header = 1 + 20 + 8  # tag + <iiiq> + count word + checksum
    elif tag == 3:
        header = 1 + 24 + 8  # tag + <iiiqi> + count word + checksum
    else:  # non-payload messages never take the deferred path
        raise ValueError(f"not a payload frame tag: {tag}")
    n = msg.value.size
    if mode == "f16":
        payload = 2 * n
    elif mode == "int8":
        payload = 4 + n  # f32 scale + i8 elements
    else:
        payload = 4 * n
    return (
        4  # u32 length prefix
        + 2 + len(dest.encode("utf-8"))
        + header
        + payload
        + (_TRACE_LEN if has_trace else 0)
    )


# -- sub-chunk continuation frames (intra-chunk striping) ----------------------
#
# With DataPlaneConfig.intra_chunk_min_bytes set, a payload frame whose
# encoded body reaches the bar is SPLIT across the endpoint's payload
# streams: each stripe carries ``[u32 len][u32 seq]`` framing (the ordinary
# payload-stream framing) around a CONTINUATION body
#   [u16 0xFFFF][u32 frag_id][u32 total_len][u32 offset][fragment bytes]
# where 0xFFFF occupies the position of a normal body's dest-length prefix —
# no real destination string is 65535 bytes (max_frame_bytes caps frames far
# below the implied size), so one 2-byte peek disambiguates continuation
# frames from whole-frame bodies on a payload stream. The receive side
# lands every fragment DIRECTLY at its offset in one pooled frame-sized
# buffer (no join copy — the PR-1 zero-copy contract holds: decode hands
# out views into that buffer) and delivers the reassembled body when
# ``total_len`` bytes have arrived, whatever order the stripes landed in.
#
# Version skew: continuation frames exist only on payload streams, whose
# existence (and this lever's bar) a cluster negotiates via Welcome — a
# legacy peer never opens a payload stream, so it can never meet one.

FRAG_MARKER = 0xFFFF
_FRAG_HDR = struct.Struct("<HIII")
FRAG_HDR_LEN = _FRAG_HDR.size


def encode_frag_header(frag_id: int, total_len: int, offset: int) -> bytes:
    """Continuation header for one stripe of a split payload frame."""
    return _FRAG_HDR.pack(
        FRAG_MARKER, frag_id & 0xFFFF_FFFF, total_len, offset
    )


def parse_frag_header(
    buf: bytes | memoryview,
) -> tuple[int, int, int] | None:
    """``(frag_id, total_len, offset)`` for a continuation body, or None
    when ``buf`` holds fewer than :data:`FRAG_HDR_LEN` bytes (wait for
    more). Raises ``ValueError`` when the marker does not match (the
    caller peeked wrong) or the offset lies outside the total — a
    malformed header must never become an out-of-bounds buffer write."""
    if len(buf) < FRAG_HDR_LEN:
        return None
    marker, frag_id, total_len, offset = _FRAG_HDR.unpack_from(buf, 0)
    if marker != FRAG_MARKER:
        raise ValueError("not a continuation frame")
    if offset >= total_len:
        raise ValueError(
            f"fragment offset {offset} outside body of {total_len} bytes"
        )
    return frag_id, total_len, offset


def slice_parts(parts: list, start: int, end: int) -> list[memoryview]:
    """Byte range ``[start, end)`` of a scatter-gather segment list as
    views — no copy, so a stripe of a deferred-encoded frame reuses the
    one shared encode's payload memory. ``parts`` are the BODY segments
    (``encode_frame_parts(...)[1:]`` — the u32 length prefix is per-stripe
    framing, not body bytes)."""
    out: list[memoryview] = []
    pos = 0
    for p in parts:
        mv = p if isinstance(p, memoryview) else memoryview(p)
        n = len(mv)
        if pos + n <= start or pos >= end:
            pos += n
            continue
        lo = max(0, start - pos)
        hi = min(n, end - pos)
        out.append(mv[lo:hi])
        pos += n
    return out


def decode_frame_body(body: bytes | memoryview) -> tuple[str, Any]:
    """Inverse of ``encode_frame`` minus the length prefix."""
    dest, msg, _ = decode_frame_body_ex(body)
    return dest, msg


def decode_frame_body_ex(body: bytes | memoryview):
    """``(dest, message, trace context | None)`` — the transport's decode."""
    buf = memoryview(body)
    dest, off = _unpack_str(buf, 0)
    rest, trace = split_trace(buf[off:])
    return dest, decode(rest), trace
