"""Binary wire codec for the control-plane protocol.

The reference configures Akka serializers for its ``Array[Float]``-carrying
actor messages (SURVEY.md §2 L0 "serializer config for Array[Float] messages").
This is the same layer, purpose-built: each message encodes to
``[u8 tag][fixed struct fields][u32 count][u32 checksum][raw little-endian
float payload]`` and a framed envelope is ``[u32 frame_len][u16 dest_len]
[dest utf8][encoded msg]``. No pickle — the format is versioned by tag,
language-neutral, and float payloads are zero-copy BOTH ways:
``encode_frame_parts`` returns scatter-gather segments whose payload segment
is a ``memoryview`` of the caller's array (the transport hands the segments
to ``sendmsg`` — no concatenation copy ever happens), and decode yields
``np.frombuffer`` views into the receive buffer. Payload frames carry an
additive byte checksum, computed/verified in the native wire hot loop
(``native/wire.cpp``) when built, with an exact struct/numpy fallback.
"""

from __future__ import annotations

import logging
import struct
from typing import Any

import numpy as np

_log = logging.getLogger(__name__)

from akka_allreduce_tpu import native
from akka_allreduce_tpu.control import cluster as cl
from akka_allreduce_tpu.control import statetransfer as st
from akka_allreduce_tpu.protocol import (
    CompleteAllreduce,
    ConfirmPreparation,
    PrepareAllreduce,
    ReduceBlock,
    ScatterBlock,
    StartAllreduce,
)

# one tag per message type; payload-carrying tags end the body with raw f32
# (tags 2/3) or raw checksummed bytes (tag 18 — peer chunk transfer)
_TAGS: dict[type, int] = {
    StartAllreduce: 1,
    ScatterBlock: 2,
    ReduceBlock: 3,
    CompleteAllreduce: 4,
    PrepareAllreduce: 5,
    ConfirmPreparation: 6,
    cl.JoinCluster: 7,
    cl.Welcome: 8,
    cl.Heartbeat: 9,
    cl.LeaveCluster: 10,
    cl.AddressBook: 11,
    cl.Shutdown: 12,
    cl.Rejoin: 13,
    # peer state transfer (control/statetransfer.py, RESILIENCE.md "Recovery")
    st.CheckpointAdvert: 14,
    st.ManifestRequest: 15,
    st.ManifestReply: 16,
    st.ChunkFetch: 17,
    st.ChunkData: 18,
    st.ChunkMissing: 19,
    st.ReplicaManifest: 20,
    # master high availability (RESILIENCE.md "Tier 4 — control-plane
    # failover"): standby registration, the leader's replicated state
    # digest (doubles as its lease heartbeat), and the replacement
    # master's checkpoint-advert solicitation
    cl.StandbyRegister: 21,
    cl.StateDigest: 22,
    st.AdvertSolicit: 23,
}

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


def _pack_str(text: str) -> bytes:
    raw = text.encode("utf-8")
    return _U16.pack(len(raw)) + raw


def _unpack_str(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


def _pack_str32(text: str) -> bytes:
    """u32-length string — manifest JSON routinely exceeds the u16 bound
    (one entry per checkpoint leaf)."""
    raw = text.encode("utf-8")
    return _U32.pack(len(raw)) + raw


def _unpack_str32(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


def _unpack_endpoints(
    buf: memoryview, off: int, n: int
) -> tuple[tuple[tuple[str, int], ...], int]:
    """``n`` consecutive ``[str host][u16 port]`` pairs (standby lists)."""
    out = []
    for _ in range(n):
        host, off = _unpack_str(buf, off)
        (port,) = _U16.unpack_from(buf, off)
        off += 2
        out.append((host, port))
    return tuple(out), off


def _chunk_payload_view(payload) -> memoryview:
    """Raw byte view of a ChunkData payload (bytes / bytearray / memoryview
    / u8 ndarray) — stays a view, so the transport's vectored send moves
    the chunk bytes zero-copy exactly like a float payload segment."""
    if isinstance(payload, np.ndarray):
        return memoryview(np.ascontiguousarray(payload)).cast("B")
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    return mv if mv.format == "B" and mv.contiguous else mv.cast("B")


# Top bit of the u32 element count flags a float16 payload (the wire-
# compression mode, MetaDataConfig.wire_dtype): the f32 format is unchanged
# byte for byte, and the flag costs nothing. Decode always hands the engine
# float32 — compression lives entirely on the wire.
_F16_FLAG = 0x8000_0000


_F16_MAX = np.float32(65504.0)  # float16's finite range

#: total payload elements saturated at ±65504 by f16 wire casts in this
#: process — saturation silently alters out-of-range values, so operators
#: need a signal (ADVICE r2); read it via ``f16_clip_count()``
_f16_clipped = 0
_f16_clip_warned = False


def f16_clip_count() -> int:
    """Elements the f16 wire mode has saturated since process start."""
    return _f16_clipped


def _note_clipped(n: int) -> None:
    global _f16_clipped, _f16_clip_warned
    _f16_clipped += n
    if not _f16_clip_warned:
        _f16_clip_warned = True
        _log.warning(
            "f16 wire mode saturated %d out-of-range payload element(s) at "
            "+-65504; values were altered on the wire (further saturation "
            "is counted, not logged — wire.f16_clip_count())",
            n,
        )


def _pack_floats(value: np.ndarray, f16: bool = False) -> tuple[memoryview, int]:
    """(payload byte view, count word) — the view aliases the caller's array
    (or the one f16 cast), so the send path never copies the payload; the
    transport's vectored write is the only consumer. ``f16`` casts the
    payload to float16 for the wire, SATURATING at ±65504: a silent cast
    would turn out-of-range elements into inf and poison every downstream
    f32 accumulation (unlike bf16, float16 trades range for mantissa).
    Saturation is counted and warned once (``f16_clip_count``)."""
    if f16:
        arr32 = np.asarray(value, dtype=np.float32)
        clipped = int(np.count_nonzero(np.abs(arr32) > _F16_MAX))
        if clipped:
            _note_clipped(clipped)
        arr = np.clip(arr32, -_F16_MAX, _F16_MAX).astype("<f2")
        return memoryview(arr).cast("B"), arr.size | _F16_FLAG
    arr = np.ascontiguousarray(value, dtype="<f4")
    return memoryview(arr).cast("B"), arr.size


def _decode_block(buf: memoryview):
    """Payload-frame body -> (value view, src, dest, chunk, round, count).

    One native call parses the header AND verifies the payload checksum
    (``native.unpack_block``); the returned array is a zero-copy
    ``np.frombuffer`` view into ``buf`` (f16 payloads decompress — the
    astype is the one necessary copy)."""
    src, dest, chunk, rnd, count, n, is_f16, off = native.unpack_block(buf)
    if is_f16:
        value = np.frombuffer(buf, dtype="<f2", count=n, offset=off).astype(
            np.float32
        )
    else:
        value = np.frombuffer(buf, dtype="<f4", count=n, offset=off)
    return value, src, dest, chunk, rnd, count


def encode(msg: Any, *, f16: bool = False) -> bytes:
    """Message -> ``[tag][body]`` bytes."""
    return b"".join(_encode_parts(msg, f16))


def _encode_parts(msg: Any, f16: bool = False) -> list:
    """Message -> list of buffer segments (bytes / memoryviews).

    Payload-carrying messages keep the float array as a zero-copy view so the
    caller's single ``join`` is the only copy on the send path.
    """
    tag = _TAGS.get(type(msg))
    if tag is None:
        raise TypeError(f"no wire tag for {type(msg).__name__}")
    head = bytes([tag])
    if tag == 1:
        return [head, struct.pack("<qq", msg.round_num, msg.epoch)]
    if tag == 2:
        payload, count_word = _pack_floats(msg.value, f16)
        head = native.pack_block_header(
            2, msg.src_id, msg.dest_id, msg.chunk_id, msg.round_num, 0,
            payload, count_word,
        )
        return [head, payload]
    if tag == 3:
        payload, count_word = _pack_floats(msg.value, f16)
        head = native.pack_block_header(
            3, msg.src_id, msg.dest_id, msg.chunk_id, msg.round_num,
            msg.count, payload, count_word,
        )
        return [head, payload]
    if tag == 4:
        return [head, struct.pack("<iq", msg.src_id, msg.round_num)]
    if tag == 5:
        peers = msg.peer_ids
        # epoch rides AFTER the peer list so the variable-length tail stays
        # where every decoder expects it
        return [
            head,
            struct.pack(
                f"<qiqiH{len(peers)}iq",
                msg.config_id,
                msg.worker_id,
                msg.round_num,
                msg.line_id,
                len(peers),
                *peers,
                msg.epoch,
            ),
        ]
    if tag == 6:
        return [head, struct.pack("<qi", msg.config_id, msg.worker_id)]
    if tag == 7:
        return [
            head,
            _pack_str(msg.host),
            struct.pack("<Hiq", msg.port, msg.preferred_node_id, msg.incarnation),
        ]
    if tag == 8:
        parts = [
            head,
            struct.pack("<i", msg.node_id),
            _pack_str(msg.config_json),
            struct.pack("<qH", msg.epoch, len(msg.standbys)),
        ]
        for h, p in msg.standbys:
            parts.append(_pack_str(h) + _U16.pack(p))
        return parts
    if tag == 9:
        return [
            head,
            struct.pack("<iq", msg.node_id, msg.incarnation),
            _pack_str(msg.host),
            _U16.pack(msg.port),
        ]
    if tag == 10:
        return [head, struct.pack("<i", msg.node_id)]
    if tag == 11:
        parts = [head, _U16.pack(len(msg.entries))]
        for nid, host, port in msg.entries:
            parts.append(struct.pack("<i", nid) + _pack_str(host) + _U16.pack(port))
        parts.append(struct.pack("<qH", msg.epoch, len(msg.standbys)))
        for h, p in msg.standbys:
            parts.append(_pack_str(h) + _U16.pack(p))
        return parts
    if tag == 12:
        return [head, _pack_str(msg.reason), struct.pack("<q", msg.epoch)]
    if tag == 13:
        return [head, _pack_str(msg.reason), struct.pack("<q", msg.epoch)]
    if tag == 14:
        return [
            head,
            struct.pack("<iiq", msg.node_id, msg.origin, msg.step),
            _pack_str32(msg.manifest_json),
        ]
    if tag == 15:
        return [head, struct.pack("<i", msg.node_id)]
    if tag == 16:
        holders = msg.holders
        return [
            head,
            struct.pack("<q", msg.step),
            _pack_str32(msg.manifest_json),
            struct.pack(f"<H{len(holders)}i", len(holders), *holders),
        ]
    if tag == 17:
        return [head, _pack_str(msg.sha), struct.pack("<i", msg.requester)]
    if tag == 18:
        # chunk payload: raw checksummed bytes, zero-copy like tags 2/3 —
        # the payload segment is a memoryview the vectored send gathers
        payload = _chunk_payload_view(msg.payload)
        return [
            head,
            struct.pack("<Biq", 1 if msg.push else 0, msg.origin, msg.step),
            _pack_str(msg.sha),
            struct.pack(
                "<II", payload.nbytes, native.wire_checksum(payload)
            ),
            payload,
        ]
    if tag == 19:
        return [head, _pack_str(msg.sha), struct.pack("<i", msg.holder)]
    if tag == 20:
        return [
            head,
            struct.pack("<qi", msg.step, msg.origin),
            _pack_str32(msg.manifest_json),
        ]
    if tag == 21:
        return [head, _pack_str(msg.host), _U16.pack(msg.port)]
    if tag == 22:
        return [
            head,
            struct.pack("<qq", msg.epoch, msg.seq),
            _pack_str(msg.host),
            _U16.pack(msg.port),
            # the digest body routinely exceeds the u16 string bound (it
            # embeds the full config plus the ckpt manifest registry)
            _pack_str32(msg.state_json),
        ]
    if tag == 23:
        return [head, _pack_str(msg.reason)]
    raise AssertionError(f"unhandled tag {tag}")


def decode(data: bytes | memoryview) -> Any:
    """``[tag][body]`` bytes -> message (float payloads are zero-copy views)."""
    buf = memoryview(data)
    tag = buf[0]
    off = 1
    if tag == 1:
        return StartAllreduce(*struct.unpack_from("<qq", buf, off))
    if tag == 2:
        value, src, dest, chunk, rnd, _ = _decode_block(buf)
        return ScatterBlock(value, src, dest, chunk, rnd)
    if tag == 3:
        value, src, dest, chunk, rnd, count = _decode_block(buf)
        return ReduceBlock(value, src, dest, chunk, rnd, count)
    if tag == 4:
        return CompleteAllreduce(*struct.unpack_from("<iq", buf, off))
    if tag == 5:
        config_id, worker_id, round_num, line_id, n = struct.unpack_from(
            "<qiqiH", buf, off
        )
        peers = struct.unpack_from(f"<{n}i", buf, off + 26)
        (epoch,) = struct.unpack_from("<q", buf, off + 26 + 4 * n)
        return PrepareAllreduce(
            config_id, peers, worker_id, round_num, line_id, epoch
        )
    if tag == 6:
        return ConfirmPreparation(*struct.unpack_from("<qi", buf, off))
    if tag == 7:
        host, off = _unpack_str(buf, off)
        port, preferred, incarnation = struct.unpack_from("<Hiq", buf, off)
        return cl.JoinCluster(host, port, preferred, incarnation)
    if tag == 8:
        (node_id,) = struct.unpack_from("<i", buf, off)
        config_json, off = _unpack_str(buf, off + 4)
        epoch, n = struct.unpack_from("<qH", buf, off)
        standbys, off = _unpack_endpoints(buf, off + 10, n)
        return cl.Welcome(node_id, config_json, epoch, standbys)
    if tag == 9:
        node_id, incarnation = struct.unpack_from("<iq", buf, off)
        host, off = _unpack_str(buf, off + 12)
        (port,) = _U16.unpack_from(buf, off)
        return cl.Heartbeat(node_id, incarnation, host, port)
    if tag == 10:
        return cl.LeaveCluster(*struct.unpack_from("<i", buf, off))
    if tag == 11:
        (n,) = _U16.unpack_from(buf, off)
        off += 2
        entries = []
        for _ in range(n):
            (nid,) = struct.unpack_from("<i", buf, off)
            host, off = _unpack_str(buf, off + 4)
            (port,) = _U16.unpack_from(buf, off)
            off += 2
            entries.append((nid, host, port))
        epoch, n_standby = struct.unpack_from("<qH", buf, off)
        standbys, off = _unpack_endpoints(buf, off + 10, n_standby)
        return cl.AddressBook(tuple(entries), epoch, standbys)
    if tag == 12:
        reason, off = _unpack_str(buf, off)
        return cl.Shutdown(reason, *struct.unpack_from("<q", buf, off))
    if tag == 13:
        reason, off = _unpack_str(buf, off)
        return cl.Rejoin(reason, *struct.unpack_from("<q", buf, off))
    if tag == 14:
        node_id, origin, step = struct.unpack_from("<iiq", buf, off)
        manifest, _ = _unpack_str32(buf, off + 16)
        return st.CheckpointAdvert(node_id, origin, step, manifest)
    if tag == 15:
        return st.ManifestRequest(*struct.unpack_from("<i", buf, off))
    if tag == 16:
        (step,) = struct.unpack_from("<q", buf, off)
        manifest, off = _unpack_str32(buf, off + 8)
        (n,) = _U16.unpack_from(buf, off)
        holders = struct.unpack_from(f"<{n}i", buf, off + 2)
        return st.ManifestReply(step, manifest, holders)
    if tag == 17:
        sha, off = _unpack_str(buf, off)
        return st.ChunkFetch(sha, *struct.unpack_from("<i", buf, off))
    if tag == 18:
        push, origin, step = struct.unpack_from("<Biq", buf, off)
        sha, off = _unpack_str(buf, off + 13)
        nbytes, ck = struct.unpack_from("<II", buf, off)
        off += 8
        # bound with <=, never ==: trailing bytes (e.g. the trace trailer)
        # must be tolerated, exactly like the tag-2/3 payload decode
        if off + nbytes > len(buf):
            raise ValueError("truncated chunk payload")
        payload = buf[off : off + nbytes]
        if native.wire_checksum(payload) != ck:
            raise ValueError("chunk payload checksum mismatch")
        # zero-copy u8 view into the receive buffer, like the float tags —
        # the recv-pool export check keeps recycling safe
        value = np.frombuffer(payload, dtype=np.uint8)
        return st.ChunkData(sha, value, origin, step, bool(push))
    if tag == 19:
        sha, off = _unpack_str(buf, off)
        return st.ChunkMissing(sha, *struct.unpack_from("<i", buf, off))
    if tag == 20:
        step, origin = struct.unpack_from("<qi", buf, off)
        manifest, _ = _unpack_str32(buf, off + 12)
        return st.ReplicaManifest(step, manifest, origin)
    if tag == 21:
        host, off = _unpack_str(buf, off)
        return cl.StandbyRegister(host, *_U16.unpack_from(buf, off))
    if tag == 22:
        epoch, seq = struct.unpack_from("<qq", buf, off)
        host, off = _unpack_str(buf, off + 16)
        (port,) = _U16.unpack_from(buf, off)
        state_json, _ = _unpack_str32(buf, off + 2)
        return cl.StateDigest(epoch, seq, host, port, state_json)
    if tag == 23:
        reason, _ = _unpack_str(buf, off)
        return st.AdvertSolicit(reason)
    raise ValueError(f"unknown wire tag {tag}")


# -- trace-context trailer -----------------------------------------------------
#
# Version-skew-compatible by construction (reserved-BYTES encoding, not a new
# tag): a frame carrying trace context appends
#   [u64 trace_id][u64 span_id][u8 flags][8-byte magic]
# AFTER the message body. Every per-tag decode arm reads exactly the bytes it
# needs and ignores anything after them (the payload tags bound-check
# `offset + payload <= len`, never `==` — native and fallback paths alike),
# so a decoder built BEFORE this trailer existed accepts trailered frames
# unchanged, and this decoder accepts trailer-less frames (no magic -> no
# context). tests/test_wire_roundtrip.py ratchets both directions over every
# tag. The magic ends the frame (constant offset from the end — no length
# field to trust) and an accidental 8-byte collision in payload data is a
# 2^-64 event whose worst case is one dropped frame (at-most-once absorbs it).

_TRACE_STRUCT = struct.Struct("<QQB")
_TRACE_MAGIC = b"\x00\xf7aRtC\x9e\x01"
_TRACE_LEN = _TRACE_STRUCT.size + len(_TRACE_MAGIC)
_TRACE_SAMPLED = 0x01


def encode_trace(trace) -> bytes:
    """Trace context (``obs.trace.TraceContext`` or (trace_id, span_id,
    sampled) triple) -> wire trailer bytes."""
    trace_id, span_id, sampled = trace
    return (
        _TRACE_STRUCT.pack(
            trace_id & 0xFFFF_FFFF_FFFF_FFFF,
            span_id & 0xFFFF_FFFF_FFFF_FFFF,
            _TRACE_SAMPLED if sampled else 0,
        )
        + _TRACE_MAGIC
    )


def split_trace(buf: memoryview):
    """``(message bytes view, trace context | None)`` for a frame body whose
    dest prefix is already consumed."""
    n = len(buf)
    if n >= _TRACE_LEN + 1 and bytes(buf[n - 8 : n]) == _TRACE_MAGIC:
        trace_id, span_id, flags = _TRACE_STRUCT.unpack_from(
            buf, n - _TRACE_LEN
        )
        from akka_allreduce_tpu.obs.trace import TraceContext

        return buf[: n - _TRACE_LEN], TraceContext(
            trace_id, span_id, bool(flags & _TRACE_SAMPLED)
        )
    return buf, None


def encode_frame_parts(
    dest: str, msg: Any, *, f16: bool = False, trace=None
) -> list[bytes | memoryview]:
    """Framed envelope as scatter-gather segments:
    ``[u32 len][u16 dest_len][dest][tag][body...][trace trailer?]``.

    The float payload stays a ``memoryview`` of the caller's array — NO
    payload-sized copy happens here or anywhere on the send path: the
    transport passes the segments straight to ``socket.sendmsg`` (writev),
    so the kernel gathers them. The payload memory must stay unmodified
    until the send completes (the engine's frozen-after-reduce buffers and
    snapshot-publishing sources guarantee this). ``f16`` sends float
    payloads at half width (decode side is automatic). ``trace`` appends
    the 25-byte trace-context trailer (see above — old decoders ignore
    it)."""
    parts: list[Any] = [b"", _pack_str(dest), *_encode_parts(msg, f16)]
    if trace is not None:
        parts.append(encode_trace(trace))
    body_len = sum(len(p) for p in parts)
    parts[0] = _U32.pack(body_len)
    return parts


def encode_frame(dest: str, msg: Any, *, f16: bool = False, trace=None) -> bytes:
    """``encode_frame_parts`` joined to one buffer (compat / tests — the
    transport itself sends the segments unjoined)."""
    return b"".join(encode_frame_parts(dest, msg, f16=f16, trace=trace))


def decode_frame_body(body: bytes | memoryview) -> tuple[str, Any]:
    """Inverse of ``encode_frame`` minus the length prefix."""
    dest, msg, _ = decode_frame_body_ex(body)
    return dest, msg


def decode_frame_body_ex(body: bytes | memoryview):
    """``(dest, message, trace context | None)`` — the transport's decode."""
    buf = memoryview(body)
    dest, off = _unpack_str(buf, 0)
    rest, trace = split_trace(buf[off:])
    return dest, decode(rest), trace
