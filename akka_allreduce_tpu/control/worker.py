"""The worker engine: per-round scatter-reduce-allgather state machine.

Host-engine equivalent of the reference's ``AllreduceWorker`` (SURVEY.md §3):
on ``StartAllreduce`` fetch from the data source, partition into P blocks, chunk
by ``max_chunk_size``, scatter to peers; on ``ScatterBlock`` accumulate and — at
the ``th_reduce`` crossing — reduce and broadcast; on ``ReduceBlock`` assemble
and — at ``th_complete`` — flush to the data sink and report completion
(SURVEY.md §4.2 call stack).

Round discipline: a bounded out-of-order window absorbs peers running ahead;
when a *newer* round completes first, older in-flight rounds are abandoned
(their data is stale for SGD — the same discipline the reference's threshold
design embodies: never wait for stragglers).

On the TPU path this engine handles only control messages; payload movement
happens in the XLA collective. The payload-carrying path below is exercised by
tests, the CPU fallback, and DCN-side movement.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

import numpy as np

from akka_allreduce_tpu.buffers import RoundBuffers, RoundOutOfWindowError
from akka_allreduce_tpu.config import (
    MetaDataConfig,
    ThresholdConfig,
    WorkerConfig,
)
from akka_allreduce_tpu.control import wire as wire_codec
from akka_allreduce_tpu.control.envelope import Envelope, master_addr, peer_addr
from akka_allreduce_tpu.obs import flight as obs_flight
from akka_allreduce_tpu.obs import metrics as obs_metrics
from akka_allreduce_tpu.obs import trace as obs_trace
from akka_allreduce_tpu.protocol import (
    DEFAULT_POLICY,
    AllReduceInput,
    AllReduceInputRequest,
    AllReduceOutput,
    CompleteAllreduce,
    ConfirmPreparation,
    PrepareAllreduce,
    ReduceBlock,
    RoundPolicy,
    ScatterBlock,
    StartAllreduce,
)

log = logging.getLogger(__name__)

DataSource = Callable[[AllReduceInputRequest], AllReduceInput]
DataSink = Callable[[AllReduceOutput], None]

# process-wide worker stats (summed over a node's per-dimension workers):
# the in-flight round gauge is what a flight-recorder dump names first
_ROUNDS_COMPLETED = obs_metrics.counter("worker.rounds_completed")
_DROPPED = obs_metrics.counter("worker.dropped_messages")
_ROUND_IN_FLIGHT = obs_metrics.gauge("worker.round_in_flight")


class AllreduceWorker:
    """Transport-agnostic worker: feed messages to ``handle``, send what it returns."""

    def __init__(
        self,
        data_source: DataSource,
        data_sink: DataSink,
        config: WorkerConfig = WorkerConfig(),
        line_id: int = 0,
    ) -> None:
        self.data_source = data_source
        self.data_sink = data_sink
        self.config = config
        self.line_id = line_id
        # configured state (set by PrepareAllreduce)
        self.worker_id: int | None = None
        self.peer_ids: tuple[int, ...] = ()
        self.config_id: int = -1
        self.metadata: MetaDataConfig | None = None
        self.threshold: ThresholdConfig | None = None
        self.rounds: RoundBuffers | None = None
        self.completed_rounds = 0
        self.dropped_messages = 0
        # highest round this worker ever FLUSHED to its sink — the
        # cross-epoch dedup floor (RESILIENCE.md "Tier 4"): a replacement
        # master restoring from a slightly stale digest may re-issue a
        # round id this worker already applied; the floor turns that
        # re-Start into a CompleteAllreduce re-assert instead of a second
        # flush of the same round. Callers that rebuild the worker (a node
        # rejoin) carry the value across instances via AllreduceNode.
        self.flushed_up_to = -1
        # per-round degradation policy (RESILIENCE.md "Tier 5"): the
        # StartAllreduce stamp, applied to this round's reduce trigger and
        # this round's outgoing payload frames — every worker sees the
        # SAME stamp for a round id, so thresholds can never disagree
        self._policies: dict[int, RoundPolicy] = {}
        # the newest Start's stamp (monotone by round id) — the ICI-side
        # adaptive loop's observation point (RESILIENCE.md "Tier 7"): the
        # trainer loop polls this to follow the leader's wire ladder; a
        # DEFAULT stamp is recorded too, so a restore to full fidelity is
        # just as visible as a degrade
        self.last_policy: RoundPolicy = DEFAULT_POLICY
        self.last_policy_round: int = -1
        # int8 wire-mode error feedback: per-(dest worker, chunk) residual
        # of the last quantized send, added into the next round's chunk —
        # the ring_ef_residual identity (comm/allreduce.py) with v=1: the
        # whole hop error carries forward, so steady-state reduce error
        # stays bounded by ONE quantization step instead of accumulating
        self._ef_residual: dict[tuple[int, int], np.ndarray] = {}

    # -- configuration -------------------------------------------------------

    def configure(
        self, metadata: MetaDataConfig, threshold: ThresholdConfig
    ) -> None:
        """Set payload geometry + thresholds (bootstrap, before Prepare)."""
        self.metadata = metadata
        self.threshold = threshold

    @property
    def peer_size(self) -> int:
        return len(self.peer_ids)

    def _require_ready(self) -> RoundBuffers:
        if self.rounds is None:
            raise RuntimeError(
                "worker not prepared: PrepareAllreduce must precede rounds"
            )
        return self.rounds

    # -- message dispatch ----------------------------------------------------

    def handle(self, msg: Any) -> list[Envelope]:
        if isinstance(msg, PrepareAllreduce):
            return self._on_prepare(msg)
        if isinstance(msg, StartAllreduce):
            return self._on_start(msg)
        if isinstance(msg, ScatterBlock):
            return self._on_scatter(msg)
        if isinstance(msg, ReduceBlock):
            return self._on_reduce(msg)
        raise TypeError(f"worker cannot handle {type(msg).__name__}")

    # -- handlers ------------------------------------------------------------

    def _on_prepare(self, msg: PrepareAllreduce) -> list[Envelope]:
        if self.metadata is None or self.threshold is None:
            raise RuntimeError("configure(metadata, threshold) before Prepare")
        if (
            msg.config_id == self.config_id
            and msg.worker_id == self.worker_id
            and self.rounds is not None
        ):
            # duplicate of the current config (the master re-sends Prepare
            # when a confirm is slow/lost): just re-confirm — rebuilding would
            # destroy in-flight round state
            return [
                Envelope(
                    master_addr(self.line_id),
                    ConfirmPreparation(msg.config_id, msg.worker_id),
                )
            ]
        self.worker_id = msg.worker_id
        self.peer_ids = msg.peer_ids
        self.config_id = msg.config_id
        self.line_id = msg.line_id
        # a new configuration resets per-round policies and the EF keys
        # (both are keyed against the old peer set); the Prepare's own
        # policy stamp seeds rounds whose Start we have not seen yet
        self._policies.clear()
        self._ef_residual.clear()
        if not msg.policy.is_default:
            self._policies[msg.round_num] = msg.policy
        self.rounds = RoundBuffers(
            self.metadata,
            self.threshold,
            peer_size=len(msg.peer_ids),
            window=self.config.round_window,
        )
        # resume numbering where the master says (late joiner / re-mesh) —
        # floored at the rounds this worker already flushed, so a new
        # master epoch resuming from a stale digest can never make us
        # apply a round twice (its re-Start gets a re-assert instead)
        self.rounds.completed_up_to = max(msg.round_num - 1, self.flushed_up_to)
        log.info(
            "worker %s prepared: config=%d peers=%s from round %d",
            self.worker_id,
            msg.config_id,
            msg.peer_ids,
            msg.round_num,
        )
        return [
            Envelope(
                master_addr(self.line_id),
                ConfirmPreparation(msg.config_id, msg.worker_id),
            )
        ]

    def _on_start(self, msg: StartAllreduce) -> list[Envelope]:
        rounds = self._require_ready()
        r = msg.round_num
        if not rounds.in_window(r):
            if r > rounds.completed_up_to + rounds.window:
                # The master started r, so older rounds are abandoned
                # cluster-wide: fast-forward instead of wedging forever behind
                # the window (a lagging worker must rejoin, not retire).
                rounds.fast_forward(r)
                log.info(
                    "worker %s: fast-forwarded to round window ending at %d",
                    self.worker_id,
                    r,
                )
            else:  # stale round: already completed locally
                self.dropped_messages += 1
                _DROPPED.inc()
                # the master re-Starts a stalled round at workers missing
                # from its completion set — being asked about a round we
                # already finished means our CompleteAllreduce was lost
                # (at-most-once): re-assert it. Idempotent at the line
                # master (stale/duplicate completions are ignored).
                assert self.worker_id is not None
                return [
                    Envelope(
                        master_addr(self.line_id),
                        CompleteAllreduce(self.worker_id, r),
                    )
                ]
        # the round this worker is actively working on — the first thing a
        # flight-recorder post-mortem wants to know
        _ROUND_IN_FLIGHT.set(r)
        obs_flight.set_state("worker.round_in_flight", r)
        # apply the round's policy stamp BEFORE scattering: the trigger
        # must be in force when our own self-delivery contributions land,
        # and chunks that peers already filled past the (lowered) trigger
        # fire their once-only reduce-broadcast right now
        for stale in [k for k in self._policies if k <= rounds.completed_up_to]:
            del self._policies[stale]
        out: list[Envelope] = []
        pol = msg.policy
        if r >= self.last_policy_round:
            # Start is authoritative for its round; an out-of-order OLDER
            # Start (window overlap) must not regress the observation
            self.last_policy = pol
            self.last_policy_round = r
        if pol.is_default:
            # the Start's stamp is authoritative for its round id: drop a
            # Prepare-seeded policy it supersedes (the controller may have
            # restored between the Prepare and the line's first Start — the
            # round must run at the mode the master froze for it, not the
            # seed), so _wire_for/_round_policy agree with the master
            self._policies.pop(r, None)
        else:
            self._policies[r] = pol
        trig = pol.reduce_count(self.peer_size)
        if trig is not None:
            buf = rounds.scattered(r)
            for chunk_id in buf.set_reduce_trigger(trig):
                out.extend(self._reduce_and_broadcast(buf, r, chunk_id))
        with obs_trace.span(
            "worker.round_start", worker=self.worker_id, round=r
        ):
            out.extend(self._scatter_round(msg))
        return out

    def _round_policy(self, r: int) -> RoundPolicy:
        return self._policies.get(r, DEFAULT_POLICY)

    def _wire_for(self, r: int) -> str | None:
        """Per-frame wire precision for round ``r``'s payload envelopes
        (None = the transport's configured default)."""
        return self._round_policy(r).wire or None

    def _scatter_round(self, msg: StartAllreduce) -> list[Envelope]:
        r = msg.round_num
        data = self.data_source(AllReduceInputRequest(r)).data
        meta = self.metadata
        assert meta is not None
        if data.shape != (meta.data_size,):
            raise ValueError(
                f"dataSource returned shape {data.shape}, expected ({meta.data_size},)"
            )
        out: list[Envelope] = []
        block = meta.block_size(self.peer_size)
        n_chunks = meta.chunks_per_block(self.peer_size)
        # Partition my input into one block per peer, chunk each block; only
        # chunks running past data_size materialize a zero-padded tail (peers
        # trim the padding on flush). With ``zero_copy_scatter`` the chunks
        # are views of the source's array all the way to the socket: the
        # transport's vectored write (sendmsg of [header, payload view])
        # reads the chunk's LIVE memory at write time, with no copy at any
        # layer — sound only for snapshot-publishing sources, see
        # WorkerConfig. Otherwise each chunk is snapshotted here,
        # synchronously, and the snapshot is what the socket reads.
        data = np.ascontiguousarray(data, dtype=np.float32)
        zero_copy = self.config.zero_copy_scatter
        pol = self._round_policy(r)
        wire_mode = pol.wire or None
        int8 = pol.wire == "int8"
        if not int8 and self._ef_residual:
            # the mode restored out of int8: the pending corrections are
            # bounded by one quantization step — drop them rather than
            # inject stale int8-era error into full-fidelity rounds
            self._ef_residual.clear()
        my_id = self.worker_id
        assert my_id is not None
        my_rank = self.peer_ids.index(my_id)
        for dest_rank, dest_id in enumerate(self.peer_ids):
            for c in range(n_chunks):
                lo = dest_rank * block + c * meta.max_chunk_size
                hi = min(lo + meta.max_chunk_size, (dest_rank + 1) * block)
                if hi <= meta.data_size:
                    chunk = data[lo:hi] if zero_copy else data[lo:hi].copy()
                else:
                    chunk = np.zeros(hi - lo, dtype=np.float32)
                    if lo < meta.data_size:
                        chunk[: meta.data_size - lo] = data[lo:]
                if int8 and dest_id != my_id:
                    # error feedback on the wire-bound copy (self-delivery
                    # never quantizes): fold the last send's residual in,
                    # then carry THIS send's residual forward — computed
                    # with the exact quantizer the encode path runs
                    # (wire.quantize_int8), so sent - received == residual
                    prev = self._ef_residual.pop((dest_id, c), None)
                    if prev is not None and prev.shape == chunk.shape:
                        chunk = chunk + prev
                    self._ef_residual[(dest_id, c)] = (
                        chunk - wire_codec.int8_roundtrip(chunk)
                    )
                sb = ScatterBlock(chunk, my_rank, dest_rank, c, r)
                if dest_id == my_id:
                    out.extend(self._on_scatter(sb))  # self-delivery, no wire
                else:
                    out.append(
                        Envelope(peer_addr(dest_id), sb, wire=wire_mode)
                    )
        return out

    def _on_scatter(self, msg: ScatterBlock) -> list[Envelope]:
        rounds = self._require_ready()
        r = msg.round_num
        try:
            buf = rounds.scattered(r)
        except RoundOutOfWindowError:
            self.dropped_messages += 1
            _DROPPED.inc()
            return []
        crossed = buf.store(msg.value, msg.src_id, msg.chunk_id)
        if not crossed:
            return []
        return self._reduce_and_broadcast(buf, r, msg.chunk_id)

    def _reduce_and_broadcast(self, buf, r: int, chunk_id: int) -> list[Envelope]:
        """The once-per-chunk reduce + broadcast body — fired either by
        ``store``'s trigger crossing or by a RoundPolicy lowering the
        trigger under contributions that already satisfy it. The broadcast
        rides at the round's policy wire mode (decode is stateless, so a
        frame sent before the policy stamp arrived mixes harmlessly)."""
        with obs_trace.span(
            "worker.reduce",
            worker=self.worker_id,
            round=r,
            chunk=chunk_id,
        ):
            value, count = buf.reduce(chunk_id)
            my_rank = self.peer_ids.index(self.worker_id)
            wire_mode = self._wire_for(r)
            out: list[Envelope] = []
            for dest_id in self.peer_ids:
                rb = ReduceBlock(value, my_rank, 0, chunk_id, r, count)
                if dest_id == self.worker_id:
                    out.extend(self._on_reduce(rb))
                else:
                    out.append(
                        Envelope(peer_addr(dest_id), rb, wire=wire_mode)
                    )
            return out

    def _on_reduce(self, msg: ReduceBlock) -> list[Envelope]:
        rounds = self._require_ready()
        r = msg.round_num
        try:
            buf = rounds.reduced(r)
        except RoundOutOfWindowError:
            self.dropped_messages += 1
            _DROPPED.inc()
            return []
        buf.store(msg.value, msg.src_id, msg.chunk_id, msg.count)
        if not buf.reach_completion_threshold():
            return []
        # copy=False: the round is evicted on the next line, so the flushed
        # view's storage is never written again
        with obs_trace.span(
            "worker.flush", worker=self.worker_id, round=r
        ):
            data, counts = buf.get_with_counts(copy=False)
            rounds.complete(r)  # evicts this round AND abandons older ones
            self.completed_rounds += 1
            self.flushed_up_to = max(self.flushed_up_to, r)
            for stale in [k for k in self._policies if k <= r]:
                del self._policies[stale]  # evicted with their rounds
            self.data_sink(AllReduceOutput(data, counts, r))
        _ROUNDS_COMPLETED.inc()
        obs_flight.set_state("worker.last_completed_round", r)
        # between rounds nothing is in flight: a post-mortem taken now must
        # not misdirect the operator to a round that actually completed
        if obs_flight.get_state("worker.round_in_flight") == r:
            _ROUND_IN_FLIGHT.set(-1)
            obs_flight.set_state("worker.round_in_flight", None)
        my_id = self.worker_id
        assert my_id is not None
        if (
            self.config.stats_reporting_round_frequency > 0
            and self.completed_rounds % self.config.stats_reporting_round_frequency == 0
        ):
            log.info(
                "worker %s: %d rounds complete (dropped=%d)",
                my_id,
                self.completed_rounds,
                self.dropped_messages,
            )
        return [Envelope(master_addr(self.line_id), CompleteAllreduce(my_id, r))]
