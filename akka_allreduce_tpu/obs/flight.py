"""Flight recorder: always-on ring of recent events, dumped on demand.

The ring costs one bounded ``deque.append`` per event — cheap enough to
leave on in production — and turns "the soak hung at round 412" into a
post-mortem artifact. A dump is triggered by:

- an **unhandled crash** (``sys.excepthook`` wrapper, via ``install()``),
- **SIGUSR1** (``install()``; with ``signal_exit=True`` the handler dumps
  and then dies by the signal — kill-with-post-mortem),
- the **round watchdog** (``obs.watchdog``) when a round blows its
  deadline,
- an explicit ``dump()`` call.

Dump format (JSONL, one object per line — OBSERVABILITY.md):

    {"kind": "flight_header", "reason": ..., "pid": ..., "argv": ..., ...}
    {"kind": "state", ...}          # last-known values (set_state)
    {"kind": "metrics", ...}        # obs.metrics.REGISTRY.snapshot()
    {"kind": "event"|"span", ...}   # the ring, oldest first

``state`` carries the pointers a post-mortem needs first: the in-flight
round (``worker.round_in_flight``) and the last transport stage
(``transport.last_stage``) are maintained by the worker and transport.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from collections import deque
from typing import Any

from akka_allreduce_tpu.obs import metrics

__all__ = [
    "note",
    "record_span",
    "set_state",
    "get_state",
    "dump",
    "install",
    "uninstall",
    "events",
    "clear",
]

_RING_MAX = 4096
_ring: deque = deque(maxlen=_RING_MAX)

#: last-known values — one dict store per update, safe from signal handlers
_state: dict[str, Any] = {}

_dump_dir: str | None = None
_installed = False
_signal_exit = False
_prev_excepthook = None
_prev_sigusr1 = None


def note(kind: str, **attrs: Any) -> None:
    """Record a point event into the ring."""
    _ring.append({"kind": "event", "t": time.time(), "event": kind, **attrs})


def record_span(rec: dict) -> None:
    """Called by obs.trace when a span ends."""
    _ring.append({"kind": "span", **rec})


def set_state(key: str, value: Any) -> None:
    # Deliberately lock-free: one dict store per update, last-writer-wins.
    # dump() must stay callable from signal handlers and excepthooks, and a
    # lock here could deadlock a handler that fires mid-update.
    _state[key] = value  # arlint: disable=THRD001 -- single-opcode store


def get_state(key: str, default: Any = None) -> Any:
    return _state.get(key, default)


def events() -> list[dict]:
    return list(_ring)


def clear() -> None:
    _ring.clear()
    _state.clear()


def _default_dir() -> str:
    return _dump_dir or os.environ.get("AKKA_OBS_DIR") or os.getcwd()


def dump(path: str | None = None, *, reason: str = "manual") -> str:
    """Write the flight record as JSONL; returns the file path.

    Safe to call from a signal handler or excepthook: everything read here
    is either immutable or mutated only by single opcode stores.
    """
    if path is None:
        path = os.path.join(
            _default_dir(),
            f"flightrec-{os.getpid()}-{reason}-{int(time.time() * 1e3)}.jsonl",
        )
    header = {
        "kind": "flight_header",
        "reason": reason,
        "pid": os.getpid(),
        "argv": sys.argv,
        "t": time.time(),
        "n_events": len(_ring),
    }
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        f.write(json.dumps({"kind": "state", **_state}) + "\n")
        f.write(
            json.dumps(
                {"kind": "metrics", **metrics.REGISTRY.snapshot()},
                default=str,
            )
            + "\n"
        )
        for rec in list(_ring):
            f.write(json.dumps(rec, default=str) + "\n")
    return path


def _on_crash(exc_type, exc, tb) -> None:
    try:
        note("unhandled_exception", type=exc_type.__name__, message=str(exc))
        path = dump(reason="crash")
        print(f"flight recorder: crash dump written to {path}", file=sys.stderr)
    except Exception:  # the dump must never mask the original crash
        pass
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _on_sigusr1(signum, frame) -> None:
    path = dump(reason="sigusr1")
    print(f"flight recorder: SIGUSR1 dump written to {path}", file=sys.stderr)
    if _signal_exit:
        # die BY the signal (proper waitstatus for the parent): restore the
        # default disposition and re-raise at ourselves
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGUSR1)


def install(dump_dir: str | None = None, *, signal_exit: bool = False) -> None:
    """Arm the crash and SIGUSR1 dump triggers for this process.

    ``signal_exit=True`` makes SIGUSR1 fatal after the dump (the
    kill-with-post-mortem mode the cluster CLI roles use); the default
    dumps and keeps running. Idempotent; ``uninstall()`` undoes it.
    """
    global _dump_dir, _installed, _signal_exit, _prev_excepthook, _prev_sigusr1
    if dump_dir is not None:
        _dump_dir = dump_dir
        os.makedirs(dump_dir, exist_ok=True)
    _signal_exit = signal_exit
    if _installed:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _on_crash
    try:
        _prev_sigusr1 = signal.signal(signal.SIGUSR1, _on_sigusr1)
    except ValueError:
        # not the main thread: crash hook still works, the signal trigger
        # is simply unavailable here
        _prev_sigusr1 = None
    _installed = True


def uninstall() -> None:
    global _installed, _prev_excepthook, _prev_sigusr1, _dump_dir, _signal_exit
    if not _installed:
        return
    if sys.excepthook is _on_crash and _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
    if _prev_sigusr1 is not None:
        try:
            signal.signal(signal.SIGUSR1, _prev_sigusr1)
        except ValueError:
            pass
    _prev_excepthook = None
    _prev_sigusr1 = None
    _dump_dir = None
    _signal_exit = False
    _installed = False
