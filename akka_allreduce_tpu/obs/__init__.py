"""Observability layer: tracing, metrics, and the flight recorder.

Zero-dependency (stdlib only) and safe to import from every layer — the
control plane, the trainers, and the CLI all feed the same three pillars:

- ``obs.metrics`` — a process-wide registry of counters / gauges /
  fixed-bucket histograms (lock-cheap, allocation-free on the hot path,
  ``snapshot()``-to-dict for JSONL sinks).
- ``obs.trace`` — spans with round-scoped trace IDs that propagate across
  the TCP control plane (an optional trailer on every wire frame), plus a
  Chrome/Perfetto ``trace_event`` JSON exporter so a multi-process run
  renders as one timeline.
- ``obs.flight`` — an always-on fixed-size ring of recent spans/events,
  dumped to JSONL on unhandled crash, on ``SIGUSR1``, and when the round
  watchdog (``obs.watchdog``) sees a round exceed its deadline.

See OBSERVABILITY.md for the span model, metric naming convention, and the
flight-recorder dump format.
"""

from __future__ import annotations

from akka_allreduce_tpu.obs import flight, metrics, trace, watchdog

__all__ = ["flight", "metrics", "trace", "watchdog"]
