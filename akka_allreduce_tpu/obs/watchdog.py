"""Stall watchdog: a round that exceeds its deadline dumps the flight
recorder instead of becoming a shrug.

``RoundWatchdog`` is fed round lifecycle events (``round_started`` /
``round_completed``, keyed by ``(line_id, round)``) by whoever schedules
rounds — the master process wires it to its line masters — and checks ages
either from the caller's own poll loop (``check()``) or from its own
periodic task (``start()``, which goes through ``observed_task`` so a dead
watchdog is an ERROR log, not silence — arlint ASYNC003).

On the first deadline crossing of a given round it:

- increments ``watchdog.round_stalls`` in the metrics registry,
- records a ``round_stall`` flight event, and
- dumps the flight recorder (``flightrec-…-stall-….jsonl``) naming the
  stalled round — one dump per stalled round, not one per poll.
"""

from __future__ import annotations

import time
from typing import Callable

from akka_allreduce_tpu.obs import flight, metrics

__all__ = ["RoundWatchdog"]


class RoundWatchdog:
    """Deadline monitor over in-flight rounds."""

    def __init__(
        self,
        deadline_s: float,
        *,
        clock: Callable[[], float] = time.monotonic,
        poll_interval_s: float | None = None,
        on_stall: Callable[[int, int, float], None] | None = None,
        dump: bool = True,
    ) -> None:
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.deadline_s = deadline_s
        self.clock = clock
        self.poll_interval_s = poll_interval_s or max(deadline_s / 4.0, 0.05)
        self.on_stall = on_stall
        self.dump = dump
        self._inflight: dict[tuple[int, int], float] = {}
        self._reported: set[tuple[int, int]] = set()
        self._task = None
        self.stalls = metrics.counter("watchdog.round_stalls")
        self.last_dump_path: str | None = None

    # -- lifecycle events (called by the round scheduler) ----------------------

    def round_started(self, line_id: int, round_num: int) -> None:
        self._inflight[(line_id, round_num)] = self.clock()

    def round_completed(self, line_id: int, round_num: int) -> None:
        """A completed round also retires older in-flight rounds of its
        line (the schedulers abandon them — same discipline)."""
        for key in [
            k for k in self._inflight if k[0] == line_id and k[1] <= round_num
        ]:
            self._inflight.pop(key, None)
            self._reported.discard(key)

    def reset(self) -> None:
        """Retire EVERY in-flight round — called on grid reorganization:
        the replaced line masters' rounds are abandoned by design (their
        line ids may not even exist in the new configuration), so letting
        their deadlines ride would turn every re-mesh into spurious stall
        dumps. Rounds of the new configuration re-register via
        ``round_started``."""
        self._inflight.clear()
        self._reported.clear()

    # -- checking --------------------------------------------------------------

    def check(self, now: float | None = None) -> list[tuple[int, int, float]]:
        """Report rounds newly past deadline as ``(line, round, age_s)``."""
        now = self.clock() if now is None else now
        stalled = []
        for key, started in self._inflight.items():
            age = now - started
            if age > self.deadline_s and key not in self._reported:
                self._reported.add(key)
                stalled.append((key[0], key[1], age))
        for line_id, round_num, age in stalled:
            self.stalls.inc()
            flight.set_state("watchdog.stalled_round", round_num)
            flight.set_state("watchdog.stalled_line", line_id)
            flight.note(
                "round_stall",
                line=line_id,
                round=round_num,
                age_s=round(age, 3),
                deadline_s=self.deadline_s,
            )
            if self.dump:
                self.last_dump_path = flight.dump(
                    reason=f"stall-round{round_num}"
                )
            if self.on_stall is not None:
                self.on_stall(line_id, round_num, age)
        return stalled

    # -- optional self-driven polling ------------------------------------------

    async def _run(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.poll_interval_s)
            self.check()

    def start(self) -> None:
        """Spawn the periodic check task (requires a running event loop)."""
        from akka_allreduce_tpu.control.remote import observed_task

        if self._task is None or self._task.done():
            self._task = observed_task(self._run(), name="round-watchdog")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
