"""Spans with round-scoped trace IDs + a Chrome/Perfetto exporter.

Model (OBSERVABILITY.md "Span model"):

- A **trace** is one allreduce round (or any other unit of work): the line
  master mints a fresh 63-bit trace id when it starts a round and stamps it
  onto the ``StartAllreduce`` envelopes; every hop after that — worker
  scatter, peer reduce, completion report — inherits the id through the
  wire trailer (``control/wire.py``), so one round stitches across every
  process it touched.
- A **span** is one timed operation inside a trace: name, wall-clock start,
  duration, attributes, and parent span id. The *current* trace context is
  a ``contextvars.ContextVar`` set by the transport around each handler
  invocation; ``span()`` opens a child of it.
- Finished spans land in a bounded in-process buffer (and the flight
  recorder's ring); ``write_chrome_trace`` renders them as Chrome
  ``trace_event`` JSON that Perfetto / ``chrome://tracing`` open directly,
  and ``merge_chrome_traces`` folds multiple processes' files into one
  timeline (events carry real pids, timestamps are epoch-based).

Sampling: ``AKKA_OBS_TRACE=0`` disables span *recording* entirely (context
still propagates, so re-enabling downstream works); the default records
every span — span volume here is per control message, not per byte, so the
steady-state cost is two clock reads and one small dict per span.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Iterable, NamedTuple

from akka_allreduce_tpu.obs import flight as _flight

__all__ = [
    "TraceContext",
    "Span",
    "current",
    "use",
    "new_context",
    "span",
    "start_span",
    "enabled",
    "set_enabled",
    "drain",
    "snapshot",
    "chrome_events",
    "write_chrome_trace",
    "merge_chrome_traces",
]


class TraceContext(NamedTuple):
    """What propagates across the wire: 8+8 bytes of ids + a sampled bit."""

    trace_id: int
    span_id: int
    sampled: bool = True


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "akka_obs_trace", default=None
)

# finished-span buffer: bounded so an unexported long run cannot grow without
# limit (drain() or write_chrome_trace() empties it)
_BUFFER_MAX = 65536
_finished: deque = deque(maxlen=_BUFFER_MAX)

_enabled = os.environ.get("AKKA_OBS_TRACE", "1") not in ("0", "false", "off")

# random.Random instance: never perturbs the global RNG the payload
# generators seed deterministically
_ids = random.Random()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def _new_id() -> int:
    return _ids.getrandbits(63) or 1


def new_context(*, sampled: bool | None = None) -> TraceContext:
    """Mint a fresh trace root (e.g. one per allreduce round)."""
    return TraceContext(
        _new_id(), _new_id(), _enabled if sampled is None else sampled
    )


def current() -> TraceContext | None:
    return _current.get()


@contextlib.contextmanager
def use(ctx: TraceContext | None):
    """Make ``ctx`` the current trace context for the with-body (the
    transport wraps every handler invocation in this)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


class Span:
    """One timed operation. Create via ``span()`` (context manager) or
    ``start_span()`` (manual ``end()`` — for spans that outlive a single
    callback, e.g. the line master's per-round span)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "sampled", "attrs",
        "_t_wall", "_t0", "ended",
    )

    def __init__(
        self,
        name: str,
        ctx: TraceContext | None,
        attrs: dict[str, Any] | None,
        *,
        root: bool = False,
    ) -> None:
        self.name = name
        if root:
            ctx = None
        if ctx is not None:
            self.trace_id = ctx.trace_id
            self.parent_id = ctx.span_id
            self.sampled = ctx.sampled and _enabled
        else:
            self.trace_id = _new_id()
            self.parent_id = 0
            self.sampled = _enabled
        self.span_id = _new_id()
        self.attrs = attrs
        self._t_wall = time.time()
        self._t0 = time.perf_counter()
        self.ended = False

    @property
    def context(self) -> TraceContext:
        """The context a child (or an outgoing envelope) should inherit."""
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    def set(self, **attrs: Any) -> None:
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def end(self) -> None:
        if self.ended:
            return
        self.ended = True
        if not self.sampled:
            return
        rec = {
            "name": self.name,
            "ts": self._t_wall,
            "dur": time.perf_counter() - self._t0,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        _finished.append(rec)
        # the flight recorder keeps its own ring of recent spans
        _flight.record_span(rec)


def start_span(
    name: str,
    *,
    ctx: TraceContext | None = None,
    root: bool = False,
    **attrs: Any,
) -> Span:
    """Open a span (parent = ``ctx`` or the current context); caller ends
    it. ``root=True`` forces a FRESH trace id regardless of any ambient
    context — how a new allreduce round starts its own trace even when the
    scheduler runs inside the previous round's completion handler."""
    return Span(
        name,
        ctx if ctx is not None else _current.get(),
        attrs or None,
        root=root,
    )


@contextlib.contextmanager
def span(
    name: str,
    *,
    ctx: TraceContext | None = None,
    root: bool = False,
    **attrs: Any,
):
    """Span around the with-body; the body runs with the span as the
    current context, so nested spans (and envelopes sent from inside) are
    its children."""
    s = start_span(name, ctx=ctx, root=root, **attrs)
    token = _current.set(s.context)
    try:
        yield s
    finally:
        _current.reset(token)
        s.end()


def snapshot() -> list[dict]:
    """Finished spans recorded so far (oldest first), without clearing."""
    return list(_finished)


def drain() -> list[dict]:
    out = list(_finished)
    _finished.clear()
    return out


# -- Chrome trace_event export -------------------------------------------------


def _layer(name: str) -> str:
    """Span-name prefix = its layer (grid_master / line_master / worker /
    transport / ...), used as the Chrome event category."""
    return name.split(".", 1)[0]


def chrome_events(
    records: Iterable[dict], *, pid: int | None = None
) -> list[dict]:
    """Span records -> Chrome ``trace_event`` complete ('X') events.

    Timestamps are wall-clock epoch microseconds, so events from different
    processes land on one timeline when merged. Trace/span ids ride in
    ``args`` (hex strings — Perfetto keeps them queryable).
    """
    pid = os.getpid() if pid is None else pid
    tid = threading.get_ident() & 0x7FFFFFFF
    out = []
    for r in records:
        args = {
            "trace_id": format(r["trace_id"], "016x"),
            "span_id": format(r["span_id"], "016x"),
            "parent_id": format(r.get("parent_id", 0), "016x"),
        }
        args.update(r.get("attrs") or {})
        out.append(
            {
                "name": r["name"],
                "cat": _layer(r["name"]),
                "ph": "X",
                "ts": r["ts"] * 1e6,
                "dur": max(r["dur"], 1e-6) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    return out


def write_chrome_trace(
    path: str, records: Iterable[dict] | None = None, *, drain_buffer: bool = True
) -> str:
    """Write (and by default drain) the span buffer as a Chrome/Perfetto
    trace JSON file; returns ``path``."""
    if records is None:
        records = drain() if drain_buffer else snapshot()
    doc = {
        "traceEvents": chrome_events(records),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "akka_allreduce_tpu.obs", "pid": os.getpid()},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def merge_chrome_traces(paths: Iterable[str], out_path: str) -> str:
    """Fold several processes' trace files into one timeline (events carry
    their producing pid, so Perfetto shows one track group per process)."""
    events: list[dict] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
    events.sort(key=lambda e: e.get("ts", 0))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out_path
